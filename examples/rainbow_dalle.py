#!/usr/bin/env python
"""End-to-end walkthrough: synthetic shapes → dVAE → DALL·E → generation.

The script form of the reference's ``examples/rainbow_dalle.ipynb`` (its
de-facto integration test, SURVEY.md §4): generate a cairo-style shapes
dataset, train the discrete VAE, train DALL·E on a split, generate images for
held-out captions, and report **token-exact accuracy** per split (notebook
cells 0-47: train ≈ 1.0, held-out ≈ 0.3, per-position > 0.8).

Runs on one TPU chip or the CPU mesh. Scale knobs are CLI flags; the defaults
are sized to finish in minutes, not hours.

Example (small, CPU-friendly):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/rainbow_dalle.py --image_size 32 --num_tokens 64 \
      --vae_steps 500 --dalle_steps 800 --train_frac 0.3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--image_size", type=int, default=32)
    ap.add_argument("--num_tokens", type=int, default=64)
    ap.add_argument("--vae_steps", type=int, default=500)
    ap.add_argument("--dalle_steps", type=int, default=800)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--train_frac", type=float, default=0.3,
                    help="fraction of the dataset used for DALLE training "
                         "(notebook uses 30%%)")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--outdir", type=str, default="./rainbow_out")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from dalle_tpu.config import (DVAEConfig, DalleConfig, MeshConfig,
                                  OptimConfig, TrainConfig)
    from dalle_tpu.data.loaders import Token
    from dalle_tpu.data.synthetic import ShapesDataset, batch_iterator
    from dalle_tpu.models.dalle import DALLE
    from dalle_tpu.models.wrapper import DalleWithVae, DiscreteVAEAdapter
    from dalle_tpu.train.trainer_dalle import DalleTrainer
    from dalle_tpu.train.trainer_vae import VAETrainer

    rng = np.random.RandomState(args.seed)
    ds = ShapesDataset(image_size=args.image_size)
    print(f"dataset: {len(ds)} shape/color/scale combinations")

    # ---- stage 1: train the dVAE on everything (notebook cells 23-30) ----
    vcfg = DVAEConfig(image_size=args.image_size, num_tokens=args.num_tokens,
                      codebook_dim=64, num_layers=2, hidden_dim=32,
                      num_resnet_blocks=1)
    tc = TrainConfig(batch_size=args.batch_size,
                     checkpoint_dir=os.path.join(args.outdir, "vae"),
                     log_every=100, metrics_every=20, preflight_checkpoint=False,
                     optim=OptimConfig(learning_rate=2e-3, grad_clip_norm=0.0))
    vt = VAETrainer(vcfg, tc)
    batches = batch_iterator(ds, args.batch_size, seed=args.seed)
    vt.fit(batches, steps=args.vae_steps)
    vae = DiscreteVAEAdapter(vt.model, vt.state.params)

    # ---- tokenize all captions + images ----------------------------------
    imgs = np.stack([ds[i].image for i in range(len(ds))]).astype(np.float32) / 255.0
    caps = [ds[i].caption for i in range(len(ds))]
    codes = np.concatenate([np.asarray(vae.get_codebook_indices(imgs[s:s + 64]))
                            for s in range(0, len(imgs), 64)])
    tok = Token([c.split() for c in caps])
    seq_len = tok.sequence_len
    text = tok.parse(seq_len=seq_len)

    order = rng.permutation(len(ds))
    n_train = max(int(len(ds) * args.train_frac), args.batch_size)
    tr_idx, te_idx = order[:n_train], order[n_train:]
    print(f"DALLE split: {len(tr_idx)} train / {len(te_idx)} held out; "
          f"vocab {tok.num_pairs} words, {seq_len} text tokens, "
          f"{codes.shape[1]} image tokens")

    # ---- stage 2: train DALLE on the split (cells 31-40) -----------------
    dcfg = DalleConfig(num_text_tokens=tok.num_pairs, text_seq_len=seq_len,
                       dim=args.dim, depth=args.depth, heads=4,
                       dim_head=args.dim // 4, image_size=args.image_size,
                       image_vocab_size=args.num_tokens,
                       image_fmap_size=vae.image_fmap_size)
    tc2 = TrainConfig(batch_size=args.batch_size,
                      checkpoint_dir=os.path.join(args.outdir, "dalle"),
                      log_every=100, metrics_every=20,
                      preflight_checkpoint=False,
                      optim=OptimConfig(learning_rate=1e-3, grad_clip_norm=0.0))
    dt = DalleTrainer(dcfg, tc2)

    def dalle_batches():
        while True:
            sel = rng.choice(tr_idx, args.batch_size)
            yield text[sel], codes[sel]

    dt.fit(dalle_batches(), steps=args.dalle_steps)

    # ---- stage 3: token-exact accuracy per split (cells 41-44) -----------
    metrics = {}

    def accuracy(split_idx, name, n=32):
        sel = split_idx[:n]
        ids = dt.model.apply(dt.state.params, jnp.asarray(text[sel]),
                             jax.random.PRNGKey(1), filter_thres=0.9,
                             temperature=0.5,
                             method=DALLE.generate_images_tokens)
        exact = (np.asarray(ids) == codes[sel]).mean()
        per_pos = (np.asarray(ids) == codes[sel]).mean(axis=0)
        print(f"{name}: token-exact {exact:.3f}; "
              f"positions >0.8: {(per_pos > 0.8).mean():.2f}")
        metrics[f"{name}_exact"] = float(exact)
        metrics[f"{name}_pos_frac"] = float((per_pos > 0.8).mean())
        return np.asarray(ids)

    accuracy(tr_idx, "train")
    if len(te_idx):
        ids = accuracy(te_idx, "held-out")
        # decode a few held-out generations to PNGs
        dv = DalleWithVae(dt.model, dt.state.params, vae)
        out = np.asarray(vae.decode(jnp.asarray(ids[:8])))
        os.makedirs(args.outdir, exist_ok=True)
        from PIL import Image
        for i, im in enumerate((out * 255).clip(0, 255).astype("uint8")):
            Image.fromarray(im).save(os.path.join(args.outdir, f"gen_{i}.png"))
        print(f"wrote samples to {args.outdir}")
    return metrics


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
