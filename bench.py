"""Benchmark: DALL·E-small training throughput on the attached chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no formal numbers (BASELINE.md): its only hooks are a
samples/sec meter and a flops profile. The driver-set target is ≥45% MFU
(BASELINE.json north_star), so ``vs_baseline`` reports measured MFU / 0.45 —
>1.0 beats the target.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from dalle_tpu.config import DalleConfig, MeshConfig, OptimConfig, TrainConfig
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import device_peak_tflops
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    on_accel = jax.devices()[0].platform != "cpu"
    # DALL·E-small (BASELINE.md config 2): 12L/8H/512d, full causal attention,
    # 256 text + 256 image tokens. bf16 compute with bf16 attention scores —
    # the HBM-dominant tensor (see ops/attention.py softmax_f32).
    cfg = DalleConfig(
        num_text_tokens=10000, text_seq_len=256, dim=512, depth=12, heads=8,
        dim_head=64, image_size=128, image_vocab_size=8192, image_fmap_size=16,
        attn_softmax_f32=False)
    batch = 64 if on_accel else 8
    steps = 10 if on_accel else 3

    n_dev = jax.device_count()
    mesh_cfg = MeshConfig(dp=n_dev)
    mesh = build_mesh(mesh_cfg)
    train_cfg = TrainConfig(batch_size=batch, checkpoint_dir="/tmp/bench_ckpt",
                            preflight_checkpoint=False, mesh=mesh_cfg,
                            metrics_every=1000,   # pipeline steps: no per-step sync
                            optim=OptimConfig(grad_clip_norm=0.5))
    trainer = DalleTrainer(cfg, train_cfg, mesh=mesh)

    rng = np.random.RandomState(0)
    text = rng.randint(1, cfg.num_text_tokens, (batch, cfg.text_seq_len))
    image_ids = rng.randint(0, cfg.image_vocab_size, (batch, cfg.image_seq_len))

    def sync():
        # hard sync: pull one scalar (block_until_ready can return early
        # through remote-device tunnels)
        jax.device_get(jax.tree.leaves(trainer.state.params)[0]).ravel()[0]

    # 3 warmups: the first covers compile, the rest absorb any post-donation
    # relayout recompile
    for _ in range(3):
        trainer.train_step(text, image_ids)
    sync()
    t0 = time.perf_counter()
    for _ in range(steps):   # steps queue back-to-back (metrics_every→no sync)
        trainer.train_step(text, image_ids)
    sync()
    dt = (time.perf_counter() - t0) / steps

    tokens_per_step = batch * cfg.total_seq_len
    tokens_per_sec_per_chip = tokens_per_step / dt / n_dev
    flops_per_step = 6.0 * trainer.num_params * tokens_per_step
    mfu = (flops_per_step / dt) / (device_peak_tflops() * 1e12 * n_dev)

    print(json.dumps({
        "metric": "dalle_small_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
