"""Benchmark: DALL·E-1.4B training throughput on the attached chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no formal numbers (BASELINE.md): its only hooks are a
samples/sec meter and a flops profile. The driver-set target is ≥45% MFU at
the 1.3B scale (BASELINE.json north_star, config 4), so ``vs_baseline``
reports measured MFU / 0.45 — >1.0 beats the target.

Config recorded: DALL·E-1.4B (24L/14H/1792d — BASELINE.md config 4's model
scale) with the production CLIP text vocab (49,408), 256 text + 256 image
tokens, full causal attention, bf16 compute with f32 masters, NO
rematerialization (at b8 the activations fit once chunked CE keeps the
58k-vocab logits out of HBM; b16 regresses to 0.55 from spill pressure),
Adafactor + global-norm clipping — the full production train step as one
scanned multi-step program (train_steps, k=5 per dispatch) with state
donation. Adafactor's factored second moments are what fit 1.4B params on
one chip; multi-chip gets the same memory relief from fsdp-sharded Adam
instead (dryrun_multichip covers that path). MFU uses the PaLM convention:
(6·N + 12·L·h·d_head·n) FLOPs/token.

Cross-config reference (scripts/bench_sweep.py, docs/PERF_SMALL.md):
DALL·E-small (12L/512d, b64) 169.8k tokens/s/chip at ~0.39 MFU
(attention-score HBM-bound at dim 512 — see the ceiling analysis);
DALL·E-medium (24L/1024d, Adam, b12) 33.3k at 0.554; this 1.4B config
13.7k at 0.62 — bigger GEMMs keep the MXU busier.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main():
    from dalle_tpu.config import DalleConfig, MeshConfig, OptimConfig, TrainConfig
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import device_peak_tflops
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    on_accel = jax.devices()[0].platform != "cpu"
    # DALL·E-1.4B (BASELINE.md config 4 scale): 24L/14H/1792d, CLIP vocab,
    # full causal attention, 256 text + 256 image tokens. bf16 attention
    # scores (the HBM-dominant tensor), chunked CE, Adafactor.
    cfg = DalleConfig(
        num_text_tokens=49408, text_seq_len=256, dim=1792, depth=24, heads=14,
        dim_head=128, image_size=128, image_vocab_size=8192,
        image_fmap_size=16, attn_softmax_f32=False, loss_chunk=128,
        # at b8 the full activation set fits without rematerialization
        # (chunked CE keeps the logits out): +1% over per-block remat;
        # b16 regresses (0.55 — spill pressure), so b8 stays the recipe
        use_remat=False)
    batch = 8 if on_accel else 2
    steps = 10 if on_accel else 2

    n_dev = jax.device_count()
    mesh_cfg = MeshConfig(dp=n_dev)
    mesh = build_mesh(mesh_cfg)
    train_cfg = TrainConfig(batch_size=batch, checkpoint_dir="/tmp/bench_ckpt",
                            preflight_checkpoint=False, mesh=mesh_cfg,
                            metrics_every=1000,   # pipeline steps: no per-step sync
                            optim=OptimConfig(optimizer="adafactor",
                                              grad_clip_norm=0.5))
    trainer = DalleTrainer(cfg, train_cfg, mesh=mesh)

    rng = np.random.RandomState(0)
    text = rng.randint(1, cfg.num_text_tokens, (batch, cfg.text_seq_len))
    image_ids = rng.randint(0, cfg.image_vocab_size, (batch, cfg.image_seq_len))

    def sync():
        # hard sync: pull one scalar (block_until_ready can return early
        # through remote-device tunnels)
        jax.device_get(jax.tree.leaves(trainer.state.params)[0]).ravel()[0]

    # k steps per dispatch via the scanned multi-step (train_steps): interior
    # state handoffs never touch the host, so per-dispatch tunnel overhead
    # (~20ms here) is amortized — measuring the chip, not the host
    scan_k = 5 if on_accel else 1   # keep the CPU smoke run cheap
    texts = np.broadcast_to(text, (scan_k, *text.shape)).copy()
    idss = np.broadcast_to(image_ids, (scan_k, *image_ids.shape)).copy()
    # 2 warmups: the first covers compile, the second absorbs any
    # post-donation relayout recompile
    for _ in range(2):
        trainer.train_steps(texts, idss)
    sync()
    calls = max(1, steps // scan_k)
    t0 = time.perf_counter()
    for _ in range(calls):
        trainer.train_steps(texts, idss)
    sync()
    dt = (time.perf_counter() - t0) / (calls * scan_k)

    n = cfg.total_seq_len
    tokens_per_step = batch * n
    tokens_per_sec_per_chip = tokens_per_step / dt / n_dev
    flops_per_token = (6.0 * trainer.num_params
                       + 12.0 * cfg.depth * cfg.heads * cfg.dim_head * n)
    mfu = (flops_per_token * tokens_per_step / dt) / (
        device_peak_tflops() * 1e12 * n_dev)

    print(json.dumps({
        "metric": "dalle_1p4b_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
