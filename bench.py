"""Benchmark: DALL·E-medium training throughput on the attached chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no formal numbers (BASELINE.md): its only hooks are a
samples/sec meter and a flops profile. The driver-set target is ≥45% MFU
(BASELINE.json north_star, config 4), so ``vs_baseline`` reports measured
MFU / 0.45 — >1.0 beats the target.

Config recorded: DALL·E-medium (24L/16H/1024d — BASELINE.md config 3) with the
production CLIP text vocab (49,408), 256 text + 256 image tokens, full causal
attention, bf16 compute with f32 masters, per-block rematerialization, Adam +
global-norm clipping — the full production train step, jitted once with state
donation. MFU uses the PaLM convention: (6·N + 12·L·h·d_head·n) FLOPs/token,
i.e. parameter FLOPs plus the n² attention term (attention is real work the
chip does; a params-only denominator undercounts it).

Round-1 note: the previous flagship (DALL·E-small, 12L/8H/512d, batch 64)
reaches 170k tokens/s/chip but only ~0.39 MFU on a v5e — at dim 512 the
attention score traffic is HBM-bound (NEXT.md r1 profile: attention ≈53% of
step). The medium config's 1024-wide GEMMs keep the MXU busy instead;
scripts/bench_sweep.py holds both configs for comparison.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main():
    from dalle_tpu.config import DalleConfig, MeshConfig, OptimConfig, TrainConfig
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import device_peak_tflops
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    on_accel = jax.devices()[0].platform != "cpu"
    # DALL·E-medium (BASELINE.md config 3): 24L/16H/1024d, CLIP vocab, full
    # causal attention, 256 text + 256 image tokens. bf16 attention scores —
    # the HBM-dominant tensor (ops/attention.py softmax_f32).
    cfg = DalleConfig(
        num_text_tokens=49408, text_seq_len=256, dim=1024, depth=24, heads=16,
        dim_head=64, image_size=128, image_vocab_size=8192, image_fmap_size=16,
        attn_softmax_f32=False)
    batch = 12 if on_accel else 4
    steps = 10 if on_accel else 2

    n_dev = jax.device_count()
    mesh_cfg = MeshConfig(dp=n_dev)
    mesh = build_mesh(mesh_cfg)
    train_cfg = TrainConfig(batch_size=batch, checkpoint_dir="/tmp/bench_ckpt",
                            preflight_checkpoint=False, mesh=mesh_cfg,
                            metrics_every=1000,   # pipeline steps: no per-step sync
                            optim=OptimConfig(grad_clip_norm=0.5))
    trainer = DalleTrainer(cfg, train_cfg, mesh=mesh)

    rng = np.random.RandomState(0)
    text = rng.randint(1, cfg.num_text_tokens, (batch, cfg.text_seq_len))
    image_ids = rng.randint(0, cfg.image_vocab_size, (batch, cfg.image_seq_len))

    def sync():
        # hard sync: pull one scalar (block_until_ready can return early
        # through remote-device tunnels)
        jax.device_get(jax.tree.leaves(trainer.state.params)[0]).ravel()[0]

    # 3 warmups: the first covers compile, the rest absorb any post-donation
    # relayout recompile
    for _ in range(3):
        trainer.train_step(text, image_ids)
    sync()
    t0 = time.perf_counter()
    for _ in range(steps):   # steps queue back-to-back (metrics_every→no sync)
        trainer.train_step(text, image_ids)
    sync()
    dt = (time.perf_counter() - t0) / steps

    n = cfg.total_seq_len
    tokens_per_step = batch * n
    tokens_per_sec_per_chip = tokens_per_step / dt / n_dev
    flops_per_token = (6.0 * trainer.num_params
                       + 12.0 * cfg.depth * cfg.heads * cfg.dim_head * n)
    mfu = (flops_per_token * tokens_per_step / dt) / (
        device_peak_tflops() * 1e12 * n_dev)

    print(json.dumps({
        "metric": "dalle_medium_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
