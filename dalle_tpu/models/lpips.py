"""LPIPS perceptual distance (conv features + learned 1×1 heads).

Reference: ``LPIPS`` (dalle_pytorch/taming/modules/losses/lpips.py:11-123):
a frozen torchvision VGG16 split into 5 relu slices, per-channel input
scaling, unit-normalized feature differences, squeezed through learned 1×1
"lin" layers and spatially averaged.

TPU notes: plain XLA convs in NHWC; the whole distance is one fused forward —
no kernel work needed.

Pretrained weights — two paths for a zero-egress environment:
  * ``load_torch_weights`` imports a local torchvision ``vgg16`` state_dict +
    taming ``vgg.pth`` lin heads when the user has them on disk (the
    reference downloads them, taming/util.py:5-44; golden-tested in
    tests/test_golden_import.py).
  * ``load_tiny_perceptual`` loads the repo's OWN shipped weights
    (models/data/tiny_perceptual.npz): a small trunk with the same
    slice/normalize/lin structure, trained in-repo by
    scripts/train_perceptual.py — trunk on shape/color/scale classification
    over the synthetic shapes corpus (data/synthetic.py), lin heads on
    2AFC-style distortion ranking (the same supervision style LPIPS lins get,
    synthesized from parametric distortions instead of human judgments).
    This is the default perceptual net for VQGAN training, replacing the
    round-2 ones-init placeholder with a real perceptual metric.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# torchvision VGG16 conv layout: channels per conv, with maxpool boundaries
# splitting the 5 LPIPS slices after relu1_2/2_2/3_3/4_3/5_3
_VGG_SLICES = (
    (64, 64),
    (128, 128),
    (256, 256, 256),
    (512, 512, 512),
    (512, 512, 512),
)
_LPIPS_CHANNELS = (64, 128, 256, 512, 512)

# the in-repo trained trunk (scripts/train_perceptual.py): same structure,
# ~0.6M params so the weights ship inside the package
TINY_SLICES = ((32, 32), (64, 64), (128, 128), (256,))
_TINY_WEIGHTS = os.path.join(os.path.dirname(__file__), "data",
                             "tiny_perceptual.npz")

# ImageNet scaling constants (taming lpips.py ScalingLayer:57-66)
_SHIFT = np.array([-0.030, -0.088, -0.188], np.float32)
_SCALE = np.array([0.458, 0.448, 0.450], np.float32)


class VGG16Features(nn.Module):
    """Conv trunk returning the relu slice outputs (lpips.py:69-101). The
    default slice spec is torchvision VGG16; ``TINY_SLICES`` gives the
    in-repo trunk (same structure, package-shippable size)."""
    slices: Optional[Tuple[Tuple[int, ...], ...]] = None

    @nn.compact
    def __call__(self, x) -> Sequence[jnp.ndarray]:
        outs = []
        for s, chans in enumerate(self.slices or _VGG_SLICES):
            if s > 0:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            for i, ch in enumerate(chans):
                x = nn.Conv(ch, (3, 3), padding=1, name=f"slice{s}_conv{i}")(x)
                x = nn.relu(x)
            outs.append(x)
        return outs


def _unit_normalize(x, eps: float = 1e-10):
    # normalize_tensor (lpips.py:119-121): unit L2 norm over channels
    norm = jnp.sqrt(jnp.sum(x ** 2, axis=-1, keepdims=True))
    return x / (norm + eps)


class LPIPS(nn.Module):
    """Perceptual distance d(x, y); inputs NHWC in [−1, 1]."""
    slices: Optional[Tuple[Tuple[int, ...], ...]] = None

    @nn.compact
    def __call__(self, x, y):
        vgg = VGG16Features(slices=self.slices, name="vgg")
        shift = jnp.asarray(_SHIFT, x.dtype)
        scale = jnp.asarray(_SCALE, x.dtype)
        fx = vgg((x - shift) / scale)
        fy = vgg((y - shift) / scale)
        total = 0.0
        for i, (a, b) in enumerate(zip(fx, fy)):
            diff = (_unit_normalize(a) - _unit_normalize(b)) ** 2
            # learned 1×1 head (NetLinLayer, lpips.py:104-116), then spatial mean
            w = self.param(f"lin{i}", nn.initializers.ones, (1, 1, 1, diff.shape[-1]))
            total = total + jnp.mean(jnp.sum(diff * jnp.abs(w), axis=-1),
                                     axis=(1, 2), keepdims=False)
        return total  # (b,)


def init_lpips(key: jax.Array, image_size: int = 64,
               slices: Optional[Tuple[Tuple[int, ...], ...]] = None):
    model = LPIPS(slices=slices)
    x = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    params = model.init(key, x, x)
    return model, params


def save_perceptual_weights(params, path: str = _TINY_WEIGHTS):
    """Flatten a params pytree to an npz ('/'-joined keys)."""
    from flax.traverse_util import flatten_dict
    flat = {"/".join(k): np.asarray(v)
            for k, v in flatten_dict(jax.device_get(params)).items()}
    os.makedirs(os.path.dirname(path), exist_ok=True)
    np.savez_compressed(path, **flat)


def load_tiny_perceptual(path: str = _TINY_WEIGHTS):
    """The shipped in-repo perceptual net (see module docstring). Returns
    (LPIPS model, params). Raises FileNotFoundError if the artifact is
    missing (callers may fall back to ones-init)."""
    from flax.traverse_util import unflatten_dict
    data = np.load(path)
    params = unflatten_dict({tuple(k.split("/")): jnp.asarray(data[k])
                             for k in data.files})
    return LPIPS(slices=TINY_SLICES), params


def load_torch_weights(params, vgg_state: Dict[str, Any],
                       lin_state: Dict[str, Any] | None = None):
    """Map a torchvision ``vgg16().features`` state_dict (+ optional taming
    ``vgg.pth`` lin heads) onto LPIPS params. OIHW → HWIO transpose only."""
    import numpy as np

    p = jax.device_get(params)
    # torchvision features indices of conv layers, in slice order
    conv_idx = iter([0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28])
    vgg_p = p["params"]["vgg"]
    for s, chans in enumerate(_VGG_SLICES):
        for i in range(len(chans)):
            idx = next(conv_idx)
            w = np.asarray(vgg_state[f"features.{idx}.weight"])  # OIHW
            b = np.asarray(vgg_state[f"features.{idx}.bias"])
            vgg_p[f"slice{s}_conv{i}"]["kernel"] = w.transpose(2, 3, 1, 0)  # HWIO
            vgg_p[f"slice{s}_conv{i}"]["bias"] = b
    if lin_state is not None:
        for i in range(5):
            w = np.asarray(lin_state[f"lin{i}.model.1.weight"])  # (1, C, 1, 1)
            p["params"][f"lin{i}"] = w.reshape(1, 1, 1, -1)
    return jax.tree_util.tree_map(jnp.asarray, p)
