"""Discrete VAE — the trainable image tokenizer.

Reference: ``DiscreteVAE`` (dalle_pytorch/dalle_pytorch.py:101-252) and ``ResBlock``
(:87-99). Re-designed for TPU:

  * NHWC layout throughout (XLA:TPU's native conv layout; the reference is NCHW).
  * The Gumbel-softmax quantizer + codebook contraction is pure XLA
    (ops/quantize.py) — the reference's ``F.gumbel_softmax`` + einsum
    (dalle_pytorch.py:229-230) becomes one fused softmax+matmul that lands on
    the MXU.
  * Explicit RNG: the gumbel key is a ``'gumbel'`` rng collection, not hidden
    global state — this is what makes data-parallel determinism trivial
    (SURVEY.md §7 "Gumbel-softmax determinism across hosts").

Capability parity: encoder/decoder conv stacks with ResBlocks, per-channel
normalization buffers, smooth-l1/mse recon loss + batchmean KL-to-uniform,
``get_codebook_indices`` (argmax of logits), ``decode`` (codebook → decoder),
temperature / straight-through options.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config import DVAEConfig
from ..ops.quantize import gumbel_softmax, kl_to_uniform


class ResBlock(nn.Module):
    """conv3x3 → relu → conv3x3 → relu → conv1x1, residual (reference :87-99)."""
    chan: int

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.chan, (3, 3), padding=1, name="conv1")(x)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (3, 3), padding=1, name="conv2")(h)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (1, 1), name="conv3")(h)
        return h + x


class Encoder(nn.Module):
    """num_layers × (conv4x4/s2 + relu), then ResBlocks, then 1×1 to num_tokens
    logits (reference :140-158 layer assembly)."""
    cfg: DVAEConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        for i in range(c.num_layers):
            x = nn.Conv(c.hidden_dim, (4, 4), strides=(2, 2), padding=1,
                        name=f"down_{i}")(x)
            x = nn.relu(x)
        for i in range(c.num_resnet_blocks):
            x = ResBlock(c.hidden_dim, name=f"res_{i}")(x)
        x = nn.Conv(c.num_tokens, (1, 1), name="to_logits")(x)
        return x  # (b, h', w', num_tokens)


class Decoder(nn.Module):
    """1×1 from codebook_dim (when resblocks exist), ResBlocks, then
    num_layers × (convT4x4/s2 + relu), final 1×1 to channels (reference :144-158)."""
    cfg: DVAEConfig

    @nn.compact
    def __call__(self, z):
        c = self.cfg
        if c.num_resnet_blocks > 0:
            z = nn.Conv(c.hidden_dim, (1, 1), name="proj_in")(z)
            for i in range(c.num_resnet_blocks):
                z = ResBlock(c.hidden_dim, name=f"res_{i}")(z)
        for i in range(c.num_layers):
            z = nn.ConvTranspose(c.hidden_dim, (4, 4), strides=(2, 2),
                                 padding="SAME", name=f"up_{i}")(z)
            z = nn.relu(z)
        z = nn.Conv(c.channels, (1, 1), name="to_pixels")(z)
        return z


class DiscreteVAE(nn.Module):
    """The dVAE. Images are NHWC floats in [0, 1].

    Methods (select with ``method=`` in ``.apply``):
      * ``__call__(img, temp, return_loss, return_recons)`` — train/recon path;
        needs a ``'gumbel'`` rng.
      * ``get_codebook_indices(img)`` — (b, n) int32 hard token ids.
      * ``decode(img_seq)`` — token ids → image.
      * ``encode_logits(img)`` — (b, h, w, num_tokens) logits.
    """
    cfg: DVAEConfig

    def setup(self):
        c = self.cfg
        assert c.image_size & (c.image_size - 1) == 0, "image size must be a power of 2"
        assert c.num_layers >= 1
        self.encoder = Encoder(c, name="encoder")
        self.decoder = Decoder(c, name="decoder")
        self.codebook = nn.Embed(c.num_tokens, c.codebook_dim, name="codebook")

    def norm(self, images):
        """Per-channel (x - mean)/std buffers (reference :181-189)."""
        if self.cfg.normalization is None:
            return images
        means, stds = self.cfg.normalization
        means = jnp.asarray(means, images.dtype)
        stds = jnp.asarray(stds, images.dtype)
        return (images - means) / stds

    def encode_logits(self, img):
        assert img.shape[1] == img.shape[2] == self.cfg.image_size, (
            f"input must be {self.cfg.image_size}px, got {img.shape}")
        return self.encoder(self.norm(img))

    def get_codebook_indices(self, img):
        """argmax over token logits, flattened to raster order (reference :191-196)."""
        logits = self.encode_logits(img)
        b = logits.shape[0]
        return jnp.argmax(logits, axis=-1).reshape(b, -1).astype(jnp.int32)

    def decode(self, img_seq):
        """(b, n) token ids → (b, H, W, C) image (reference :198-208)."""
        emb = self.codebook(img_seq)
        b, n, d = emb.shape
        hw = int(n ** 0.5)
        return self.decoder(emb.reshape(b, hw, hw, d))

    def __call__(self, img, temp: Optional[float] = None, return_loss: bool = False,
                 return_recons: bool = False, hard_recons: bool = False,
                 return_health: bool = False):
        """``return_health`` appends a graftpulse health dict (codebook
        usage perplexity/dead-frac, gumbel temperature, straight-through
        sharpness — obs/health.py) as the LAST tuple element of every
        return path: pure jnp scalars computed from tensors already live
        in the step, so the taps fuse into the jitted program with no
        extra passes and no host syncs."""
        c = self.cfg
        img_n = self.norm(img)
        logits = self.encoder(img_n)

        temp = c.temperature if temp is None else temp
        if hard_recons:
            # deterministic eval path: argmax codebook lookup, no gumbel noise
            one_hot = jax.nn.one_hot(jnp.argmax(logits, -1), c.num_tokens, dtype=logits.dtype)
        else:
            key = self.make_rng("gumbel")
            one_hot = gumbel_softmax(key, logits, tau=temp, hard=c.straight_through)
        # (b,h,w,n) @ (n,d): the quantizer is a single MXU matmul
        sampled = jnp.einsum("bhwn,nd->bhwd", one_hot, self.codebook.embedding)
        out = self.decoder(sampled)

        health = None
        if return_health:
            from ..obs.health import codebook_health, gumbel_health
            # usage from the encoder argmax — the same statistic the
            # reference's wandb collapse histogram plots (train_vae:258-264)
            health = codebook_health(jnp.argmax(logits, -1), c.num_tokens)
            health.update(gumbel_health(logits, one_hot, temp))

        if not return_loss:
            return (out, health) if return_health else out

        # recon loss on *normalized* target, as the reference does (:236);
        # reductions in f32 so a bf16 compute path keeps a clean loss signal
        diff = img_n.astype(jnp.float32) - out.astype(jnp.float32)
        if c.smooth_l1_loss:
            a = jnp.abs(diff)
            recon = jnp.mean(jnp.where(a < 1.0, 0.5 * diff ** 2, a - 0.5))
        else:
            recon = jnp.mean(diff ** 2)

        b, h, w, n = logits.shape
        kl = kl_to_uniform(logits.reshape(b, h * w, n).astype(jnp.float32))
        loss = recon + kl * c.kl_div_loss_weight

        if not return_recons:
            return (loss, health) if return_health else loss
        return (loss, out, health) if return_health else (loss, out)


def init_dvae(cfg: DVAEConfig, key: jax.Array, batch: int = 1):
    """Initialize params with a dummy batch. Returns (model, params)."""
    model = DiscreteVAE(cfg)
    img = jnp.zeros((batch, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32)
    params = model.init({"params": key, "gumbel": key}, img, return_loss=True)
    return model, params
