"""DALL·E — the autoregressive text→image transformer.

Reference: ``DALLE`` (dalle_pytorch/dalle_pytorch.py:336-653). Capability parity:
per-position unique padding tokens (:370,578-579), <bos> prepend (:583), combined
text+image vocab with the static logits mask (:428-439), 7:1 image loss weighting
(:440,649-653), classifier-free-guidance text dropout (:570-574), stable-training
tricks (token blend :615-617 + DivideMax), shared input/output embeddings
(:71-83,421-423), axial positional embeddings when rotary is off, incremental
decoding with caches, top-k+gumbel sampling, image priming, text generation.

TPU redesign:
  * The VAE is NOT a submodule. JAX has no "frozen submodule" notion worth
    carrying; the model consumes image *token ids* and a thin ``DalleWithVae``
    wrapper tokenizes raw pixels through any VAE adapter (reference freezes the
    vae inside the module, :386-387 — same capability, cleaner separation).
  * ``generate_images`` is a single ``lax.scan`` over a preallocated cache
    pytree: O(1) compilations, static shapes, runs entirely on-device.
  * CFG keeps TWO caches (conditioned + null-text). The reference's cached CFG
    forks the *conditioned* cache for the null pass every step
    (dalle_pytorch.py:528-538), so its null branch silently attends to
    conditioned text keys; this implements the semantics its uncached path
    (use_cache=False) defines. Not a copy — a fix.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import DalleConfig
from ..ops.quantize_weights import QDense
from ..ops.sampling import (gumbel_sample, gumbel_sample_rows,
                            prob_mask_like, top_k_filter)
from .transformer import DivideMax, Transformer

MASK_VALUE = -1e9  # max_neg/2-style fill for the logits mask


class AxialPositionalEmbedding(nn.Module):
    """Learned factored 2D position embedding: row + col tables broadcast over
    the grid and summed (reference axial_positional_embedding.py:6-74, used with
    full-dim per axis as DALLE does)."""
    dim: int
    shape: Tuple[int, int]

    def setup(self):
        h, w = self.shape
        init = nn.initializers.normal(stddev=1.0)
        self.row = self.param("row", init, (h, 1, self.dim))
        self.col = self.param("col", init, (1, w, self.dim))

    def __call__(self, n: Optional[int] = None):
        h, w = self.shape
        emb = (self.row + self.col).reshape(h * w, self.dim)
        return emb if n is None else emb[:n]


def _ce_chunk_body(mdl, x_c, lbl_c, start: int):
    """Head + cross-entropy for one sequence chunk — module-first so
    ``nn.remat`` can lift it (same pattern as transformer._block_body)."""
    logits = mdl._finish(x_c, (start, x_c.shape[1]))
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), lbl_c)


class DALLE(nn.Module):
    cfg: DalleConfig
    # sequence-parallel mesh: routes the training forward's attention through
    # ring attention over the 'sp' axis (static module metadata; generation
    # paths keep the cached dense core)
    sp_mesh: Any = None

    def setup(self):
        c = self.cfg
        self.num_text_tokens = c.num_text_tokens + c.text_seq_len  # + per-pos pads
        self.total_tokens = self.num_text_tokens + c.image_vocab_size
        self.transformer = Transformer(c.transformer(), sp_mesh=self.sp_mesh,
                                       name="transformer")

        if c.share_input_output_emb:
            # one (total_tokens, dim) table serves both embeddings and the
            # output projection (reference SharedEmbedding, :71-83)
            self.shared_emb = self.param(
                "shared_emb", nn.initializers.normal(stddev=0.02),
                (self.total_tokens, c.dim))
            self.logits_bias = self.param(
                "logits_bias", nn.initializers.zeros, (self.total_tokens,))
        else:
            self.text_emb = nn.Embed(self.num_text_tokens, c.dim, name="text_emb")
            self.image_emb = nn.Embed(c.image_vocab_size, c.dim, name="image_emb")
            self.head = QDense(self.total_tokens, name="to_logits")

        if not c.rotary_emb:
            self.text_pos_emb = nn.Embed(c.text_seq_len + 1, c.dim,
                                         name="text_pos_emb")
            self.image_pos_emb = AxialPositionalEmbedding(
                c.dim, (c.image_fmap_size, c.image_fmap_size),
                name="image_pos_emb")

        self.final_norm = nn.LayerNorm(name="final_norm")
        self.norm_by_max = DivideMax(axis=-1)

        # static (seq, total_tokens) allow-mask: text positions predict text
        # tokens, image positions image tokens (reference :428-439, inverted
        # polarity: here True = allowed)
        seq_range = np.arange(c.total_seq_len)[:, None]
        logit_range = np.arange(self.total_tokens)[None, :]
        forbidden = (((seq_range >= c.text_seq_len) & (logit_range < self.num_text_tokens)) |
                     ((seq_range < c.text_seq_len) & (logit_range >= self.num_text_tokens)))
        self.logits_allow = jnp.asarray(~forbidden)

    # -- embedding helpers -------------------------------------------------
    def _shared_rows(self, ids):
        """Gather from the tied table; int8 tables (decode weight quant,
        ops/quantize_weights.py) dequantize per gathered row — only the int8
        bytes cross HBM."""
        tab = self.shared_emb
        rows = jnp.take(tab, ids, axis=0)
        if tab.dtype == jnp.int8:
            scale = self.get_variable("quant", "shared_emb_scale")
            dt = self.logits_bias.dtype
            rows = rows.astype(dt) * jnp.take(scale, ids, axis=0).astype(dt)
        return rows

    def _embed_text_ids(self, ids):
        if self.cfg.share_input_output_emb:
            return self._shared_rows(ids)
        return self.text_emb(ids)

    def _embed_image_ids(self, ids):
        if self.cfg.share_input_output_emb:
            return self._shared_rows(ids + self.num_text_tokens)
        return self.image_emb(ids)

    def _logits(self, x):
        x = self.final_norm(x)
        if self.cfg.share_input_output_emb:
            tab = self.shared_emb
            if tab.dtype == jnp.int8:
                scale = self.get_variable("quant", "shared_emb_scale")
                tab = tab.astype(x.dtype) * scale.astype(x.dtype)
            return x @ tab.T + self.logits_bias
        return self.head(x)

    def remap_and_bos(self, text):
        """0-pads → unique per-position pad ids; prepend <bos>=0
        (reference :578-583). Text longer than text_seq_len is cropped, shorter
        is 0-padded (reference generate_images crops at :507; tokenizers pad)."""
        c = self.cfg
        n = text.shape[1]
        if n > c.text_seq_len:
            text = text[:, :c.text_seq_len]
        elif n < c.text_seq_len:
            text = jnp.pad(text, ((0, 0), (0, c.text_seq_len - n)))
        pad_ids = jnp.arange(c.text_seq_len) + c.num_text_tokens
        text = jnp.where(text == 0, pad_ids[None, :], text)
        return jnp.pad(text, ((0, 0), (1, 0)))  # <bos> id 0

    def embed_text(self, text_with_bos):
        n = text_with_bos.shape[1]
        tok = self._embed_text_ids(text_with_bos)
        if not self.cfg.rotary_emb:
            tok = tok + self.text_pos_emb(jnp.arange(n))
        return tok

    def embed_image(self, image_ids, first_pos: int = 0):
        tok = self._embed_image_ids(image_ids)
        if not self.cfg.rotary_emb:
            n = image_ids.shape[1]
            tok = tok + self.image_pos_emb()[first_pos:first_pos + n]
        return tok

    def _stabilize(self, tokens):
        if self.cfg.stable:  # α-blend trick (reference :615-617)
            alpha = 0.1
            tokens = tokens * alpha + jax.lax.stop_gradient(tokens) * (1 - alpha)
        return tokens

    def _finish(self, x, mask_rows):
        """transformer output → masked logits. ``mask_rows``: (start, n) row
        window of the static logits mask aligned with these positions."""
        if self.cfg.stable:
            x = self.norm_by_max(x)
        logits = self._logits(x)
        start, n = mask_rows
        allow = jax.lax.dynamic_slice_in_dim(self.logits_allow, start, n, axis=0)
        return jnp.where(allow[None], logits, MASK_VALUE)

    # -- training forward --------------------------------------------------
    def __call__(self, text, image_ids, return_loss: bool = False,
                 null_cond_prob: float = 0.0, deterministic: bool = True):
        """``text``: (b, text_seq_len) int32 (0 = pad); ``image_ids``:
        (b, image_seq_len) int32 codebook indices."""
        c = self.cfg
        assert text.shape[1] == c.text_seq_len, (
            f"text must be {c.text_seq_len} tokens, got {text.shape[1]}")

        if null_cond_prob > 0:
            # CFG dropout: whole-row text nulling (reference :570-574)
            null = prob_mask_like(self.make_rng("cfg"), (text.shape[0],),
                                  null_cond_prob)
            text = jnp.where(null[:, None], 0, text)

        text_b = self.remap_and_bos(text)
        tokens = jnp.concatenate(
            [self.embed_text(text_b), self.embed_image(image_ids)], axis=1)
        # drop final token when over length (reference :608-613)
        if tokens.shape[1] > c.total_seq_len:
            tokens = tokens[:, :c.total_seq_len]
        tokens = self._stabilize(tokens)

        out = self.transformer(tokens, deterministic=deterministic)

        if not return_loss:
            return self._finish(out, (0, tokens.shape[1]))

        labels = jnp.concatenate(
            [text_b[:, 1:], image_ids + self.num_text_tokens], axis=1)
        n = tokens.shape[1]
        if c.loss_chunk > 0 and n % c.loss_chunk != 0:
            raise ValueError(
                f"loss_chunk={c.loss_chunk} must divide the sequence length "
                f"{n} — a silent fall-back would rematerialize the full "
                f"(b, n, vocab) logits the option exists to avoid")
        if c.loss_chunk > 0 and not self.is_initializing():
            # chunked head+CE under remat: full (b, n, vocab) logits never hit
            # HBM — each chunk's logits are recomputed in backward
            parts = []
            for i in range(0, n, c.loss_chunk):
                body = nn.remat(_ce_chunk_body, prevent_cse=False,
                                static_argnums=(3,))
                parts.append(body(self, out[:, i:i + c.loss_chunk],
                                  labels[:, i:i + c.loss_chunk], i))
            ce = jnp.concatenate(parts, axis=1)
        else:
            logits = self._finish(out, (0, n))
            logits32 = logits.astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits32, labels)
        loss_text = ce[:, :c.text_seq_len].mean()
        loss_img = ce[:, c.text_seq_len:].mean()
        loss = (loss_text + c.loss_img_weight * loss_img) / (c.loss_img_weight + 1)
        return loss, {"loss_text": loss_text, "loss_img": loss_img}

    # -- generation --------------------------------------------------------
    def _prefill(self, text, image_prime: Optional[jnp.ndarray], batch: int,
                 dtype=jnp.float32, extra_slots: int = 0):
        c = self.cfg
        cache = self.transformer.init_cache(batch,
                                            c.total_seq_len + extra_slots,
                                            dtype)
        text_b = self.remap_and_bos(text)
        tokens = self.embed_text(text_b)
        if image_prime is not None and image_prime.shape[1] > 0:
            tokens = jnp.concatenate(
                [tokens, self.embed_image(image_prime)], axis=1)
        tokens = self._stabilize(tokens)
        y, cache = self.transformer.prefill(tokens, cache)
        logits = self._finish(y[:, -1:], (tokens.shape[1] - 1, 1))[:, 0]
        return logits, cache, tokens.shape[1]

    def _decode_one(self, token_id, img_pos, offset, cache, use_kernel=None):
        """Embed image token sampled at image position ``img_pos`` and advance."""
        tok = self._embed_image_ids(token_id[:, None])
        if not self.cfg.rotary_emb:
            emb = self.image_pos_emb()
            tok = tok + jax.lax.dynamic_slice_in_dim(emb, img_pos, 1, axis=0)[None]
        tok = self._stabilize(tok)
        y, cache = self.transformer.decode_step(tok, cache, offset,
                                                use_kernel=use_kernel)
        logits = self._finish(y, (offset, 1))[:, 0]
        return logits, cache

    def generate_images_tokens(self, text, key, *, filter_thres: float = 0.5,
                               temperature: float = 1.0, cond_scale: float = 1.0,
                               image_prime: Optional[jnp.ndarray] = None,
                               cache_dtype=jnp.float32,
                               topk_approx: bool = False,
                               use_kernel=None):
        """AR-sample the full image token sequence. Returns (b, image_seq_len)
        int32 codebook ids. ``text`` must be (b, text_seq_len).
        ``cache_dtype=bf16`` halves the KV-cache traffic of the decode loop;
        ``cache_dtype=jnp.int8`` halves it again via per-position symmetric
        quantization (ops/attention.KVCache — sampling itself always runs on
        f32 logits). ``topk_approx`` swaps the exact per-step top-k sort for
        TPU's approximate top-k unit (ops/sampling.top_k_filter) — the sort
        is ~17% of decode wall time at batch 64. ``use_kernel`` pins the
        Pallas decode-kernel selection (None = shape-gated auto on TPU,
        always dense elsewhere); pin False here AND on a serve engine for
        strict bitwise parity between the two — the single-token and
        windowed kernels are distinct implementations, so auto mode may
        pick different attends per path on TPU.
        (reference generate_images :490-557 minus vae decode/CLIP, which live in
        DalleWithVae)"""
        c = self.cfg
        b = text.shape[0]
        n_prime = 0 if image_prime is None else image_prime.shape[1]
        n_steps = c.image_seq_len - n_prime
        use_cfg = cond_scale != 1.0

        logits, cache, prefix_len = self._prefill(text, image_prime, b,
                                                  dtype=cache_dtype)
        if use_cfg:
            null_text = jnp.zeros_like(text)  # all-pad after remap
            null_logits, null_cache, _ = self._prefill(null_text, image_prime,
                                                       b, dtype=cache_dtype)
            logits = null_logits + (logits - null_logits) * cond_scale

        def sample_from(logits, k):
            band = logits[:, self.num_text_tokens:]  # image band only
            filtered = top_k_filter(band, thres=filter_thres,
                                    approx=topk_approx)
            return gumbel_sample(k, filtered, temperature=temperature).astype(jnp.int32)

        def body(carry, i):
            logits, cache, null_cache, k = carry
            k, sub = jax.random.split(k)
            tok = sample_from(logits, sub)
            img_pos = n_prime + i
            offset = prefix_len + i
            new_logits, cache = self._decode_one(tok, img_pos, offset, cache,
                                                 use_kernel)
            if use_cfg:
                nl, null_cache = self._decode_one(tok, img_pos, offset,
                                                  null_cache, use_kernel)
                new_logits = nl + (new_logits - nl) * cond_scale
            return (new_logits, cache, null_cache, k), tok

        # when CFG is off the null slot carries a scalar placeholder, not a
        # second copy of the cache
        init = (logits, cache, null_cache if use_cfg else jnp.zeros(()), key)
        (last_logits, *_), toks = nn.scan(
            lambda m, carry, i: body(carry, i),
            variable_broadcast=("params", "quant"),
            split_rngs={"params": False},
            length=n_steps - 1)(self, init, jnp.arange(n_steps - 1))
        # final token sampled from the last logits (no decode needed after it)
        final = sample_from(last_logits, jax.random.fold_in(key, n_steps))
        toks = jnp.moveaxis(toks, 0, 1)  # (b, n_steps-1)
        out = jnp.concatenate([toks, final[:, None]], axis=1)
        if image_prime is not None and n_prime > 0:
            out = jnp.concatenate([image_prime, out], axis=1)
        return out

    def generate_images_tokens_speculative(
            self, text, key, *, gamma: int = 4, draft: str = "row",
            filter_thres: float = 0.5, temperature: float = 1.0,
            cache_dtype=jnp.float32, topk_approx: bool = False,
            return_stats: bool = False):
        """Draft-free speculative AR sampling: each round drafts ``gamma``
        tokens with a zero-cost image prior, verifies them in ONE windowed
        forward (w = gamma+1 tokens ≈ the cost of a single decode step —
        batched decode is weight/KV-bandwidth-bound, so extra window tokens
        ride the same HBM streams), and commits the accepted prefix + one
        token. Rows accept independently (per-row cache offsets/lengths).

        Sampling semantics are EXACT for any draft quality: token t is
        always argmax(top_k(logits_t)/T + gumbel(key_t_row)) with
        logits_t computed from the committed prefix — rejected drafts only
        cost wasted work, never bias (gamma=0 degenerates to the sequential
        loop and must produce identical tokens; asserted by
        tests/test_speculative.py). Keys are per-(step, row) fold-ins —
        a different stream from generate_images_tokens' split chain, so
        outputs match that path distributionally, not bitwise.

        ``draft``: "row" = the committed token one grid-row above (the
        2D-autoregressive prior — vertically continuous images accept
        long runs); "repeat" = repeat the last sampled token (flat-region
        prior). Reference bar: the strictly sequential generate_images loop
        (dalle_pytorch/dalle_pytorch.py:523-546).

        ``return_stats``: also return (rounds_used, committed_total) —
        committed_total / (batch · rounds_used) is the per-row acceptance
        rate in committed tokens per round."""
        c = self.cfg
        b = text.shape[0]
        n_steps = c.image_seq_len
        fmap = c.image_fmap_size
        assert gamma >= 0
        assert draft in ("row", "repeat")
        if draft == "row":
            assert gamma < fmap, (
                f"'row' draft needs gamma < image_fmap_size ({fmap}); the "
                f"row-above token of a draft slot must already be committed")
        w = gamma + 1
        arange_b = jnp.arange(b)

        logits0, cache, prefix_len = self._prefill(
            text, None, b, dtype=cache_dtype, extra_slots=gamma)

        def sample_rows(logits, t_idx):
            """Token at per-row step ``t_idx`` from (b, V) logits — the
            committed key discipline key(step, row)."""
            keys = jax.vmap(lambda t, r: jax.random.fold_in(
                jax.random.fold_in(key, t), r))(t_idx, arange_b)
            return gumbel_sample_rows(keys, logits[:, self.num_text_tokens:],
                                      thres=filter_thres,
                                      temperature=temperature,
                                      approx=topk_approx)

        def draft_tokens(tok0, out_buf, t_idx):
            if gamma == 0:
                return jnp.zeros((b, 0), jnp.int32)
            p = t_idx[:, None] + jnp.arange(1, gamma + 1)[None, :]  # (b, γ)
            if draft == "row":
                src = jnp.clip(p - fmap, 0, n_steps - 1)
                above = jnp.take_along_axis(out_buf, src, axis=1)
                return jnp.where(p - fmap >= 0, above, tok0[:, None])
            return jnp.broadcast_to(tok0[:, None], (b, gamma))

        img_allow = self.logits_allow[c.text_seq_len]   # every image row ==

        def finish_rows(y):
            if c.stable:
                y = self.norm_by_max(y)
            logits = self._logits(y)
            return jnp.where(img_allow[None, None], logits, MASK_VALUE)

        def body(carry):
            out_buf, t_idx, logits, cache, rounds, committed_total = carry
            t_eff = jnp.minimum(t_idx, n_steps - 1)   # finished rows idle
            tok0 = sample_rows(logits, t_eff)
            drafts = draft_tokens(tok0, out_buf, t_eff)
            window = jnp.concatenate([tok0[:, None], drafts], axis=1)
            emb = self._embed_image_ids(window)
            if not c.rotary_emb:
                img_pos = t_eff[:, None] + jnp.arange(w)[None, :]
                emb = emb + jnp.take(self.image_pos_emb(),
                                     jnp.clip(img_pos, 0, n_steps - 1),
                                     axis=0)
            emb = self._stabilize(emb)
            y, cache = self.transformer.decode_window(
                emb, cache, prefix_len + t_eff)
            logits_w = finish_rows(y)                    # (b, w, V)
            cands = jnp.stack(
                [sample_rows(logits_w[:, j], t_eff + 1 + j)
                 for j in range(w)], axis=1)             # tokens t+1..t+w
            if gamma > 0:
                eq = (drafts == cands[:, :gamma]).astype(jnp.int32)
                acc = jnp.cumprod(eq, axis=1).sum(axis=1)   # (b,) 0..γ
            else:
                acc = jnp.zeros((b,), jnp.int32)
            # commit window[:, j] at index t+j for j ≤ acc (window[j] ==
            # cands[j-1] wherever accepted); drop out-of-range / finished
            idx = t_eff[:, None] + jnp.arange(w)[None, :]
            keep = ((jnp.arange(w)[None, :] <= acc[:, None])
                    & (idx < n_steps) & (t_idx[:, None] < n_steps))
            safe_idx = jnp.where(keep, idx, n_steps)
            out_buf = out_buf.at[arange_b[:, None], safe_idx].set(
                window, mode="drop")
            # carry logits after the LAST committed token: exact, because
            # cache slots ≤ t+acc hold exactly the committed tokens
            new_logits = jnp.take_along_axis(
                logits_w, acc[:, None, None], axis=1)[:, 0]
            # clamp at the sequence end: an accepted run crossing n_steps
            # only commits the in-range part (its writes were dropped above)
            step = jnp.where(t_idx < n_steps,
                             jnp.minimum(acc + 1, n_steps - t_idx), 0)
            return (out_buf, t_idx + step, new_logits, cache, rounds + 1,
                    committed_total + step.sum())

        def cond(carry):
            return jnp.any(carry[1] < n_steps)

        init = (jnp.zeros((b, n_steps), jnp.int32), jnp.zeros((b,), jnp.int32),
                logits0, cache, jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32))
        out_buf, _, _, _, rounds, committed = jax.lax.while_loop(
            cond, body, init)
        if return_stats:
            return out_buf, rounds, committed
        return out_buf

    # -- serving: per-row-length decode primitives (dalle_tpu/serve) -------
    # The continuous-batching engine keeps B decode slots in ONE shared
    # cache; slots are at ragged positions (each carries its own prompt and
    # per-row length), so every device call below threads (b,) offset
    # vectors through transformer.decode_window. Rows that must not be
    # touched get offset == max_seq: their k/v scatter indices land entirely
    # out of bounds and are DROPPED (XLA scatter OOB semantics — the same
    # contract the speculative path's mode="drop" commit relies on), so a
    # parked row's cache is bit-identical before and after the call.
    #
    # Exactness contract (tests/test_serve.py): with cache max_seq ==
    # total_seq_len — the same size single-request generation uses — every
    # reduction in these paths has the same width as its sequential
    # counterpart, and each request's logits (hence tokens, under the same
    # key discipline) match generate_images_tokens bitwise, for any
    # admission order.

    def serve_img_logits(self, y):
        """(b, dim) hidden states → (b, V) masked logits. Every served
        position predicts image tokens, and the static allow-mask rows for
        positions ≥ text_seq_len are identical — one row serves them all
        (the same argument generate_images_tokens_speculative makes)."""
        return self._finish(y[:, None], (self.cfg.text_seq_len, 1))[:, 0]

    def serve_init_cache(self, batch: int, dtype=jnp.float32):
        """Shared decode cache for ``batch`` serve slots. max_seq is exactly
        total_seq_len so softmax reduce widths match single-request
        generation (bitwise exactness); the park offset is max_seq itself."""
        return self.transformer.init_cache(batch, self.cfg.total_seq_len,
                                           dtype)

    def serve_init_cache_paged(self, num_blocks: int, block_tokens: int,
                               dtype=jnp.float32):
        """Paged serve cache (graftpage): per-layer block pools; reads
        gather back to a dense total_seq_len view so reduce widths — and
        therefore every request's tokens — stay bitwise identical to the
        dense slab and to single-request generation. The engine injects its
        single page-table leaf into each layer per dispatch."""
        return self.transformer.init_cache_paged(
            num_blocks, block_tokens, self.cfg.total_seq_len, dtype)

    def serve_refill(self, text, cache, refill_mask, use_kernel=None):
        """Admission: prefill new prompts into SELECTED rows of the live
        multi-slot cache in one multi-row window. ``text`` (b, text_seq_len)
        int32 (rows with ``refill_mask`` False are ignored); refilled rows
        write their prompt k/v at [0, prefix_len) — overwriting the previous
        occupant — while every other row parks at offset max_seq. Returns
        (logits (b, V) for each refilled row's first image token, cache)."""
        S = cache["kv_0"].max_seq       # max_seq == the park offset
        text_b = self.remap_and_bos(text)
        tokens = self._stabilize(self.embed_text(text_b))
        offsets = jnp.where(refill_mask, 0, S)
        y, cache = self.transformer.decode_window(tokens, cache, offsets,
                                                  use_kernel=use_kernel)
        return self.serve_img_logits(y[:, -1]), cache

    def serve_refill_shared(self, text1, cache, refill_mask,
                            cache_dtype=jnp.float32):
        """Shared-prefix admission (graftloom): ONE b=1 text prefill —
        bitwise the sequential ``_prefill``, exactly ``serve_prefill_row`` —
        broadcast into every ``refill_mask`` row of the live multi-slot
        cache. N candidates of one prompt (a ``/v1/images`` fan-out) pay ONE
        prompt prefill instead of N: the prefix KV depends only on the text,
        never the seed, so copying the same bits into each sibling row is
        exact by construction — each candidate then decodes under its own
        RNG lane and stays bitwise identical to an independent
        single-candidate request (the PR4 bar, (N−1) prefills cheaper).
        Returns (logits (1, V) for the shared first image token, cache)."""
        logits1, cache1 = self.serve_prefill_row(text1,
                                                 cache_dtype=cache_dtype)
        cache = dict(cache)
        m2 = refill_mask[:, None, None]
        for name, small in cache1.items():
            big = cache[name]
            # (1, S, 2hd) broadcasts over the slot axis; unmasked rows keep
            # their occupant's cache bit-identically
            kv = jnp.where(m2, small.kv, big.kv)
            if big.scale is not None:
                sc = jnp.where(m2, small.scale, big.scale)
                cache[name] = big.replace(kv=kv, scale=sc)
            else:
                cache[name] = big.replace(kv=kv)
        return logits1, cache

    def serve_refill_window(self, ids, cache, refill_mask, start,
                            use_kernel=None):
        """Chunked-prefill admission: one bounded window of an already
        remapped+bos'd prompt (``ids`` (b, w), full-vocab token ids — the
        engine host-applies ``remap_and_bos`` and slices) written at
        absolute positions [start, start+w) of each ``refill_mask`` row.
        Dispatching the prompt as ceil(prefix/w) of these windows
        interleaved with decode iterations bounds how long one fat
        admission can stall its neighbors' tokens (p95 TTFT isolation);
        causality makes the chunked prefix bitwise identical to the one-shot
        ``serve_refill`` window — each chunk token attends exactly the cache
        prefix the full window would have shown it, at the same reduce
        widths. Returns (logits (b, V) from the window's LAST position —
        meaningful only on the final chunk — and the cache)."""
        S = cache["kv_0"].max_seq       # max_seq == the park offset
        n = ids.shape[1]
        tok = self._embed_text_ids(ids)
        if not self.cfg.rotary_emb:
            tok = tok + self.text_pos_emb(start + jnp.arange(n))
        tokens = self._stabilize(tok)
        offsets = jnp.where(refill_mask, start, S)
        y, cache = self.transformer.decode_window(tokens, cache, offsets,
                                                  use_kernel=use_kernel)
        return self.serve_img_logits(y[:, -1]), cache

    def serve_prefill_row(self, text, cache_dtype=jnp.float32):
        """Single-request prefill for the engine's per-row admission path:
        (1, text_seq_len) text → (logits (1, V), fresh b=1 cache sized
        total_seq_len). Bitwise identical to the sequential ``_prefill`` by
        construction — the engine scatters the cache row into the shared
        multi-slot cache (cheaper than the multi-row refill window when
        admitting a small fraction of the slots)."""
        logits, cache, _ = self._prefill(text, None, 1, dtype=cache_dtype,
                                         extra_slots=0)
        return logits, cache

    def serve_decode(self, tok, img_pos, offsets, cache, use_kernel=None):
        """One decode step for every slot at PER-ROW positions: ``tok`` (b,)
        image-band token ids, ``img_pos`` (b,) image grid positions (axial
        table rows when rotary is off), ``offsets`` (b,) absolute cache
        write positions — parked rows pass max_seq (write dropped, output
        discarded by the engine). Returns (logits (b, V), cache)."""
        c = self.cfg
        emb = self._embed_image_ids(tok[:, None])
        if not c.rotary_emb:
            pos = jnp.clip(img_pos, 0, c.image_seq_len - 1)
            emb = emb + jnp.take(self.image_pos_emb(), pos, axis=0)[:, None]
        emb = self._stabilize(emb)
        y, cache = self.transformer.decode_window(emb, cache, offsets,
                                                  use_kernel=use_kernel)
        return self.serve_img_logits(y[:, 0]), cache

    def generate_texts_tokens(self, key, text: Optional[jnp.ndarray] = None, *,
                              batch: int = 1, filter_thres: float = 0.5,
                              temperature: float = 1.0):
        """Complete a text prefix to text_seq_len tokens by AR sampling over the
        text band (reference generate_texts :443-488). Returns (b, text_seq_len)."""
        c = self.cfg
        if text is None:
            text = jnp.zeros((batch, 0), jnp.int32)
        b, start = text.shape
        assert start < c.text_seq_len, (
            f"text prefix must be shorter than text_seq_len={c.text_seq_len}, "
            f"got {start}")
        cache = self.transformer.init_cache(b, c.total_seq_len)
        # prefix: bos + given tokens (no pad remap — these are real tokens)
        ids = jnp.pad(text, ((0, 0), (1, 0)))
        tokens = self._stabilize(self.embed_text(ids))
        y, cache = self.transformer.prefill(tokens, cache)
        logits = self._finish(y[:, -1:], (start, 1))[:, 0]

        def sample_text(logits, k):
            filtered = top_k_filter(logits[:, :self.num_text_tokens],
                                    thres=filter_thres)
            return gumbel_sample(k, filtered, temperature=temperature).astype(jnp.int32)

        def body(carry, i):
            logits, cache, k = carry
            k, sub = jax.random.split(k)
            tok = sample_text(logits, sub)
            pos = start + 1 + i  # position of this token (after bos)
            emb = self._embed_text_ids(tok[:, None])
            if not c.rotary_emb:
                emb = emb + self.text_pos_emb(jnp.array([pos]))[None]
            emb = self._stabilize(emb)
            y, cache = self.transformer.decode_step(emb, cache, pos)
            new_logits = self._finish(y, (pos, 1))[:, 0]
            return (new_logits, cache, k), tok

        n_new = c.text_seq_len - start
        (last_logits, *_), toks = nn.scan(
            lambda m, carry, i: body(carry, i),
            variable_broadcast=("params", "quant"),
            split_rngs={"params": False},
            length=n_new - 1)(self, (logits, cache, key), jnp.arange(n_new - 1))
        final = sample_text(last_logits, jax.random.fold_in(key, n_new))
        toks = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([text, toks, final[:, None]], axis=1)


def init_dalle(cfg: DalleConfig, key: jax.Array, batch: int = 1, sp_mesh=None):
    model = DALLE(cfg, sp_mesh=sp_mesh)
    text = jnp.zeros((batch, cfg.text_seq_len), jnp.int32)
    img = jnp.zeros((batch, cfg.image_seq_len), jnp.int32)
    params = model.init({"params": key, "cfg": key}, text, img, return_loss=True)
    return model, params
