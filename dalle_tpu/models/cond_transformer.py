"""Net2Net conditional transformer — second-stage AR model over VQGAN codes.

Reference: ``Net2NetTransformer`` (taming/models/cond_transformer.py:21-343):
first-stage VQGAN codes conditioned on cond-stage codes (another VQGAN, a
``CoordStage``, or an unconditional SOS token), a minGPT transformer over the
concatenated sequence, ``pkeep`` token corruption during training, top-k AR
sampling, and a permuter controlling generation order.

TPU design: stages are frozen apply-fns over their own param trees (the
functional analogue of the reference's ``.eval()`` + ``disabled_train``
freezing, :54-78); the train forward is fully jittable (bernoulli corruption
from an explicit key); sampling reuses the scan-based cached sampler in
``mingpt.py``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.permuter import Permuter, identity
from .mingpt import GPT, GPTConfig, make_sampler


class CoordStage:
    """Fake-vq coordinate conditioning stage (taming/modules/misc/coord.py:3-31):
    area-downsample a [0,1] coord map, quantize into n_embed integer bins.
    NHWC with a single channel."""

    def __init__(self, n_embed: int, down_factor: int):
        self.n_embed = n_embed
        self.down_factor = down_factor

    def encode(self, c: jnp.ndarray):
        assert c.ndim == 4 and c.shape[-1] == 1
        b, h, w, _ = c.shape
        f = self.down_factor
        # area interpolation == mean pooling for integer factors
        c = c.reshape(b, h // f, f, w // f, f, 1).mean(axis=(2, 4))
        c = jnp.clip(c, 0.0, 1.0) * self.n_embed
        # the reference rounds to [0, n_embed] INCLUSIVE (coord.py:21-23) —
        # n_embed+1 bins, with the top bin OOB for an n_embed vocab; clamp it
        c_quant = jnp.minimum(jnp.round(c), self.n_embed - 1)
        c_ind = c_quant.astype(jnp.int32).reshape(b, -1)
        return c_quant, c_ind

    def decode(self, c_quant: jnp.ndarray):
        c = c_quant / self.n_embed
        b, h, w, ch = c.shape
        f = self.down_factor
        return jax.image.resize(c, (b, h * f, w * f, ch), method="nearest")


class SOSProvider:
    """Unconditional stand-in: a constant start-of-sequence token
    (cond_transformer.py SOSProvider + :68-74)."""

    def __init__(self, sos_token: int):
        self.sos_token = sos_token

    def encode(self, c):
        b = c.shape[0]
        ids = jnp.full((b, 1), self.sos_token, jnp.int32)
        return None, ids


class Net2NetTransformer:
    """Pairs a GPT with frozen first/cond stages.

    ``first_stage_encode(x) -> (b, n) int32`` and
    ``first_stage_decode(ids) -> images`` are closures over the frozen VQGAN
    params (see ``from_vqgan``); ``cond_encode(c) -> (b, m) int32`` likewise.
    """

    def __init__(self, gpt: GPT, first_stage_encode: Callable,
                 first_stage_decode: Callable, cond_encode: Callable,
                 permuter: Optional[Permuter] = None, pkeep: float = 1.0,
                 first_stage_vocab: Optional[int] = None):
        self.gpt = gpt
        self.first_stage_encode = first_stage_encode
        self.first_stage_decode = first_stage_decode
        self.cond_encode = cond_encode
        self.permuter = permuter
        self.pkeep = pkeep
        # ids ≥ this are cond-stage vocabulary: never sampled into z positions
        self.first_stage_vocab = first_stage_vocab
        self._samplers = {}   # (steps, top_k, temperature) → jitted sampler

    @classmethod
    def from_vqgan(cls, gpt_cfg: GPTConfig, vq_model, vq_params, *,
                   cond_encode: Callable, permuter: Optional[Permuter] = None,
                   pkeep: float = 1.0, key: Optional[jax.Array] = None):
        from .vqgan import VQModel
        gpt = GPT(gpt_cfg)

        def fs_encode(x):
            return vq_model.apply(vq_params, x,
                                  method=VQModel.get_codebook_indices)

        def fs_decode(ids):
            return vq_model.apply(vq_params, ids, method=VQModel.decode_code)

        return cls(gpt, fs_encode, fs_decode, cond_encode, permuter, pkeep,
                   first_stage_vocab=vq_model.cfg.n_embed)

    # -- token plumbing ----------------------------------------------------
    def encode_to_z(self, x) -> jnp.ndarray:
        ids = jax.lax.stop_gradient(self.first_stage_encode(x))
        if self.permuter is not None:
            ids = self.permuter(ids)
        return ids

    def encode_to_c(self, c) -> jnp.ndarray:
        out = self.cond_encode(c)
        ids = out[-1] if isinstance(out, tuple) else out
        return jax.lax.stop_gradient(ids.reshape(ids.shape[0], -1))

    def decode_to_img(self, ids) -> jnp.ndarray:
        if self.permuter is not None:
            ids = self.permuter(ids, reverse=True)
        return self.first_stage_decode(ids)

    # -- training forward (cond_transformer.py:80-105) ---------------------
    def forward(self, gpt_params, x, c, *, key: Optional[jax.Array] = None,
                train: bool = True):
        """Returns (logits over z positions, target z indices)."""
        z_indices = self.encode_to_z(x)
        c_indices = self.encode_to_c(c)
        a_indices = z_indices
        if train and self.pkeep < 1.0:
            assert key is not None, "pkeep corruption needs an rng key"
            kmask, krand = jax.random.split(key)
            mask = jax.random.bernoulli(kmask, self.pkeep, z_indices.shape)
            rand = jax.random.randint(krand, z_indices.shape, 0,
                                      self.gpt.cfg.vocab_size, jnp.int32)
            a_indices = jnp.where(mask, z_indices, rand)
        cz = jnp.concatenate([c_indices, a_indices], axis=1)
        logits = self.gpt.apply(gpt_params, cz[:, :-1], deterministic=not train)
        # output i predicts p(z_i | z_<i, c): drop the cond positions
        logits = logits[:, c_indices.shape[1] - 1:]
        return logits, z_indices

    def loss(self, gpt_params, x, c, *, key: Optional[jax.Array] = None,
             train: bool = True):
        logits, target = self.forward(gpt_params, x, c, key=key, train=train)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)
        return jnp.mean(nll)

    # -- sampling (cond_transformer.py:107-166, scan-based) ----------------
    def sample(self, gpt_params, c_images, steps: int, key: jax.Array, *,
               temperature: float = 1.0, top_k: Optional[int] = None,
               z_prime: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """Generate ``steps`` z tokens conditioned on ``c_images``; returns
        decoded images. ``z_prime`` optionally primes the image prefix."""
        c_indices = self.encode_to_c(c_images)
        prompt = c_indices
        if z_prime is not None:
            prompt = jnp.concatenate([c_indices, z_prime], axis=1)
        skey = (steps, top_k, temperature)
        if skey not in self._samplers:
            self._samplers[skey] = make_sampler(
                self.gpt, steps, top_k=top_k, temperature=temperature,
                vocab_limit=self.first_stage_vocab)
        out = self._samplers[skey](gpt_params, prompt, key)
        z_ids = out[:, c_indices.shape[1]:]
        return self.decode_to_img(z_ids)
