"""VQGAN autoencoder — the taming-transformers capability, rebuilt TPU-first.

Reference: ``VQModel``/``GumbelVQ`` (dalle_pytorch/taming/models/vqgan.py:12-303)
over the DDPM-style conv stacks (taming/modules/diffusionmodules/model.py:342-537:
ResnetBlock :78-137, AttnBlock :140-192, Down/Upsample :38-76) and the quantizers
(taming/modules/vqvae/quantize.py:110-329).

TPU redesign notes:
  * NHWC layout throughout (XLA:TPU native conv layout; reference is NCHW).
  * The spatial self-attention block is phrased as two batched matmuls over the
    flattened (h·w) axis so it lands on the MXU; at the configured
    ``attn_resolutions`` (default 16×16 = 256 positions) dense attention is
    exactly the right tool — no kernel needed.
  * Quantizers are the pure-XLA ops in ``ops/quantize.py`` (NN lookup phrased as
    one big matmul; straight-through via ``stop_gradient``).
  * No Lightning, no optimizer_idx switches — training lives in
    ``train/trainer_vqgan.py`` as two explicit jitted steps.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config import VQGANConfig
from ..ops.quantize import (VQOutput, gumbel_quantize, remap_indices,
                            unmap_indices, vector_quantize)
from ..utils.misc import deterministic_key


def swish(x):
    return x * jax.nn.sigmoid(x)


def group_norm(name: str, channels: Optional[int] = None):
    # GroupNorm(32, eps=1e-6) — taming model.py:34-35 ("Normalize"). For small
    # test-sized channel counts, fall back to the largest divisor ≤ 32.
    groups = 32
    if channels is not None and channels % 32 != 0:
        import math
        groups = math.gcd(32, channels)
    return nn.GroupNorm(num_groups=groups, epsilon=1e-6, name=name)


class ResnetBlock(nn.Module):
    """norm→swish→conv3x3, norm→swish→dropout→conv3x3, 1×1 nin shortcut when the
    channel count changes (taming model.py:78-137; temb path unused by VQGAN)."""
    out_ch: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        h = group_norm("norm1", x.shape[-1])(x)
        h = swish(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, name="conv1")(h)
        h = group_norm("norm2", h.shape[-1])(h)
        h = swish(h)
        h = nn.Dropout(self.dropout, deterministic=deterministic)(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, name="conv2")(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), name="nin_shortcut")(x)
        return x + h


class AttnBlock(nn.Module):
    """Single-head spatial self-attention over the h×w grid
    (taming model.py:140-192), as two MXU matmuls on the flattened axis."""

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        hn = group_norm("norm", c)(x)
        q = nn.Conv(c, (1, 1), name="q")(hn).reshape(b, h * w, c)
        k = nn.Conv(c, (1, 1), name="k")(hn).reshape(b, h * w, c)
        v = nn.Conv(c, (1, 1), name="v")(hn).reshape(b, h * w, c)
        attn = jax.nn.softmax(jnp.einsum("bic,bjc->bij", q, k) * (c ** -0.5), axis=-1)
        out = jnp.einsum("bij,bjc->bic", attn, v).reshape(b, h, w, c)
        out = nn.Conv(c, (1, 1), name="proj_out")(out)
        return x + out


class Downsample(nn.Module):
    """conv3x3 stride 2 with the reference's asymmetric (0,1) pad
    (taming model.py:56-75)."""
    ch: int

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.ch, (3, 3), strides=(2, 2),
                       padding=((0, 1), (0, 1)), name="conv")(x)


class Upsample(nn.Module):
    """nearest ×2 then conv3x3 (taming model.py:38-53)."""
    ch: int

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        x = jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")
        return nn.Conv(self.ch, (3, 3), padding=1, name="conv")(x)


class VQGANEncoder(nn.Module):
    """conv_in → [num_res_blocks × ResnetBlock (+Attn at attn_resolutions),
    Downsample] per ch_mult level → mid(Res, Attn, Res) → norm/swish/conv_out
    to z_channels (taming model.py:342-433)."""
    cfg: VQGANConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        c = self.cfg
        h = nn.Conv(c.ch, (3, 3), padding=1, name="conv_in")(x)
        curr_res = c.resolution
        for i_level, mult in enumerate(c.ch_mult):
            for i_block in range(c.num_res_blocks):
                h = ResnetBlock(c.ch * mult, c.dropout,
                                name=f"down_{i_level}_block_{i_block}")(h, deterministic)
                if curr_res in c.attn_resolutions:
                    h = AttnBlock(name=f"down_{i_level}_attn_{i_block}")(h)
            if i_level != len(c.ch_mult) - 1:
                h = Downsample(h.shape[-1], name=f"down_{i_level}_downsample")(h)
                curr_res //= 2
        h = ResnetBlock(h.shape[-1], c.dropout, name="mid_block_1")(h, deterministic)
        h = AttnBlock(name="mid_attn_1")(h)
        h = ResnetBlock(h.shape[-1], c.dropout, name="mid_block_2")(h, deterministic)
        h = group_norm("norm_out", h.shape[-1])(h)
        h = swish(h)
        out_ch = 2 * c.z_channels if c.double_z else c.z_channels
        return nn.Conv(out_ch, (3, 3), padding=1, name="conv_out")(h)


class VQGANDecoder(nn.Module):
    """conv_in → mid(Res, Attn, Res) → [(num_res_blocks+1) × ResnetBlock
    (+Attn), Upsample] per reversed ch_mult level → norm/swish/conv_out
    (taming model.py:436-537)."""
    cfg: VQGANConfig

    @nn.compact
    def __call__(self, z, deterministic: bool = True, return_pre_out: bool = False):
        c = self.cfg
        num_levels = len(c.ch_mult)
        curr_res = c.resolution // 2 ** (num_levels - 1)
        h = nn.Conv(c.ch * c.ch_mult[-1], (3, 3), padding=1, name="conv_in")(z)
        h = ResnetBlock(h.shape[-1], c.dropout, name="mid_block_1")(h, deterministic)
        h = AttnBlock(name="mid_attn_1")(h)
        h = ResnetBlock(h.shape[-1], c.dropout, name="mid_block_2")(h, deterministic)
        for i_level in reversed(range(num_levels)):
            for i_block in range(c.num_res_blocks + 1):
                h = ResnetBlock(c.ch * c.ch_mult[i_level], c.dropout,
                                name=f"up_{i_level}_block_{i_block}")(h, deterministic)
                if curr_res in c.attn_resolutions:
                    h = AttnBlock(name=f"up_{i_level}_attn_{i_block}")(h)
            if i_level != 0:
                h = Upsample(h.shape[-1], name=f"up_{i_level}_upsample")(h)
                curr_res *= 2
        h = group_norm("norm_out", h.shape[-1])(h)
        h = swish(h)
        out = nn.Conv(c.out_ch, (3, 3), padding=1, name="conv_out")(h)
        if return_pre_out:
            # h is the conv_out input — the hook the adaptive GAN weight
            # differentiates through (gan.py; taming vqgan.py:78-81 get_last_layer)
            return out, h
        return out


class VQModel(nn.Module):
    """The VQGAN autoencoder: encoder → quant_conv 1×1 → quantizer →
    post_quant_conv 1×1 → decoder (taming/models/vqgan.py:12-74; GumbelVQ
    variant :261-303). Images are NHWC floats in [−1, 1].

    Methods (select with ``method=`` in ``.apply``):
      * ``__call__(img)`` — (recon, vq_loss, indices); GumbelVQ needs a
        ``'gumbel'`` rng and a ``temp``.
      * ``encode(img)`` — VQOutput (quantized latents NHWC, indices, loss).
      * ``get_codebook_indices(img)`` — (b, n) int32 raster-order token ids.
      * ``decode_code(ids)`` — token ids → image (vqgan.py:66-69 +
        dalle_pytorch/vae.py:207-217).
    """
    cfg: VQGANConfig

    def setup(self):
        c = self.cfg
        self.encoder = VQGANEncoder(c, name="encoder")
        self.decoder = VQGANDecoder(c, name="decoder")
        self.codebook = nn.Embed(c.n_embed, c.embed_dim, name="codebook")
        # both variants keep the 1×1 quant_conv (GumbelVQ inherits it from
        # VQModel: encode = encoder → quant_conv → quantize, vqgan.py:55-59)
        self.quant_conv = nn.Conv(c.embed_dim, (1, 1), name="quant_conv")
        if c.quantizer == "gumbel":
            # GumbelQuantize: 1×1 proj to n_embed logits (quantize.py:110-141)
            self.quant_proj = nn.Conv(c.n_embed, (1, 1), name="quant_proj")
        self.post_quant_conv = nn.Conv(c.z_channels, (1, 1), name="post_quant_conv")

    def quantize(self, h, temp: Optional[float] = None,
                 deterministic: bool = True) -> VQOutput:
        c = self.cfg
        z = self.quant_conv(h)
        if c.quantizer == "gumbel":
            logits = self.quant_proj(z)
            hard = c.straight_through if not deterministic else True
            # deterministic eval still evaluates the gumbel path's argmax —
            # a fixed stream makes it reproducible without an rng collection
            key = (self.make_rng("gumbel") if not deterministic
                   else deterministic_key())
            return gumbel_quantize(key, logits, self.codebook.embedding,
                                   tau=1.0 if temp is None else temp,
                                   hard=hard, kl_weight=c.gumbel_kl_weight)
        return vector_quantize(z, self.codebook.embedding, beta=c.beta)

    def encode(self, img, temp: Optional[float] = None,
               deterministic: bool = True) -> VQOutput:
        h = self.encoder(img, deterministic)
        return self.quantize(h, temp=temp, deterministic=deterministic)

    def decode(self, quant, deterministic: bool = True, return_pre_out: bool = False):
        return self.decoder(self.post_quant_conv(quant), deterministic,
                            return_pre_out=return_pre_out)

    def get_codebook_indices(self, img):
        out = self.encode(img, deterministic=True)
        b = out.indices.shape[0]
        ids = out.indices
        if self.cfg.remap_used is not None:
            # restricted-vocab checkpoints (taming quantize.py remap): expose
            # indices in the used subset's id space. taming draws a fresh
            # randint per call for unknown codes; pass a 'remap' rng to get
            # that — without one the fill is a fixed-key (deterministic)
            # pseudo-random assignment, the sane choice for eval tokenization
            key = (self.make_rng("remap") if self.has_rng("remap") else None)
            ids = remap_indices(ids, self.cfg.remap_used,
                                unknown=self.cfg.remap_unknown, key=key)
        return ids.reshape(b, -1)

    def decode_code(self, ids):
        b, n = ids.shape
        hw = int(n ** 0.5)
        if self.cfg.remap_used is not None:
            ids = unmap_indices(ids, self.cfg.remap_used)
        # a second-stage sampler's vocab may exceed n_embed (taming GPT vocab
        # covers cond codes too); clamp instead of XLA's undefined OOB gather
        ids = jnp.clip(ids, 0, self.cfg.n_embed - 1)
        quant = self.codebook(ids).reshape(b, hw, hw, self.cfg.embed_dim)
        return self.decode(quant)

    def health_taps(self, q: VQOutput, temp: Optional[float] = None) -> dict:
        """graftpulse vitals from one encode's :class:`VQOutput`
        (obs/health.py): codebook usage perplexity / dead-code fraction /
        entropy from the quantizer indices, plus — on the gumbel path,
        where ``q.probs`` carries the relaxation distribution — the live
        temperature and the encoder's mean argmax confidence. Pure jnp on
        tensors the step already holds; the VQGAN trainers fuse these into
        their jitted steps when ``ObsConfig.health`` is on."""
        from ..obs.health import HEALTH_PREFIX, codebook_health
        out = codebook_health(q.indices, self.cfg.n_embed)
        if q.probs is not None:
            # health taps are f32 by contract (obs/health.py) — deliberate
            # pin, independent of the compute precision mode
            out[f"{HEALTH_PREFIX}gumbel_temp"] = jnp.asarray(  # graftlint: disable=hardcoded-dtype
                1.0 if temp is None else temp, jnp.float32)
            out[f"{HEALTH_PREFIX}encoder_confidence"] = jnp.mean(
                jnp.max(q.probs.astype(jnp.float32), axis=-1))
        return out

    def __call__(self, img, temp: Optional[float] = None,
                 deterministic: bool = True):
        q = self.encode(img, temp=temp, deterministic=deterministic)
        recon = self.decode(q.quantized, deterministic)
        return recon, q.loss, q.indices

    @property
    def fmap_size(self) -> int:
        return self.cfg.resolution // 2 ** (len(self.cfg.ch_mult) - 1)


def init_vqgan(cfg: VQGANConfig, key: jax.Array, batch: int = 1):
    """Initialize params with a dummy batch. Returns (model, params)."""
    model = VQModel(cfg)
    img = jnp.zeros((batch, cfg.resolution, cfg.resolution, cfg.in_channels),
                    jnp.float32)
    params = model.init({"params": key, "gumbel": key}, img, deterministic=True)
    return model, params
