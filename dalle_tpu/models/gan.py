"""PatchGAN discriminator + VQGAN adversarial loss.

Reference: ``NLayerDiscriminator``/``weights_init``
(dalle_pytorch/taming/modules/discriminator/model.py:8-67), ``ActNorm``
(taming/modules/util.py:10-92), and ``VQLPIPSWithDiscriminator``
(taming/modules/losses/vqperceptual.py:14-136).

TPU redesign: no ``optimizer_idx`` branching — the loss is two pure functions
(``ae_loss`` / ``disc_loss``) that the trainer jits separately, so each step is
one fused XLA program. The adaptive discriminator weight
(vqperceptual.py:63-74: ‖∂nll/∂w_last‖ / ‖∂g/∂w_last‖) is computed with
``jax.grad`` w.r.t. the decoder's ``conv_out`` kernel on a stop-gradiented
pre-output activation — exact parity with torch's ``autograd.grad(...,
last_layer)`` without a second full backward through the decoder.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config import ConfigBase


class ActNorm(nn.Module):
    """Per-channel affine with data-dependent init: loc/scale initialized from
    the first batch's channel mean/std (taming/modules/util.py:10-92; the
    logdet path is unused by the discriminator and omitted)."""

    @nn.compact
    def __call__(self, x):
        # flax runs param init with the concrete first input → data-dependent
        # init falls out of the functional init pass, no "initialized" flag
        # buffer needed (util.py:30-44).
        def loc_init(_key):
            return -jnp.mean(x, axis=(0, 1, 2), keepdims=True)[0]

        def scale_init(_key):
            std = jnp.std(x, axis=(0, 1, 2), keepdims=True)[0]
            return 1.0 / (std + 1e-6)

        loc = self.param("loc", loc_init)
        scale = self.param("scale", scale_init)
        return scale * (x + loc)


def _disc_conv_init(key, shape, dtype=jnp.float32):
    # weights_init: N(0, 0.02) on conv weights (discriminator/model.py:8-12)
    return jax.random.normal(key, shape, dtype) * 0.02


class NLayerDiscriminator(nn.Module):
    """PatchGAN: conv4x4/s2 + LeakyReLU(0.2) stacks with doubling filters
    (capped 8×), norm on all but the first conv, final 1-channel map
    (discriminator/model.py:17-67). ``use_actnorm=False`` → BatchNorm (running
    stats live in a ``batch_stats`` collection)."""
    ndf: int = 64
    n_layers: int = 3
    use_actnorm: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        # below 3*2^n, the stride-2 stack reaches <= 2 and the two stride-1
        # kernel-4/pad-1 convs produce an EMPTY 0x0 map whose mean is
        # silently NaN (poisoning the whole GAN step) — surface the
        # misconfiguration instead. At exactly [3*2^n, 4*2^n) the output is
        # a single 1x1 logit: valid, just not a patch map.
        min_res = 3 * 2 ** self.n_layers
        if x.shape[1] < min_res or x.shape[2] < min_res:
            raise ValueError(
                f"NLayerDiscriminator(n_layers={self.n_layers}) needs inputs "
                f">= {min_res}x{min_res}; got {x.shape[1]}x{x.shape[2]} — "
                "reduce disc_num_layers for small images")

        def norm(name):
            if self.use_actnorm:
                return ActNorm(name=name)
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                epsilon=1e-5, name=name)

        h = nn.Conv(self.ndf, (4, 4), strides=(2, 2), padding=1,
                    kernel_init=_disc_conv_init, name="conv_0")(x)
        h = nn.leaky_relu(h, 0.2)
        nf = 1
        for n in range(1, self.n_layers):
            nf = min(2 ** n, 8)
            h = nn.Conv(self.ndf * nf, (4, 4), strides=(2, 2), padding=1,
                        use_bias=self.use_actnorm, kernel_init=_disc_conv_init,
                        name=f"conv_{n}")(h)
            h = norm(f"norm_{n}")(h)
            h = nn.leaky_relu(h, 0.2)
        nf = min(2 ** self.n_layers, 8)
        h = nn.Conv(self.ndf * nf, (4, 4), strides=(1, 1), padding=1,
                    use_bias=self.use_actnorm, kernel_init=_disc_conv_init,
                    name=f"conv_{self.n_layers}")(h)
        h = norm(f"norm_{self.n_layers}")(h)
        h = nn.leaky_relu(h, 0.2)
        return nn.Conv(1, (4, 4), strides=(1, 1), padding=1,
                       kernel_init=_disc_conv_init, name="conv_out")(h)


def hinge_d_loss(logits_real, logits_fake):
    """0.5·(mean relu(1−real) + mean relu(1+fake)) (vqperceptual.py:20-24)."""
    return 0.5 * (jnp.mean(nn.relu(1.0 - logits_real)) +
                  jnp.mean(nn.relu(1.0 + logits_fake)))


def vanilla_d_loss(logits_real, logits_fake):
    """0.5·(mean softplus(−real) + mean softplus(fake)) (vqperceptual.py:27-31)."""
    return 0.5 * (jnp.mean(jax.nn.softplus(-logits_real)) +
                  jnp.mean(jax.nn.softplus(logits_fake)))


def adopt_weight(weight, global_step, threshold: int = 0, value: float = 0.0):
    """Zero the weight before ``disc_start`` (vqperceptual.py:14-17), as a
    ``jnp.where`` so the step counter can stay traced."""
    return jnp.where(global_step < threshold, value, weight)


@dataclass(frozen=True)
class GANLossConfig(ConfigBase):
    """VQLPIPSWithDiscriminator knobs (vqperceptual.py:34-38 ctor)."""
    disc_start: int = 0
    codebook_weight: float = 1.0
    pixelloss_weight: float = 1.0
    disc_num_layers: int = 3
    disc_ndf: int = 64
    disc_factor: float = 1.0
    disc_weight: float = 0.8
    perceptual_weight: float = 1.0
    use_actnorm: bool = False
    disc_loss: str = "hinge"   # hinge | vanilla
    # which perceptual net backs the LPIPS term: "tiny" (default) loads the
    # repo's shipped in-repo-trained weights (models/data/tiny_perceptual.npz,
    # scripts/train_perceptual.py); "vgg" builds the torchvision-shaped trunk
    # for load_torch_weights import of the reference's vgg.pth (random-init
    # until imported — the round-2 placeholder behavior)
    perceptual_net: str = "tiny"


def _conv_out_apply(h, kernel, bias):
    """Re-apply the decoder's final conv3x3 (VQGANDecoder ``conv_out``) so the
    adaptive weight can differentiate w.r.t. that kernel alone."""
    y = jax.lax.conv_general_dilated(
        h, kernel, window_strides=(1, 1), padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + bias


def adaptive_disc_weight(nll_of_recon, g_of_recon, h_last, conv_out_params,
                         disc_weight: float) -> jnp.ndarray:
    """‖∂nll/∂w_last‖ / (‖∂g/∂w_last‖ + 1e-4), clipped to [0, 1e4], detached,
    × disc_weight (vqperceptual.py:63-74). ``h_last`` is the input to the
    decoder's conv_out; both closures see it stop-gradiented so the extra
    backwards stop at the last layer, exactly like torch ``autograd.grad``."""
    h_sg = jax.lax.stop_gradient(h_last)
    kernel = conv_out_params["kernel"]
    bias = conv_out_params["bias"]

    nll_grad = jax.grad(lambda w: nll_of_recon(_conv_out_apply(h_sg, w, bias)))(kernel)
    g_grad = jax.grad(lambda w: g_of_recon(_conv_out_apply(h_sg, w, bias)))(kernel)
    d_weight = (jnp.linalg.norm(nll_grad.reshape(-1)) /
                (jnp.linalg.norm(g_grad.reshape(-1)) + 1e-4))
    d_weight = jnp.clip(d_weight, 0.0, 1e4)
    return jax.lax.stop_gradient(d_weight) * disc_weight


def bce_loss(logits, targets):
    """Sigmoid BCE, MEAN over all elements — torch
    ``binary_cross_entropy_with_logits`` default, as ``BCELoss`` uses it
    (taming/modules/losses/segmentation.py:4-11)."""
    per = jax.nn.softplus(logits) - logits * targets
    return jnp.mean(per)


def bce_with_quant_loss(logits, targets, codebook_loss,
                        codebook_weight: float = 1.0):
    """``BCELossWithQuant`` (segmentation.py:14-22): BCE + weighted codebook
    term — the loss of the VQSegmentationModel variant (taming vqgan.py:159-222).
    Returns (total, dict of parts)."""
    bce = bce_loss(logits, targets)
    total = bce + codebook_weight * jnp.mean(codebook_loss)
    return total, {"bce_loss": bce, "quant_loss": jnp.mean(codebook_loss)}
