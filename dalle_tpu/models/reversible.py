"""Reformer-style reversible residual execution with O(1) activation memory.

Reference: dalle_pytorch/reversible.py — `ReversibleSequence` duplicates the
channel dim into two streams (:149-157), each block computes y1 = x1 + f(x2),
y2 = x2 + g(y1), and a custom autograd.Function recomputes activations in the
backward pass (:70-124) instead of storing them. The reference also snapshots
and restores CPU+GPU RNG state so dropout replays identically (:20-50).

TPU redesign:
  * One `jax.custom_vjp` over the whole block stack. Forward keeps ONLY the
    final (y1, y2); backward re-derives each block's inputs by *inverting* the
    coupling (x2 = y2 − g(y1), x1 = y1 − f(x2)) and runs per-block `jax.vjp`
    for the parameter/activation cotangents — activation memory is constant in
    depth, the compute cost is one extra forward (same as the reference).
  * No RNG dance: JAX dropout keys are explicit, so a recompute with the same
    key is bit-identical by construction. Dropout works through key replay —
    each block fn carries its (depth-folded) dropout key inside its params
    pytree (Transformer._call_reversible), so the backward recompute draws the
    same masks; grads ≡ naive autodiff with dropout (tests/test_reversible.py).
  * `f`/`g` are pure functions (params pytree, activations) — the flax layers
    are unbound (`Module.unbind()`) by the Transformer before entering here, so
    the custom_vjp boundary sees only pytrees. Shared layers appear as the same
    param tracers in several blocks; JAX sums their cotangents at the fan-out.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

LayerFns = Tuple[Callable[[Any, jnp.ndarray], jnp.ndarray],
                 Callable[[Any, jnp.ndarray], jnp.ndarray]]


def reversible_forward_naive(fns: Sequence[LayerFns], params, x1, x2):
    """Plain autodiff path — the correctness oracle for the custom_vjp
    (gradients flow through stored activations as usual)."""
    for (f, g), (pf, pg) in zip(fns, params):
        x1 = x1 + f(pf, x2)
        x2 = x2 + g(pg, x1)
    return x1, x2


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def reversible_sequence(fns: Tuple[LayerFns, ...], params, x1, x2):
    return reversible_forward_naive(fns, params, x1, x2)


def _rev_fwd(fns, params, x1, x2):
    y1, y2 = reversible_forward_naive(fns, params, x1, x2)
    # residuals: only the outputs + params — NOT per-layer activations
    return (y1, y2), (params, y1, y2)


def _rev_bwd(fns, res, grads):
    params, y1, y2 = res
    d1, d2 = grads
    dparams = []
    for (f, g), (pf, pg) in zip(reversed(fns), reversed(list(params))):
        # recompute g at y1, collect its vjp, invert to x2
        g_out, vjp_g = jax.vjp(g, pg, y1)
        x2 = y2 - g_out
        dpg, dgy1 = vjp_g(d2)
        d1 = d1 + dgy1                       # total cotangent into y1
        # recompute f at x2, collect its vjp, invert to x1
        f_out, vjp_f = jax.vjp(f, pf, x2)
        x1 = y1 - f_out
        dpf, dfx2 = vjp_f(d1)
        d2 = d2 + dfx2                       # total cotangent into x2
        dparams.append((dpf, dpg))
        y1, y2 = x1, x2
    return tuple(reversed(dparams)), d1, d2


reversible_sequence.defvjp(_rev_fwd, _rev_bwd)


def run_reversible(fns: Sequence[LayerFns], params, x, *, naive: bool = False):
    """Duplicate channels into two streams, run the stack, average the streams
    (reference reversible.py:149-157)."""
    x1 = x2 = x
    if naive:
        y1, y2 = reversible_forward_naive(tuple(fns), tuple(params), x1, x2)
    else:
        y1, y2 = reversible_sequence(tuple(fns), tuple(params), x1, x2)
    return (y1 + y2) / 2.0
