"""VAE adapters + the DALLE↔VAE↔CLIP composition.

The reference duck-types its VAEs behind image_size/num_layers/num_tokens/
get_codebook_indices/decode (consumed at dalle_pytorch.py:365-368). Here that
contract is an explicit adapter holding (model, params) pairs, because JAX
models are (pure fn, pytree) — freezing the VAE (reference :386-387) is simply
not differentiating through the adapter.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..config import DalleConfig, DVAEConfig
from .clip import CLIP
from .dalle import DALLE
from .dvae import DiscreteVAE


class VAEAdapter:
    """Duck-typed VAE contract: image_size, num_layers, num_tokens,
    get_codebook_indices(images NHWC float) -> (b, n) int32,
    decode(ids) -> images NHWC float."""

    image_size: int
    num_layers: int
    num_tokens: int

    def get_codebook_indices(self, images):  # pragma: no cover - interface
        raise NotImplementedError

    def decode(self, ids):  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def image_fmap_size(self) -> int:
        return self.image_size // (2 ** self.num_layers)


class DiscreteVAEAdapter(VAEAdapter):
    def __init__(self, model: DiscreteVAE, params):
        self.model = model
        self.params = jax.lax.stop_gradient(params)
        cfg = model.cfg
        self.image_size = cfg.image_size
        self.num_layers = cfg.num_layers
        self.num_tokens = cfg.num_tokens
        self._encode = jax.jit(lambda p, x: model.apply(
            p, x, method=DiscreteVAE.get_codebook_indices))
        self._decode = jax.jit(lambda p, ids: model.apply(
            p, ids, method=DiscreteVAE.decode))

    def get_codebook_indices(self, images):
        return self._encode(self.params, images)

    def decode(self, ids):
        return self._decode(self.params, ids)


def dalle_config_for_vae(vae: VAEAdapter, **dalle_kwargs) -> DalleConfig:
    """Derive the image-side config fields from the vae, as the reference ctor
    does (dalle_pytorch.py:365-368)."""
    return DalleConfig(
        image_size=vae.image_size,
        image_vocab_size=vae.num_tokens,
        image_fmap_size=vae.image_fmap_size,
        **dalle_kwargs)


@dataclass
class DalleWithVae:
    """Raw-pixel interface around DALLE: tokenizes images through the frozen vae
    on the way in, decodes generated tokens to pixels on the way out, optional
    CLIP rerank (reference DALLE.forward :590-597 / generate_images :548-555)."""
    model: DALLE
    params: Any
    vae: VAEAdapter

    def loss(self, text, images, key=None, null_cond_prob: float = 0.0,
             deterministic: bool = True):
        ids = self.vae.get_codebook_indices(images)
        rngs = {}
        if null_cond_prob > 0 and key is not None:
            rngs["cfg"] = key
        out, aux = self.model.apply(self.params, text, ids, return_loss=True,
                                    null_cond_prob=null_cond_prob,
                                    deterministic=deterministic,
                                    rngs=rngs or None)
        return out, aux

    def generate_images(self, text, key, *, filter_thres: float = 0.5,
                        temperature: float = 1.0, cond_scale: float = 1.0,
                        img: Optional[jnp.ndarray] = None,
                        num_init_img_tokens: Optional[int] = None,
                        clip: Optional[tuple] = None,
                        precision: str = "float32"):
        """text (b, text_seq_len) → images (b, H, W, C) in [0,1]; optionally
        (images, clip_scores). ``img`` primes the first 43.75% of image tokens
        (reference :510-519, OpenAI's 14/32 rows). ``precision="bfloat16"``
        runs the decode loop with bf16 weights + KV cache — the loop is
        bandwidth-bound on both, so this roughly halves latency;
        ``precision="bf16_int8kv"`` additionally quantizes the KV cache to
        int8 with per-position scales (1.44x faster again at batch 64 on
        v5e, quantization noise well under sampling temperature); sampling
        stays on f32 logits in every mode."""
        prime = None
        if img is not None:
            n_prime = num_init_img_tokens
            if n_prime is None:
                n_prime = int(0.4375 * self.model.cfg.image_seq_len)
            assert n_prime < self.model.cfg.image_seq_len
            prime = self.vae.get_codebook_indices(img)[:, :n_prime]
        if precision not in ("float32", "f32", "bfloat16", "bf16",
                             "bf16_int8kv"):
            # a typo would otherwise fall through to the ~3x-slower f32 path
            # with no signal that the requested fast mode never engaged
            raise ValueError(f"unknown precision {precision!r}; expected "
                             "float32 | bfloat16 | bf16_int8kv")
        params, cache_dtype = self.params, jnp.float32
        if precision in ("bfloat16", "bf16", "bf16_int8kv"):
            # cast once and cache — re-casting the full tree per call would
            # serialize GBs of casts ahead of every batch's decode loop. The
            # cache keeps the source tree object and compares identity, so a
            # checkpoint reload / EMA swap on the same wrapper recasts instead
            # of reusing stale weights
            cached = getattr(self, "_bf16_params", None)
            if cached is None or cached[0] is not self.params:
                from ..train.train_state import cast_floating
                object.__setattr__(self, "_bf16_params",
                                   (self.params,
                                    cast_floating(self.params, jnp.bfloat16)))
            params = self._bf16_params[1]
            cache_dtype = (jnp.int8 if precision == "bf16_int8kv"
                           else jnp.bfloat16)
        ids = self.model.apply(
            params, text, key, filter_thres=filter_thres,
            temperature=temperature, cond_scale=cond_scale, image_prime=prime,
            cache_dtype=cache_dtype,
            method=DALLE.generate_images_tokens)
        images = self.vae.decode(ids)
        if clip is not None:
            clip_model, clip_params = clip
            # pad-remapped ids exceed CLIP's text vocab; zero them back to pad
            clip_text = jnp.where(text >= clip_model.cfg.num_text_tokens, 0, text)
            # CLIP may use a different text context than DALLE — crop or pad
            # (an out-of-range position gather would fill with NaN)
            n = clip_model.cfg.text_seq_len
            if clip_text.shape[1] > n:
                clip_text = clip_text[:, :n]
            elif clip_text.shape[1] < n:
                clip_text = jnp.pad(clip_text,
                                    ((0, 0), (0, n - clip_text.shape[1])))
            scores = clip_model.apply(clip_params, clip_text, images)
            return images, scores
        return images

    def generate_texts(self, key, text=None, *, batch: int = 1,
                       filter_thres: float = 0.5, temperature: float = 1.0):
        return self.model.apply(self.params, key, text, batch=batch,
                                filter_thres=filter_thres, temperature=temperature,
                                method=DALLE.generate_texts_tokens)
