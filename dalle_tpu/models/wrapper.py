"""VAE adapters + the DALLE↔VAE↔CLIP composition.

The reference duck-types its VAEs behind image_size/num_layers/num_tokens/
get_codebook_indices/decode (consumed at dalle_pytorch.py:365-368). Here that
contract is an explicit adapter holding (model, params) pairs, because JAX
models are (pure fn, pytree) — freezing the VAE (reference :386-387) is simply
not differentiating through the adapter.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..config import DalleConfig, DVAEConfig
from ..obs import counter_add, gauge_set, span
from ..obs import enabled as _obs_enabled
from .clip import CLIP
from .dalle import DALLE
from .dvae import DiscreteVAE


class VAEAdapter:
    """Duck-typed VAE contract: image_size, num_layers, num_tokens,
    get_codebook_indices(images NHWC float) -> (b, n) int32,
    decode(ids) -> images NHWC float."""

    image_size: int
    num_layers: int
    num_tokens: int

    def get_codebook_indices(self, images):  # pragma: no cover - interface
        raise NotImplementedError

    def decode(self, ids):  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def image_fmap_size(self) -> int:
        return self.image_size // (2 ** self.num_layers)


class DiscreteVAEAdapter(VAEAdapter):
    def __init__(self, model: DiscreteVAE, params):
        self.model = model
        self.params = jax.lax.stop_gradient(params)
        cfg = model.cfg
        self.image_size = cfg.image_size
        self.num_layers = cfg.num_layers
        self.num_tokens = cfg.num_tokens
        self._encode = jax.jit(lambda p, x: model.apply(
            p, x, method=DiscreteVAE.get_codebook_indices))
        self._decode = jax.jit(lambda p, ids: model.apply(
            p, ids, method=DiscreteVAE.decode))

    def get_codebook_indices(self, images):
        return self._encode(self.params, images)

    def decode(self, ids):
        return self._decode(self.params, ids)


def dalle_config_for_vae(vae: VAEAdapter, **dalle_kwargs) -> DalleConfig:
    """Derive the image-side config fields from the vae, as the reference ctor
    does (dalle_pytorch.py:365-368)."""
    return DalleConfig(
        image_size=vae.image_size,
        image_vocab_size=vae.num_tokens,
        image_fmap_size=vae.image_fmap_size,
        **dalle_kwargs)


@dataclass
class DalleWithVae:
    """Raw-pixel interface around DALLE: tokenizes images through the frozen vae
    on the way in, decodes generated tokens to pixels on the way out, optional
    CLIP rerank (reference DALLE.forward :590-597 / generate_images :548-555)."""
    model: DALLE
    params: Any
    vae: VAEAdapter
    # optional CLIP reranker: (CLIP module, params). Attached once (ctor or
    # ``attach_rerank``), consumed by ``generate_images(clip=...)`` callers
    # and by the serving product loop (``image_pipeline`` — the /v1/images
    # rerank stage). Kept as data, not a submodule: the reranker is frozen
    # at serve time exactly like the vae.
    clip: Any = None

    def attach_rerank(self, clip_model, clip_params) -> "DalleWithVae":
        """Attach a CLIP reranker after construction (e.g. loaded from a
        checkpoint via ``models.clip.load_clip`` — no training imports
        needed). Returns self for chaining."""
        object.__setattr__(self, "clip", (clip_model, clip_params))
        return self

    def image_pipeline(self, *, top_k: Optional[int] = None, **kw):
        """The post-decode product pipeline (serve/pipeline.py): batched
        dVAE pixel decode + batched CLIP rerank + top-k ordering over
        finished candidate groups. Built from this wrapper's vae and
        attached reranker; the gateway's /v1/images endpoint drives it."""
        from ..serve.pipeline import ImagePipeline
        clip_model, clip_params = self.clip if self.clip else (None, None)
        return ImagePipeline(vae=self.vae, clip=clip_model,
                             clip_params=clip_params, top_k=top_k, **kw)

    def loss(self, text, images, key=None, null_cond_prob: float = 0.0,
             deterministic: bool = True):
        ids = self.vae.get_codebook_indices(images)
        rngs = {}
        if null_cond_prob > 0 and key is not None:
            rngs["cfg"] = key
        out, aux = self.model.apply(self.params, text, ids, return_loss=True,
                                    null_cond_prob=null_cond_prob,
                                    deterministic=deterministic,
                                    rngs=rngs or None)
        return out, aux

    def _resolve_precision(self, precision: str):
        """(params, cache_dtype) for a decode precision mode. Casts/
        quantizes once and caches — re-transforming the full tree per call
        would serialize GBs of work ahead of every batch's decode loop. The
        cache keys on (source tree identity, mode), so a checkpoint reload /
        EMA swap on the same wrapper re-derives instead of reusing stale
        weights. Shared by ``generate_images`` and ``serve_engine``."""
        if precision not in ("float32", "f32", "bfloat16", "bf16",
                             "bf16_int8kv", "int8w"):
            # a typo would otherwise fall through to the ~3x-slower f32 path
            # with no signal that the requested fast mode never engaged
            raise ValueError(f"unknown precision {precision!r}; expected "
                             "float32 | bfloat16 | bf16_int8kv | int8w")
        params, cache_dtype = self.params, jnp.float32
        if precision in ("bfloat16", "bf16", "bf16_int8kv", "int8w"):
            mode = "int8w" if precision == "int8w" else "bf16"
            cache = getattr(self, "_fast_params", None)
            if cache is None or cache[0] is not self.params:
                # source tree changed (checkpoint reload / EMA swap): drop
                # every derived mode
                cache = (self.params, {})
                object.__setattr__(self, "_fast_params", cache)
            if mode not in cache[1]:
                if mode == "int8w":
                    # int8 matmul kernels + int8 shared table, everything
                    # else bf16 (ops/quantize_weights.py)
                    from ..ops.quantize_weights import quantize_params_int8
                    cache[1][mode] = quantize_params_int8(self.params)
                else:
                    from ..train.train_state import cast_floating
                    cache[1][mode] = cast_floating(self.params, jnp.bfloat16)
            params = cache[1][mode]
            cache_dtype = (jnp.int8 if precision in ("bf16_int8kv", "int8w")
                           else jnp.bfloat16)
        return params, cache_dtype

    def serve_engine(self, *, slots: int, precision: str = "int8w",
                     filter_thres: float = 0.5, temperature: float = 1.0,
                     topk_approx: bool = False, steps_per_sync: int = 1,
                     use_kernel=None, decode_health: bool = False,
                     prefill_chunk: int = 0, kv_block_tokens: int = 0,
                     kv_pool_blocks=None, radix_cache: bool = True):
        """Continuous-batching decode engine over this wrapper's model —
        the serving-side sibling of ``generate_images``. ``slots`` is the
        fixed device batch; precision modes are the same fast paths
        (bf16 / bf16_int8kv / int8w reuse the wrapper's cached derived
        params).

        The DEFAULT is ``int8w``: int8 matmul kernels + int8 tied table
        (per-channel scales, ops/quantize_weights.py) unified with the
        int8 KV cache — decode is bandwidth-bound on exactly those two
        streams, so this is the minimum-HBM serving configuration
        (scripts/eval_decode_precisions.py reports the bytes-per-token
        ledger). The quantized program is certified by the graftnum
        precision audit (analysis/precision_flow.py; the serve_decode /
        serve_refill graftir entries pin its boundary map), and per-request
        tokens remain BIT-exact against same-precision single-request
        generation (tests/test_serve.py). Pass ``precision="float32"`` for
        the full-width engine.

        The engine emits image TOKEN ids per completed request
        (``dalle_tpu.serve.CompletedRequest``); decode pixels with
        ``self.vae.decode(tokens[None])`` as needed — serving keeps the
        dVAE off the per-token critical path."""
        from ..serve.engine import DecodeEngine
        params, cache_dtype = self._resolve_precision(precision)
        return DecodeEngine(self.model, params, slots=slots,
                            cache_dtype=cache_dtype,
                            filter_thres=filter_thres,
                            temperature=temperature,
                            topk_approx=topk_approx,
                            steps_per_sync=steps_per_sync,
                            use_kernel=use_kernel,
                            decode_health=decode_health,
                            prefill_chunk=prefill_chunk,
                            kv_block_tokens=kv_block_tokens,
                            kv_pool_blocks=kv_pool_blocks,
                            radix_cache=radix_cache)

    def generate_images(self, text, key, *, filter_thres: float = 0.5,
                        temperature: float = 1.0, cond_scale: float = 1.0,
                        img: Optional[jnp.ndarray] = None,
                        num_init_img_tokens: Optional[int] = None,
                        clip: Optional[tuple] = None,
                        precision: str = "float32",
                        topk_approx: bool = False,
                        speculative: int = 0,
                        draft: str = "row"):
        """text (b, text_seq_len) → images (b, H, W, C) in [0,1]; optionally
        (images, clip_scores). ``img`` primes the first 43.75% of image tokens
        (reference :510-519, OpenAI's 14/32 rows). ``precision="bfloat16"``
        runs the decode loop with bf16 weights + KV cache — the loop is
        bandwidth-bound on both, so this roughly halves latency;
        ``precision="bf16_int8kv"`` additionally quantizes the KV cache to
        int8 with per-position scales (1.44x faster again at batch 64 on
        v5e, quantization noise well under sampling temperature);
        ``precision="int8w"`` further stores every matmul kernel (and the
        tied table) as int8 with per-channel scales, halving decode weight
        traffic (ops/quantize_weights.py). ``topk_approx`` swaps the exact
        per-step top-k sort for TPU's approximate top-k unit
        (ops/sampling.top_k_filter). Sampling stays on f32 logits in every
        mode; token-exact accuracy on a trained model is validated per mode
        by scripts/eval_decode_precisions.py.

        ``speculative=γ > 0`` decodes via the draft-and-verify sampler
        (DALLE.generate_images_tokens_speculative — measured p50 0.366 →
        0.281 s at b64/γ=2 on a trained model, sampling exact for any draft
        quality); requires cond_scale == 1.0 and no image priming, and uses
        a per-(step, row) key stream (same distribution as the sequential
        loop, different bits)."""
        prime = None
        if img is not None:
            n_prime = num_init_img_tokens
            if n_prime is None:
                n_prime = int(0.4375 * self.model.cfg.image_seq_len)
            assert n_prime < self.model.cfg.image_seq_len
            with span("decode/vae_encode_prime"):
                prime = self.vae.get_codebook_indices(img)[:, :n_prime]
        params, cache_dtype = self._resolve_precision(precision)
        n_new = self.model.cfg.image_seq_len - (prime.shape[1]
                                                if prime is not None else 0)
        with span("decode/generate_tokens", tokens=int(n_new),
                  batch=int(text.shape[0]), precision=precision) as dec_span:
            if speculative > 0:
                if cond_scale != 1.0 or prime is not None:
                    # not an assert: -O must not silently drop the user's CFG
                    raise ValueError(
                        "speculative decode supports cond_scale=1.0 and no "
                        "image priming (CFG would need a second verified "
                        "window per round)")
                ids = self.model.apply(
                    params, text, key, gamma=speculative, draft=draft,
                    filter_thres=filter_thres, temperature=temperature,
                    cache_dtype=cache_dtype, topk_approx=topk_approx,
                    method=DALLE.generate_images_tokens_speculative)
            else:
                ids = self.model.apply(
                    params, text, key, filter_thres=filter_thres,
                    temperature=temperature, cond_scale=cond_scale,
                    image_prime=prime, cache_dtype=cache_dtype,
                    topk_approx=topk_approx,
                    method=DALLE.generate_images_tokens)
            if _obs_enabled():
                # the decode program is async-dispatched; without the sync
                # the span would time the dispatch, not the tokens
                ids = jax.block_until_ready(ids)
        if dec_span.duration is not None and n_new > 0:
            # per-token latency — the serving-side number that decides
            # batch size and speculative-γ (scripts/obs_report.py surfaces
            # the gauge; see docs/OBSERVABILITY.md)
            gauge_set("obs.decode_per_token_ms",
                      dec_span.duration * 1e3 / n_new)
            counter_add("obs.decode_tokens_total",
                        float(n_new * text.shape[0]))
        with span("decode/vae_decode"):
            images = self.vae.decode(ids)
        if clip is not None:
            clip_model, clip_params = clip
            # pad-remapped ids exceed CLIP's text vocab; zero them back to pad
            clip_text = jnp.where(text >= clip_model.cfg.num_text_tokens, 0, text)
            # CLIP may use a different text context than DALLE — crop or pad
            # (an out-of-range position gather would fill with NaN)
            n = clip_model.cfg.text_seq_len
            if clip_text.shape[1] > n:
                clip_text = clip_text[:, :n]
            elif clip_text.shape[1] < n:
                clip_text = jnp.pad(clip_text,
                                    ((0, 0), (0, n - clip_text.shape[1])))
            with span("decode/clip_rerank"):
                scores = clip_model.apply(clip_params, clip_text, images)
            return images, scores
        return images

    def generate_texts(self, key, text=None, *, batch: int = 1,
                       filter_thres: float = 0.5, temperature: float = 1.0):
        return self.model.apply(self.params, key, text, batch=batch,
                                filter_thres=filter_thres, temperature=temperature,
                                method=DALLE.generate_texts_tokens)
