from .dvae import DiscreteVAE, init_dvae
