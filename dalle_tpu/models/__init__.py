from .dvae import DiscreteVAE, init_dvae
from .vqgan import VQModel, VQGANEncoder, VQGANDecoder, init_vqgan
from .gan import (GANLossConfig, NLayerDiscriminator, ActNorm, hinge_d_loss,
                  vanilla_d_loss, adopt_weight, adaptive_disc_weight)
from .lpips import LPIPS, init_lpips
from .mingpt import GPT, GPTConfig, GPTBlock, init_gpt, make_sampler
from .cond_transformer import Net2NetTransformer, CoordStage, SOSProvider
from .pretrained import (OpenAIDiscreteVAE, VQGanVAE, OpenAIEncoder,
                         OpenAIDecoder, map_pixels, unmap_pixels, download,
                         convert_vqgan_state, vqgan_config_from_yaml)
