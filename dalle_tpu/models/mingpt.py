"""minGPT-style decoder-only transformer — the taming second-stage AR model.

Reference: taming/modules/transformer/mingpt.py — ``GPT`` (:125-212: token +
learned position embeddings, pre-LN blocks with GELU MLPs, unbiased head),
``CausalSelfAttention`` with an ``n_unmasked`` always-visible prefix (:42-95),
and the sampling utilities ``sample``/``sample_with_past`` (:292-351).

TPU redesign: the cached sampling loop is a ``lax.scan`` over a preallocated
``KVCache`` pytree (ops/attention.py) — one compiled program for the whole
generation instead of the reference's per-step Python loop with growing
``layer_past`` concats. The n_unmasked prefix is folded into the static mask.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..config import ConfigBase
from ..ops.attention import KVCache, attend, cached_attend
from ..ops.quantize_weights import assert_float_params
from ..ops.sampling import gumbel_sample


@dataclass(frozen=True)
class GPTConfig(ConfigBase):
    """mingpt.py GPTConfig/GPT1Config (:21-39) as a typed config."""
    vocab_size: int = 512
    block_size: int = 512
    n_layer: int = 12
    n_head: int = 8
    n_embd: int = 256
    embd_pdrop: float = 0.0
    resid_pdrop: float = 0.0
    attn_pdrop: float = 0.0
    n_unmasked: int = 0


def _prefix_causal_mask(n: int, n_unmasked: int) -> np.ndarray:
    """Lower-triangular mask with the first ``n_unmasked`` key columns fully
    visible (mingpt.py:57-61)."""
    mask = np.tril(np.ones((n, n), bool))
    if n_unmasked > 0:
        mask[:, :n_unmasked] = True
    return mask


class GPTBlock(nn.Module):
    """x += attn(ln1(x)); x += mlp(ln2(x)) with a 4× GELU MLP
    (mingpt.py:98-122)."""
    cfg: GPTConfig

    def setup(self):
        c = self.cfg
        self.ln1 = nn.LayerNorm(name="ln1")
        self.ln2 = nn.LayerNorm(name="ln2")
        self.qkv = nn.Dense(3 * c.n_embd, name="qkv")
        self.attn_out = nn.Dense(c.n_embd, name="attn_out")
        self.mlp_in = nn.Dense(4 * c.n_embd, name="mlp_in")
        self.mlp_out = nn.Dense(c.n_embd, name="mlp_out")
        self.attn_drop = nn.Dropout(c.attn_pdrop)
        self.resid_drop = nn.Dropout(c.resid_pdrop)

    def _split_heads(self, t):
        b, n, _ = t.shape
        return t.reshape(b, n, self.cfg.n_head, -1).transpose(0, 2, 1, 3)

    def __call__(self, x, mask: Optional[jnp.ndarray] = None,
                 deterministic: bool = True):
        h = self.ln1(x)
        q, k, v = jnp.split(self.qkv(h), 3, axis=-1)
        q, k, v = map(self._split_heads, (q, k, v))
        out = attend(q, k, v, causal=mask is None, static_mask=mask)
        b, nh, n, hd = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, n, nh * hd)
        x = x + self.resid_drop(self.attn_out(out), deterministic=deterministic)
        h = self.ln2(x)
        h = self.mlp_out(jax.nn.gelu(self.mlp_in(h)))
        return x + self.resid_drop(h, deterministic=deterministic)

    def decode_step(self, x, cache: KVCache, length) -> Tuple[jnp.ndarray, KVCache]:
        """Single-token cached step: x (b, 1, d)."""
        h = self.ln1(x)
        q, k, v = jnp.split(self.qkv(h), 3, axis=-1)
        q, k, v = map(self._split_heads, (q, k, v))
        cache = cache.append(k, v, length - 1)
        out = cached_attend(q, cache, length)
        b, nh, n, hd = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, n, nh * hd)
        x = x + self.attn_out(out)
        h = self.ln2(x)
        return x + self.mlp_out(jax.nn.gelu(self.mlp_in(h))), cache


class GPT(nn.Module):
    """Token + learned positional embeddings → blocks → LayerNorm → unbiased
    vocab head (mingpt.py:125-181). ``embeddings`` are optional pre-computed
    vectors prepended to the token embeddings (:156-160)."""
    cfg: GPTConfig

    def setup(self):
        c = self.cfg
        self.tok_emb = nn.Embed(c.vocab_size, c.n_embd, name="tok_emb")
        self.pos_emb = self.param(
            "pos_emb", nn.initializers.normal(0.02), (1, c.block_size, c.n_embd))
        self.drop = nn.Dropout(c.embd_pdrop)
        self.blocks = [GPTBlock(c, name=f"block_{i}") for i in range(c.n_layer)]
        self.ln_f = nn.LayerNorm(name="ln_f")
        self.head = nn.Dense(c.vocab_size, use_bias=False, name="head")

    def _mask(self, n: int):
        return jnp.asarray(_prefix_causal_mask(self.cfg.block_size,
                                               self.cfg.n_unmasked))[:n, :n]

    def __call__(self, idx, embeddings: Optional[jnp.ndarray] = None,
                 deterministic: bool = True):
        assert_float_params(self)
        x = self.tok_emb(idx)
        if embeddings is not None:
            x = jnp.concatenate([embeddings, x], axis=1)
        n = x.shape[1]
        assert n <= self.cfg.block_size, "sequence longer than block_size"
        x = self.drop(x + self.pos_emb[:, :n], deterministic=deterministic)
        mask = self._mask(n)
        for blk in self.blocks:
            x = blk(x, mask=mask, deterministic=deterministic)
        return self.head(self.ln_f(x))

    # -- cached decode (sample_with_past equivalent, mingpt.py:318-351) -----
    def init_cache(self, batch: int) -> Tuple[KVCache, ...]:
        c = self.cfg
        return tuple(KVCache.init(batch, c.n_head, c.block_size,
                                  c.n_embd // c.n_head) for _ in range(c.n_layer))

    def decode_one(self, token, pos, cache):
        """token: (b, 1) int32; pos: scalar position of this token.
        Returns (logits (b, vocab), new cache)."""
        assert_float_params(self)
        x = self.tok_emb(token)
        x = x + jax.lax.dynamic_slice_in_dim(self.pos_emb, pos, 1, axis=1)
        new_cache = []
        for blk, c in zip(self.blocks, cache):
            x, c = blk.decode_step(x, c, pos + 1)
            new_cache.append(c)
        return self.head(self.ln_f(x))[:, 0], tuple(new_cache)

    def prefill(self, idx, cache):
        """Run the prompt through the cache one layer at a time (full-sequence
        matmuls, not a scan): returns (logits of last position, cache, length)."""
        assert_float_params(self)
        x = self.tok_emb(idx)
        n = x.shape[1]
        x = x + self.pos_emb[:, :n]
        mask = self._mask(n)
        new_cache = []
        for blk, c in zip(self.blocks, cache):
            h = blk.ln1(x)
            q, k, v = jnp.split(blk.qkv(h), 3, axis=-1)
            q, k, v = map(blk._split_heads, (q, k, v))
            c = c.append(k, v, 0)
            out = attend(q, k, v, causal=False, static_mask=mask)
            b, nh, nn_, hd = out.shape
            out = out.transpose(0, 2, 1, 3).reshape(b, nn_, nh * hd)
            x = x + blk.attn_out(out)
            h2 = blk.ln2(x)
            x = x + blk.mlp_out(jax.nn.gelu(blk.mlp_in(h2)))
            new_cache.append(c)
        return self.head(self.ln_f(x))[:, -1], tuple(new_cache), n


def init_gpt(cfg: GPTConfig, key: jax.Array, batch: int = 1):
    model = GPT(cfg)
    idx = jnp.zeros((batch, min(4, cfg.block_size)), jnp.int32)
    params = model.init({"params": key}, idx)
    return model, params


def make_sampler(model: GPT, steps: int, *, top_k: Optional[int] = None,
                 temperature: float = 1.0, vocab_limit: Optional[int] = None):
    """jit-once AR sampler: (params, prompt (b, n), key) → (b, n+steps).
    The whole loop is one ``lax.scan`` over the preallocated cache — the
    TPU-idiomatic ``sample_with_past`` (mingpt.py:318-351). ``vocab_limit``
    masks ids ≥ limit so a GPT whose vocab also covers cond tokens can never
    emit them into generated positions."""

    @jax.jit
    def sample(params, prompt, key):
        batch, n_prompt = prompt.shape
        assert n_prompt + steps <= model.cfg.block_size, (
            f"prompt {n_prompt} + steps {steps} exceeds block_size "
            f"{model.cfg.block_size}")
        cache = model.init_cache(batch)
        logits, cache, n0 = model.apply(params, prompt, cache,
                                        method=GPT.prefill)

        def pick(logits, k):
            if vocab_limit is not None:
                logits = jnp.where(jnp.arange(logits.shape[-1]) < vocab_limit,
                                   logits, -jnp.inf)
            if top_k is not None:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return gumbel_sample(k, logits, temperature=temperature)

        def body(carry, i):
            logits, cache, key = carry
            key, sub = jax.random.split(key)
            tok = pick(logits, sub).astype(jnp.int32)
            next_logits, cache = model.apply(params, tok[:, None], n0 + i,
                                             cache, method=GPT.decode_one)
            return (next_logits, cache, key), tok

        (_, _, _), toks = jax.lax.scan(body, (logits, cache, key),
                                       jnp.arange(steps))
        return jnp.concatenate([prompt, toks.T], axis=1)

    return sample
