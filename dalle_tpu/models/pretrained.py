"""Pretrained image-tokenizer import: OpenAI discrete VAE and taming VQGAN.

Reference: dalle_pytorch/vae.py — ``download`` with root-worker-only fetch +
local-barrier coordination (:53-94), ``map_pixels``/``unmap_pixels`` ε=0.1
(:47-51), ``OpenAIDiscreteVAE`` (:97-130: encoder/decoder pkl from the OpenAI
CDN, argmax indices, one-hot → decoder → sigmoid → unmap, fixed attrs
num_layers=3 / image_size=256 / num_tokens=8192) and ``VQGanVAE`` (:133-220:
taming ckpt + OmegaConf yaml, [−1,1] mapping, Gumbel-vs-VQ detection,
``num_layers = log2(resolution / attn_resolution)``).

TPU redesign: instead of unpickling torch ``nn.Module``s and running them on
host (useless on TPU), both architectures are native flax modules here and the
torch checkpoints are converted tensor-by-tensor into the flax param trees
(OIHW→HWIO transposes, norm weight→scale renames). Conversion is host-side
numpy; nothing torch touches the device. With no network egress the loaders
work from a local cache dir and fail with an actionable message otherwise.
"""

from __future__ import annotations

import os
import urllib.request
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..config import VQGANConfig
from ..utils.misc import deterministic_key
from .vqgan import VQModel
from .wrapper import VAEAdapter

CACHE_PATH = os.path.expanduser("~/.cache/dalle")

OPENAI_VAE_ENCODER_URL = "https://cdn.openai.com/dall-e/encoder.pkl"
OPENAI_VAE_DECODER_URL = "https://cdn.openai.com/dall-e/decoder.pkl"
VQGAN_VAE_URL = "https://heibox.uni-heidelberg.de/f/140747ba53464f49b476/?dl=1"
VQGAN_VAE_CONFIG_URL = "https://heibox.uni-heidelberg.de/f/6ecf2af6c658432c8298/?dl=1"


def map_pixels(x, eps: float = 0.1):
    """[0,1] → [ε, 1−ε] (logit-laplace domain, reference vae.py:47-48)."""
    return (1 - 2 * eps) * x + eps


def unmap_pixels(x, eps: float = 0.1):
    """Inverse of map_pixels with clamping (reference vae.py:50-51)."""
    return jnp.clip((x - eps) / (1 - 2 * eps), 0.0, 1.0)


def download(url: str, filename: Optional[str] = None, root: str = CACHE_PATH,
             backend=None) -> str:
    """Cached download with the reference's distributed protocol (vae.py:53-94):
    only the local root worker downloads; everyone else waits at the barrier
    then reads the cached file."""
    filename = filename or os.path.basename(url)
    path = os.path.join(root, filename)
    is_root = backend is None or backend.is_local_root_worker()
    err: Optional[Exception] = None
    if is_root and not os.path.exists(path):
        os.makedirs(root, exist_ok=True)
        try:
            urllib.request.urlretrieve(url, path + ".tmp")
            os.replace(path + ".tmp", path)
        except Exception as e:      # noqa: BLE001 - surfaced after the barrier
            err = e
    # every process passes the barrier exactly once, regardless of cache state
    # (a cache-hit early-return would deadlock hosts with cold caches)
    if backend is not None:
        backend.local_barrier()
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        f"cannot fetch {url} (offline?). Place the file manually at {path} "
        f"and retry.") from err


def _t(x) -> np.ndarray:
    """torch tensor / array → numpy."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def conv_kernel(w) -> np.ndarray:
    """torch conv OIHW → flax HWIO."""
    return _t(w).transpose(2, 3, 1, 0)


# ---------------------------------------------------------------------------
# OpenAI discrete VAE — native architecture (mirrors openai/DALL-E enc/dec)
# ---------------------------------------------------------------------------

class _OpenAIBlock(nn.Module):
    """Residual block: relu→conv3 ×3 → relu→conv1, with a 1×1 identity path
    when channels change (openai/DALL-E EncoderBlock/DecoderBlock)."""
    n_out: int

    @nn.compact
    def __call__(self, x):
        n_hid = self.n_out // 4
        h = nn.Conv(n_hid, (3, 3), padding=1, name="conv_1")(nn.relu(x))
        h = nn.Conv(n_hid, (3, 3), padding=1, name="conv_2")(nn.relu(h))
        h = nn.Conv(n_hid, (3, 3), padding=1, name="conv_3")(nn.relu(h))
        h = nn.Conv(self.n_out, (1, 1), name="conv_4")(nn.relu(h))
        if x.shape[-1] != self.n_out:
            x = nn.Conv(self.n_out, (1, 1), name="id_path")(x)
        return x + h


class OpenAIEncoder(nn.Module):
    """conv7 input → 4 groups of residual blocks with 2× maxpool between →
    relu + 1×1 to vocab logits. group_count=4 is what makes the published
    model's num_layers=3 (8× downsample; reference vae.py:111-113)."""
    n_hid: int = 256
    n_blk_per_group: int = 2
    vocab_size: int = 8192

    @nn.compact
    def __call__(self, x):
        mults = (1, 1, 2, 4, 8)
        h = nn.Conv(self.n_hid, (7, 7), padding=3, name="input")(x)
        for g in range(1, 5):
            for b in range(1, self.n_blk_per_group + 1):
                h = _OpenAIBlock(self.n_hid * mults[g],
                                 name=f"group_{g}_block_{b}")(h)
            if g < 4:
                h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = nn.Conv(self.vocab_size, (1, 1), name="output")(nn.relu(h))
        return h


class OpenAIDecoder(nn.Module):
    """1×1 input from vocab one-hots → 4 groups with nearest 2× upsample
    between → relu + 1×1 to 2×channels (logit-laplace mean+logscale)."""
    n_hid: int = 256
    n_init: int = 128
    n_blk_per_group: int = 2
    out_channels: int = 3

    @nn.compact
    def __call__(self, z):
        mults = (0, 8, 4, 2, 1)
        h = nn.Conv(self.n_init, (1, 1), name="input")(z)
        for g in range(1, 5):
            for b in range(1, self.n_blk_per_group + 1):
                h = _OpenAIBlock(self.n_hid * mults[g],
                                 name=f"group_{g}_block_{b}")(h)
            if g < 4:
                bsz, hh, ww, cc = h.shape
                h = jax.image.resize(h, (bsz, hh * 2, ww * 2, cc), "nearest")
        h = nn.Conv(2 * self.out_channels, (1, 1), name="output")(nn.relu(h))
        return h


def _convert_openai_state(state: Dict[str, Any], params) -> Any:
    """Map an openai/DALL-E state_dict (keys ``blocks.group_k.block_j.
    res_path.conv_i.{w,b}``-style, from the CDN pkl's .state_dict()) onto the
    flax tree. Unknown keys are ignored; missing ones keep their random init."""
    p = jax.device_get(params)
    flat = {}
    for k, v in state.items():
        parts = k.replace("blocks.", "").split(".")
        flat[tuple(parts)] = v
    tree = p["params"]

    def set_conv(mod: dict, w_key, b_key):
        if w_key in flat:
            mod["kernel"] = conv_kernel(flat[w_key])
        if b_key in flat:
            b = _t(flat[b_key])
            mod["bias"] = b.reshape(-1)

    set_conv(tree.get("input", {}), ("input", "w"), ("input", "b"))
    if "output" in tree:
        # encoder: blocks.output.conv ; decoder: blocks.output.conv
        for cand in (("output", "conv", "w"), ("output", "w")):
            if cand in flat:
                tree["output"]["kernel"] = conv_kernel(flat[cand])
                tree["output"]["bias"] = _t(flat[cand[:-1] + ("b",)]).reshape(-1)
                break
    for name, mod in tree.items():
        if not name.startswith("group_"):
            continue
        g, b = name.split("_block_")
        prefix = (g, f"block_{b}")
        for conv in ("conv_1", "conv_2", "conv_3", "conv_4"):
            set_conv(mod[conv], prefix + ("res_path", conv, "w"),
                     prefix + ("res_path", conv, "b"))
        if "id_path" in mod:
            set_conv(mod["id_path"], prefix + ("id_path", "w"),
                     prefix + ("id_path", "b"))
    return jax.tree_util.tree_map(jnp.asarray, p)


def install_dall_e_stubs():
    """Minimal class stubs so the genuine CDN pickles unpickle WITHOUT the
    upstream ``dall_e`` package (reference vae.py:103-113 imports it; the
    pkls are full pickled modules, not state dicts). Pickle restores a torch
    module from (class reference + attribute dict) — ``__init__`` is never
    called — so empty ``nn.Module`` subclasses are enough to rebuild the
    tree and serve ``.state_dict()`` for the tensor-by-tensor converter.
    Idempotent; no-op when a real dall_e package is importable."""
    import sys
    import types

    if "dall_e" in sys.modules:
        return
    try:
        import dall_e  # noqa: F401 — real package wins if present
        return
    except ImportError:
        pass
    import torch.nn as tnn

    def make(modname, names):
        mod = types.ModuleType(modname)
        for n in names:
            setattr(mod, n, type(n, (tnn.Module,), {"__module__": modname}))
        sys.modules[modname] = mod
        return mod

    pkg = make("dall_e", ())
    pkg.encoder = make("dall_e.encoder", ("Encoder", "EncoderBlock"))
    pkg.decoder = make("dall_e.decoder", ("Decoder", "DecoderBlock"))
    pkg.utils = make("dall_e.utils", ("Conv2d",))


class OpenAIDiscreteVAE(VAEAdapter):
    """The pretrained OpenAI tokenizer behind the standard VAE contract
    (reference vae.py:97-130). fixed: 256px, 3 layers (8× downsample → 32×32
    tokens), 8192 vocab."""

    image_size = 256
    num_layers = 3
    num_tokens = 8192

    def __init__(self, enc_params=None, dec_params=None, key=None):
        self.encoder = OpenAIEncoder()
        self.decoder = OpenAIDecoder()
        # throwaway init: from_pretrained immediately replaces these params,
        # so a fixed stream is correct (and keeps shape-only init reproducible)
        key = key if key is not None else deterministic_key()
        img = jnp.zeros((1, 64, 64, 3), jnp.float32)
        # `is not None`, not `or`: a falsy params container (empty FrozenDict
        # from a partial restore) must error downstream, not be silently
        # replaced by fresh random init
        self.enc_params = (enc_params if enc_params is not None
                           else self.encoder.init(key, img))
        z = jnp.zeros((1, 8, 8, self.num_tokens), jnp.float32)
        self.dec_params = (dec_params if dec_params is not None
                           else self.decoder.init(key, z))
        self._encode = jax.jit(lambda p, x: jnp.argmax(
            self.encoder.apply(p, map_pixels(x)), axis=-1))
        self._decode = jax.jit(lambda p, z: unmap_pixels(jax.nn.sigmoid(
            self.decoder.apply(p, z)[..., :3])))

    @classmethod
    def from_pretrained(cls, root: str = CACHE_PATH, backend=None):
        """Load + convert the CDN pickles. The pkls store full pickled
        ``dall_e`` modules; ``install_dall_e_stubs`` lets them unpickle
        without the upstream package, then ``state_dict()`` feeds the
        converter. Plain state-dict files work too."""
        import torch
        install_dall_e_stubs()
        enc_path = download(OPENAI_VAE_ENCODER_URL, root=root, backend=backend)
        dec_path = download(OPENAI_VAE_DECODER_URL, root=root, backend=backend)
        with open(enc_path, "rb") as f:
            enc = torch.load(f, map_location="cpu", weights_only=False)
        with open(dec_path, "rb") as f:
            dec = torch.load(f, map_location="cpu", weights_only=False)
        state_e = enc.state_dict() if hasattr(enc, "state_dict") else enc
        state_d = dec.state_dict() if hasattr(dec, "state_dict") else dec
        return cls.from_state_dicts(state_e, state_d)

    @classmethod
    def from_state_dicts(cls, enc_state: Dict[str, Any],
                         dec_state: Dict[str, Any]):
        """Escape hatch: convert plain ``state_dict`` mappings directly (e.g.
        re-saved with ``torch.save(model.state_dict(), ...)`` on a machine
        that has the upstream package) — no module unpickling at all."""
        vae = cls()
        vae.enc_params = _convert_openai_state(enc_state, vae.enc_params)
        vae.dec_params = _convert_openai_state(dec_state, vae.dec_params)
        return vae

    def get_codebook_indices(self, images):
        """images [0,1] NHWC → (b, 1024) int32 (reference vae.py:115-120)."""
        idx = self._encode(self.enc_params, images)
        return idx.reshape(idx.shape[0], -1).astype(jnp.int32)

    def decode(self, ids):
        """(b, 1024) ids → [0,1] images (one-hot → decoder → sigmoid → unmap,
        reference vae.py:122-130)."""
        b, n = ids.shape
        hw = int(n ** 0.5)
        z = jax.nn.one_hot(ids, self.num_tokens).reshape(b, hw, hw, -1)
        return self._decode(self.dec_params, z)


# ---------------------------------------------------------------------------
# taming VQGAN checkpoint import
# ---------------------------------------------------------------------------

def vqgan_config_from_yaml(path: str) -> VQGANConfig:
    """Parse a taming OmegaConf yaml into VQGANConfig (reference vae.py:154-181
    reads model.params.{embed_dim,n_embed,ddconfig})."""
    import yaml
    with open(path) as f:
        y = yaml.safe_load(f)
    p = y["model"]["params"]
    dd = p["ddconfig"]
    target = y["model"].get("target", "")
    remap = p.get("remap")
    if isinstance(remap, str):
        # taming passes remap as a path to an .npy of used code ids
        remap = tuple(int(i) for i in np.load(remap))
    elif remap is not None:
        remap = tuple(int(i) for i in remap)
    return VQGANConfig(
        remap_used=remap,
        remap_unknown=str(p.get("unknown_index", "random")),
        embed_dim=p["embed_dim"], n_embed=p["n_embed"],
        double_z=dd.get("double_z", False), z_channels=dd["z_channels"],
        resolution=dd["resolution"], in_channels=dd["in_channels"],
        out_ch=dd["out_ch"], ch=dd["ch"], ch_mult=tuple(dd["ch_mult"]),
        num_res_blocks=dd["num_res_blocks"],
        attn_resolutions=tuple(dd["attn_resolutions"]),
        dropout=dd.get("dropout", 0.0),
        quantizer="gumbel" if "Gumbel" in target else "vq",
        gumbel_kl_weight=p.get("kl_weight", 5e-4) if "Gumbel" in target else 5e-4,
    )


def _norm_pair(tree: dict, state, prefix: str):
    if f"{prefix}.weight" in state:
        tree["scale"] = _t(state[f"{prefix}.weight"])
        tree["bias"] = _t(state[f"{prefix}.bias"])


def _conv_pair(tree: dict, state, prefix: str):
    if f"{prefix}.weight" in state:
        tree["kernel"] = conv_kernel(state[f"{prefix}.weight"])
        if f"{prefix}.bias" in state:
            tree["bias"] = _t(state[f"{prefix}.bias"])


def _convert_resblock(dst: dict, state, prefix: str):
    _norm_pair(dst["norm1"], state, f"{prefix}.norm1")
    _conv_pair(dst["conv1"], state, f"{prefix}.conv1")
    _norm_pair(dst["norm2"], state, f"{prefix}.norm2")
    _conv_pair(dst["conv2"], state, f"{prefix}.conv2")
    if "nin_shortcut" in dst:
        _conv_pair(dst["nin_shortcut"], state, f"{prefix}.nin_shortcut")


def _convert_attnblock(dst: dict, state, prefix: str):
    _norm_pair(dst["norm"], state, f"{prefix}.norm")
    for name in ("q", "k", "v", "proj_out"):
        _conv_pair(dst[name], state, f"{prefix}.{name}")


def convert_vqgan_state(state: Dict[str, Any], params, cfg: VQGANConfig):
    """Map a taming ``state_dict`` (NCHW torch names, taming/models/vqgan.py
    module layout) onto the native VQModel param tree."""
    p = jax.device_get(params)
    tree = p["params"]

    for side, stack in (("encoder", "down"), ("decoder", "up")):
        sub = tree[side]
        _conv_pair(sub["conv_in"], state, f"{side}.conv_in")
        _conv_pair(sub["conv_out"], state, f"{side}.conv_out")
        _norm_pair(sub["norm_out"], state, f"{side}.norm_out")
        _convert_resblock(sub["mid_block_1"], state, f"{side}.mid.block_1")
        _convert_resblock(sub["mid_block_2"], state, f"{side}.mid.block_2")
        _convert_attnblock(sub["mid_attn_1"], state, f"{side}.mid.attn_1")
        for name, mod in sub.items():
            if f"_{'block'}_" in name and name.startswith(stack):
                lvl, blk = name.split("_block_")
                lvl = lvl.split("_")[1]
                _convert_resblock(mod, state,
                                  f"{side}.{stack}.{lvl}.block.{blk}")
            elif "_attn_" in name and name.startswith(stack):
                lvl, blk = name.split("_attn_")
                lvl = lvl.split("_")[1]
                _convert_attnblock(mod, state,
                                   f"{side}.{stack}.{lvl}.attn.{blk}")
            elif name.endswith("downsample"):
                lvl = name.split("_")[1]
                _conv_pair(mod["conv"], state,
                           f"{side}.down.{lvl}.downsample.conv")
            elif name.endswith("upsample"):
                lvl = name.split("_")[1]
                _conv_pair(mod["conv"], state, f"{side}.up.{lvl}.upsample.conv")

    # quantizer + codebook (taming quantize.py: embedding.weight)
    for cand in ("quantize.embedding.weight", "quantize.embed.weight"):
        if cand in state:
            tree["codebook"]["embedding"] = _t(state[cand])
    _conv_pair(tree["quant_conv"], state, "quant_conv")
    if cfg.quantizer == "gumbel":
        _conv_pair(tree["quant_proj"], state, "quantize.proj")
    _conv_pair(tree["post_quant_conv"], state, "post_quant_conv")
    return jax.tree_util.tree_map(jnp.asarray, p)


class VQGanVAE(VAEAdapter):
    """Pretrained taming VQGAN behind the VAE contract (reference
    vae.py:133-220). Images in [0,1] at the interface; mapped to [−1,1]
    internally (:198-205); decode clamps back to [0,1] (:207-217)."""

    def __init__(self, cfg: VQGANConfig, params=None, key=None):
        self.cfg = cfg
        self.model = VQModel(cfg)
        if params is None:
            from .vqgan import init_vqgan
            # `key if ... is not None`, NOT `key or`: truthiness of a (2,)
            # uint32 key array raises; the old `key or PRNGKey(0)` only
            # worked because every caller passed None
            _, params = init_vqgan(
                cfg, key if key is not None else deterministic_key())
        self.params = params
        self.image_size = cfg.resolution
        # true downsample factor; equals the reference's
        # log2(resolution/attn_resolution) formula (vae.py:176-178) for the
        # published configs, and stays correct when attn resolutions differ
        import math
        f = cfg.resolution // self.model.fmap_size
        self.num_layers = int(math.log2(f))
        # with remap the interface vocab is the used subset (+1 for the
        # 'extra' unknown token) — taming's re_embed (quantize.py:229-236)
        if cfg.remap_used is not None:
            self.num_tokens = (len(cfg.remap_used)
                               + (1 if cfg.remap_unknown == "extra" else 0))
        else:
            self.num_tokens = cfg.n_embed
        self._encode = jax.jit(lambda p, x: self.model.apply(
            p, 2.0 * x - 1.0, method=VQModel.get_codebook_indices))
        self._decode = jax.jit(lambda p, ids: jnp.clip(
            (self.model.apply(p, ids, method=VQModel.decode_code) + 1.0) * 0.5,
            0.0, 1.0))

    @classmethod
    def from_pretrained(cls, vqgan_model_path: Optional[str] = None,
                        vqgan_config_path: Optional[str] = None,
                        root: str = CACHE_PATH, backend=None):
        """Load ckpt+yaml; defaults to the 1024-codebook ImageNet model the
        reference downloads (vae.py:32-33,154-172)."""
        import torch
        model_path = vqgan_model_path or download(
            VQGAN_VAE_URL, "vqgan.1024.model.ckpt", root, backend)
        config_path = vqgan_config_path or download(
            VQGAN_VAE_CONFIG_URL, "vqgan.1024.config.yml", root, backend)
        cfg = vqgan_config_from_yaml(config_path)
        vae = cls(cfg)
        ckpt = torch.load(model_path, map_location="cpu", weights_only=False)
        state = ckpt.get("state_dict", ckpt)
        vae.params = convert_vqgan_state(state, vae.params, cfg)
        return vae

    def get_codebook_indices(self, images):
        return self._encode(self.params, images)

    def decode(self, ids):
        return self._decode(self.params, ids)
