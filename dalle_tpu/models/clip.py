"""CLIP — contrastive text/image model for reranking generations.

Reference: ``CLIP`` (dalle_pytorch/dalle_pytorch.py:256-332): token+positional
embeddings, two non-causal Transformers, 32px patch embedding via rearrange+
linear, masked-mean text pooling, L2-normalized latents, learned temperature,
symmetric cross-entropy over the similarity matrix.

TPU notes: patchification is a reshape (free under XLA), the two encoder stacks
reuse the same Transformer core as DALLE (dense causal=False path), and the
similarity matrix is one (b, d) @ (d, b) MXU matmul.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ..config import ClipConfig, TransformerConfig
from ..ops.quantize_weights import assert_float_params
from ..ops.sampling import masked_mean
from .transformer import Transformer


class CLIP(nn.Module):
    cfg: ClipConfig

    def setup(self):
        c = self.cfg
        self.text_emb = nn.Embed(c.num_text_tokens, c.dim_text, name="text_emb")
        self.text_pos_emb = nn.Embed(c.text_seq_len, c.dim_text, name="text_pos_emb")
        self.text_transformer = Transformer(TransformerConfig(
            seq_len=c.text_seq_len, causal=False, dim=c.dim_text,
            depth=c.text_enc_depth, heads=c.text_heads,
            dim_head=c.dim_text // c.text_heads, attn_types=("full",),
            image_fmap_size=0, rotary_emb=False), name="text_transformer")
        self.to_text_latent = nn.Dense(c.dim_latent, use_bias=False,
                                       name="to_text_latent")

        num_patches = (c.visual_image_size // c.visual_patch_size) ** 2
        patch_dim = c.channels * c.visual_patch_size ** 2
        self.visual_patch_proj = nn.Dense(c.dim_image, name="to_visual_embedding")
        self.visual_pos_emb = nn.Embed(num_patches, c.dim_image,
                                       name="visual_pos_emb")
        self.visual_transformer = Transformer(TransformerConfig(
            seq_len=num_patches, causal=False, dim=c.dim_image,
            depth=c.visual_enc_depth, heads=c.visual_heads,
            dim_head=c.dim_image // c.visual_heads, attn_types=("full",),
            image_fmap_size=0, rotary_emb=False), name="visual_transformer")
        self.to_visual_latent = nn.Dense(c.dim_latent, use_bias=False,
                                         name="to_visual_latent")
        self.temperature = self.param("temperature", nn.initializers.ones, ())

    def embed_text(self, text):
        """(b, text_seq_len) ids → (b, dim_latent) L2-normalized."""
        assert_float_params(self)
        mask = text != 0
        x = self.text_emb(text) + self.text_pos_emb(jnp.arange(text.shape[1]))
        x = self.text_transformer(x, key_mask=mask)
        x = masked_mean(x, mask)
        lat = self.to_text_latent(x)
        return lat / jnp.linalg.norm(lat, axis=-1, keepdims=True)

    def embed_image(self, image):
        """(b, H, W, C) NHWC floats → (b, dim_latent) L2-normalized."""
        assert_float_params(self)
        c = self.cfg
        p = c.visual_patch_size
        b, h, w, ch = image.shape
        assert h == w == c.visual_image_size, (
            f"image must be {c.visual_image_size}px, got {h}x{w}")
        # (b, h/p, p, w/p, p, c) → (b, n_patches, p*p*c)
        x = image.reshape(b, h // p, p, w // p, p, ch)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, (h // p) * (w // p), -1)
        x = self.visual_patch_proj(x)
        x = x + self.visual_pos_emb(jnp.arange(x.shape[1]))
        x = self.visual_transformer(x)
        x = x.mean(axis=1)
        lat = self.to_visual_latent(x)
        return lat / jnp.linalg.norm(lat, axis=-1, keepdims=True)

    def score_images(self, text, images):
        """Serving rerank: ONE prompt against N candidate images — ``text``
        (1, text_seq_len) ids, ``images`` (n, H, W, C) → (n,) similarity
        scores. The text tower runs once per group instead of once per
        candidate (``__call__`` with a repeated text row pays it n times);
        per-candidate scores are the same per-pair similarities the
        reference's generate_images rerank computes (:553-555). This is the
        program the ``clip_rerank`` graftir entry pins and the
        serve-pipeline rerank stage (serve/pipeline.py) dispatches per
        finished candidate group."""
        t = self.embed_text(text)[0]                 # (d,)
        v = self.embed_image(images)                 # (n, d)
        return jnp.einsum("nd,d->n", v, t) * jnp.exp(self.temperature)

    def __call__(self, text, image, return_loss: bool = False):
        """return_loss=False → per-pair similarity scores (the rerank path,
        reference :553-555); True → symmetric InfoNCE loss (:329-332)."""
        t = self.embed_text(text)
        v = self.embed_image(image)
        temp = jnp.exp(self.temperature)
        if not return_loss:
            return jnp.einsum("bd,bd->b", t, v) * temp
        sim = jnp.einsum("id,jd->ij", t, v) * temp
        labels = jnp.arange(sim.shape[0])
        loss_t = optax.softmax_cross_entropy_with_integer_labels(sim, labels).mean()
        loss_v = optax.softmax_cross_entropy_with_integer_labels(sim.T, labels).mean()
        return (loss_t + loss_v) / 2


def init_clip(cfg: ClipConfig, key: jax.Array, batch: int = 1):
    model = CLIP(cfg)
    text = jnp.zeros((batch, cfg.text_seq_len), jnp.int32)
    img = jnp.zeros((batch, cfg.visual_image_size, cfg.visual_image_size,
                     cfg.channels), jnp.float32)
    params = model.init(key, text, img, return_loss=True)
    return model, params


def load_clip(ckpt_dir: str, step: Optional[int] = None):
    """Restore a ``scripts/train_clip.py`` checkpoint as (CLIP, params)
    WITHOUT training imports: the serve path (attaching a reranker to
    ``DalleWithVae`` / the gateway pipeline) must not drag in
    TrainState/optimizer construction just to read frozen weights. The
    checkpointed tree is a TrainState pytree; orbax restores it
    template-free (raw arrays) and only the ``params`` subtree is
    materialized on device — opt_state bytes never leave host."""
    import os

    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(os.path.abspath(ckpt_dir))
    try:
        step = step if step is not None else mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps under {ckpt_dir}")
        restored = mgr.restore(step, args=ocp.args.Composite(
            state=ocp.args.PyTreeRestore(),
            metadata=ocp.args.JsonRestore()))
    finally:
        mgr.close()
    meta = restored.get("metadata") or {}
    if meta.get("model_class") != "CLIP":
        raise ValueError(f"{ckpt_dir} is not a CLIP checkpoint "
                         f"(model_class={meta.get('model_class')!r})")
    model = CLIP(ClipConfig.from_dict(meta["hparams"]))
    params = jax.tree_util.tree_map(jnp.asarray, restored["state"]["params"])
    return model, params
