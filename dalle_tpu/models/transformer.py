"""The transformer stack — training forward + cached incremental decode.

Reference: dalle_pytorch/transformer.py (Transformer :204-350, LayerScale :74-88,
PreNorm :92-102, GEGLU/FeedForward :106-122, PreShiftToken :126-200, DivideMax
:29-36, cache adapters :38-71) and attention.py (full/axial/conv/sparse variants).

TPU-first redesign decisions:
  * Every sparse attention variant is the dense MXU kernel + a compile-time
    static mask (ops/attn_masks.py). The reference itself proves mask-equivalence
    via `optimize_for_inference` (transformer.py:333-350). Pallas kernels slot in
    behind the same interface for long sequences (cfg.use_pallas).
  * The decode cache is a pytree of preallocated buffers threaded functionally
    (static shapes under jit/scan) — replacing the reference's mutated dicts,
    growing concats, and deques (transformer.py:38-71,138-153; attention.py:71-76).
  * Token-shift ring buffers store *pre-shift* chunks in both prefill and decode.
    (The reference's prefill stores post-shift chunks (transformer.py:193-197) —
    inconsistent with its own decode path (:144) — a latent bug that only
    manifests with image priming + shift_tokens; not replicated.)
  * Layer sharing (shared_attn_ids/shared_ff_ids) is flax module reuse: calling
    one module instance at several depths shares its params. Caches stay
    per-depth, matching the reference's per-index cache keys (:280-287).
  * Dropout keys are explicit; reversible blocks don't need the reference's RNG
    save/restore dance (reversible.py:20-50).
"""

from __future__ import annotations

from itertools import cycle, islice
from typing import Any, Dict, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..config import TransformerConfig
from ..ops.attention import (KVCache, attend, cached_attend,
                             cached_attend_window)
from ..ops.attn_masks import build_mask
from ..ops.quantize_weights import QDense
from ..ops.rotary import apply_rotary, dalle_pos_emb


def _block_body(mdl, x, key_mask, ind: int, deterministic: bool):
    """One attn+ff residual pair — module-first so ``nn.remat`` can lift it
    (flax replays dropout rngs inside the recompute automatically, replacing
    the reference's manual RNG save/restore, reversible.py:20-50)."""
    t = mdl.mask_keys[ind]
    x = x + mdl.attn_layers[ind](x, key_mask=key_mask, rotary=mdl.rotary,
                                 np_mask=mdl.np_masks[t],
                                 mask_spec=mdl.mask_specs[t],
                                 deterministic=deterministic)
    return x + mdl.ff_layers[ind](x, deterministic=deterministic)


def layerscale_init_eps(layer_index_1based: int) -> float:
    """Per-layer LayerScale init (reference transformer.py:74-83: 0.1 up to
    depth 18, 1e-5 to 24, 1e-6 beyond — keyed on the 1-based layer index)."""
    if layer_index_1based <= 18:
        return 0.1
    if layer_index_1based <= 24:
        return 1e-5
    return 1e-6


class DivideMax(nn.Module):
    """Divide by detached max — stable-output trick (reference :29-36)."""
    axis: int = -1

    def __call__(self, x):
        maxes = jax.lax.stop_gradient(jnp.max(x, axis=self.axis, keepdims=True))
        return x / maxes


class GEGLUFeedForward(nn.Module):
    """Linear(dim→dim·mult·2) → GEGLU → Dropout → Linear(dim·mult→dim)
    (reference :106-122)."""
    dim: int
    mult: int = 4
    dropout: float = 0.0

    def setup(self):
        # QDense ≡ nn.Dense until handed an int8 kernel (decode weight
        # quantization, ops/quantize_weights.py)
        self.w1 = QDense(self.dim * self.mult * 2, name="w1")
        self.w2 = QDense(self.dim, name="w2")
        self.drop = nn.Dropout(self.dropout)

    def __call__(self, x, deterministic: bool = True):
        x, gates = jnp.split(self.w1(x), 2, axis=-1)
        x = x * jax.nn.gelu(gates)
        x = self.drop(x, deterministic=deterministic)
        return self.w2(x)


class Attention(nn.Module):
    """Multi-head attention over the shared dense core (reference attention.py:39-99).
    Rotary is applied to q, k AND v — preserved reference behavior (:66-67).

    With ``use_pallas`` the full-sequence forward runs the Pallas flash kernel
    (ops/flash_attention.py), which also block-skips any static sparse mask —
    the TPU-native successor of the DeepSpeed SparseSelfAttention path
    (attention.py:339-398). Flash is inherently max-subtracting, so the
    ``stable`` softmax variant is subsumed. Decode keeps the dense cached core
    (single-token steps are bandwidth-, not matmul-bound)."""
    dim: int
    heads: int
    dim_head: int
    dropout: float = 0.0
    causal: bool = True
    stable: bool = False
    use_pallas: bool = False
    softmax_f32: bool = True
    # sequence parallelism: a Mesh with an 'sp' axis routes the full-causal
    # training forward through ring attention (parallel/ring_attention.py) —
    # activations shard along the sequence, k/v rotate over ICI. Static
    # module metadata (hashable), not a traced value.
    sp_mesh: Any = None

    def setup(self):
        inner = self.heads * self.dim_head
        self.to_qkv = QDense(inner * 3, use_bias=False, name="to_qkv")
        self.to_out = QDense(self.dim, name="to_out")
        self.drop = nn.Dropout(self.dropout)

    def _split(self, qkv, n):
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (-1, n, self.heads, self.dim_head)
        return [t.reshape(shape).transpose(0, 2, 1, 3) for t in (q, k, v)]

    def __call__(self, x, *, key_mask=None, rotary=None, np_mask=None,
                 mask_spec=None, deterministic: bool = True):
        """``np_mask`` is the ONE mask parameter (host-side numpy, compile-time
        constant): the pallas path lowers it to block lists, the dense path
        converts it to a jnp constant — a single source of truth so the two
        backends can never disagree."""
        b, n, _ = x.shape
        if (self.use_pallas == "fused" and key_mask is None and self.causal
                and not self.stable and self.sp_mesh is None
                and not self.is_initializing()):
            # fused-boundary kernel: operand is the qkv projection's own
            # (b, n, 3·h·d) layout, head split/merge live inside the kernel
            # (ops/fused_attention.py — the r5 answer to the persistent
            # kernel's 60 ms/step boundary tax). Rotary rides the same
            # layout: applied on the (b, n, 3h, d) VIEW — a reshape, not
            # the head-split transpose the dense path pays. The fits check
            # re-validates with the RUNTIME n (resolve saw cfg.seq_len) so
            # a stale/defaulted resolve can never reach a failing Mosaic
            # compile — unfit shapes fall through to dense.
            from ..ops.fused_attention import (fused_fits, fused_fwd_fits,
                                               fused_qkv_attention,
                                               fused_qkv_attention_xbwd)
            if fused_fits(n, self.dim_head, self.heads):
                fn = fused_qkv_attention           # Pallas fwd + Pallas bwd
            elif fused_fwd_fits(n, self.dim_head, self.heads):
                # shapes whose backward busts scoped VMEM (medium h·d):
                # Pallas fwd + boundary-free XLA bwd
                fn = fused_qkv_attention_xbwd
            else:
                fn = None
            if fn is not None:
                qkv = self.to_qkv(x)
                if rotary is not None:
                    rot = rotary[:n][:, None]          # (n, 1, rot_dim)
                    qkv = apply_rotary(
                        rot, qkv.reshape(b, n, 3 * self.heads, self.dim_head)
                    ).reshape(b, n, -1)
                out = fn(qkv, np_mask, self.heads, None, None,
                         mask_spec).astype(x.dtype)
                return self.drop(self.to_out(out),
                                 deterministic=deterministic)
        q, k, v = self._split(self.to_qkv(x), n)
        if rotary is not None:
            rot = rotary[:n][None, None]
            q, k, v = (apply_rotary(rot, t) for t in (q, k, v))
        if self.sp_mesh is not None and not self.is_initializing():
            # sequence-parallel ring attention: full causal plus structured
            # (axial/conv) sparse masks, whose element test is a pure function
            # of global (qpos, kpos) the ring evaluates per chunk pair —
            # tabled masks ('sparse' random blocks) have no such function and
            # stay single-chip
            assert key_mask is None and self.causal, (
                "sequence parallelism requires causal attention, no key_mask")
            assert np_mask is None or (
                mask_spec is not None and mask_spec[0] in ("axial", "conv")), (
                "sequence parallelism supports full/axial/conv attention only")
            from ..parallel.ring_attention import ring_attention
            # zigzag: balanced causal layout + quadrant skipping (exact);
            # kernel='auto' → Pallas chunk kernels on TPU for chunks ≥ 512
            out = ring_attention(q, k, v, mesh=self.sp_mesh, causal=True,
                                 zigzag=True,
                                 mask_spec=mask_spec if np_mask is not None
                                 else None)
        elif (self.use_pallas == "persist" and key_mask is None
              and self.causal and not self.stable
              and not self.is_initializing()):
            # whole-sequence VMEM-resident kernel: the mid-length tier where
            # block-grid flash loses to dense (ops/persistent_attention.py)
            from ..ops.persistent_attention import persistent_attention
            out = persistent_attention(q, k, v, np_mask).astype(x.dtype)
        elif (self.use_pallas in (True, "flash") and key_mask is None
              and not self.is_initializing()):
            # (init uses the dense path: params are identical and eager pallas
            # execution during un-jitted init is needlessly slow. NOT a bare
            # truthiness test: a "persist" request whose gate above rejected
            # it — stable/non-causal — must fall to dense, not to the flash
            # kernel that loses to dense at these lengths)
            from ..ops.flash_attention import flash_attention
            out = flash_attention(q, k, v, mask=np_mask, mask_spec=mask_spec,
                                  causal=self.causal)
        else:
            static = None if np_mask is None else jnp.asarray(np_mask)
            out = attend(q, k, v, causal=self.causal, key_mask=key_mask,
                         static_mask=static, stable=self.stable,
                         softmax_f32=self.softmax_f32)
        out = out.transpose(0, 2, 1, 3).reshape(b, n, -1)
        return self.drop(self.to_out(out), deterministic=deterministic)

    def prefill(self, x, cache: KVCache, *, rotary=None, static_mask=None):
        """Full-prefix forward that also fills the KV cache from position 0."""
        b, n, _ = x.shape
        q, k, v = self._split(self.to_qkv(x), n)
        if rotary is not None:
            rot = rotary[:n][None, None]
            q, k, v = (apply_rotary(rot, t) for t in (q, k, v))
        cache = cache.append(k, v, 0)
        out = attend(q, k, v, causal=self.causal, static_mask=static_mask,
                     stable=self.stable)
        out = out.transpose(0, 2, 1, 3).reshape(b, n, -1)
        return self.to_out(out), cache

    def decode(self, x_t, cache: KVCache, offset, *, rotary=None, static_mask=None,
               use_kernel=None):
        """One-token step at position ``offset`` (traced scalar).
        ``use_kernel`` pins the Pallas decode-kernel selection (None = auto)
        — see cached_attend; plumbed so parity-critical callers can force
        the same attend implementation on every path."""
        b = x_t.shape[0]
        q, k, v = self._split(self.to_qkv(x_t), 1)
        if rotary is not None:
            rot = jax.lax.dynamic_slice_in_dim(rotary, offset, 1, axis=0)[None, None]
            q, k, v = (apply_rotary(rot, t) for t in (q, k, v))
        cache = cache.append(k, v, offset)
        out = cached_attend(q, cache, offset + 1, static_mask=static_mask,
                            stable=self.stable, qpos=offset,
                            use_kernel=use_kernel)
        out = out.transpose(0, 2, 1, 3).reshape(b, 1, -1)
        return self.to_out(out), cache

    def decode_window(self, x_w, cache: KVCache, offsets, *, rotary=None,
                      use_kernel=None):
        """Speculative verify step: ``w`` tokens per row at PER-ROW absolute
        positions ``offsets[b] .. offsets[b]+w-1`` (offsets: (b,) traced) —
        batch rows diverge because they accept different draft lengths.
        Causality within the window + against the per-row cache prefix is
        enforced by cached_attend_window; rotary rows are gathered per
        (row, slot). Full attention only (no static masks — see
        cached_attend_window)."""
        b, w, _ = x_w.shape
        q, k, v = self._split(self.to_qkv(x_w), w)
        if rotary is not None:
            # clamp: a window starting at the final position overshoots the
            # table by up to w-1 slots (jnp.take's fill mode would NaN them);
            # overshoot slots only ever hold rejected/never-committed drafts
            pos = jnp.clip(offsets[:, None] + jnp.arange(w)[None, :],
                           0, rotary.shape[0] - 1)               # (b, w)
            rot = jnp.take(rotary, pos, axis=0)[:, None]         # (b,1,w,rot)
            q, k, v = (apply_rotary(rot, t) for t in (q, k, v))
        cache = cache.append_rows(k, v, offsets)
        out = cached_attend_window(q, cache, offsets, stable=self.stable,
                                   use_kernel=use_kernel)
        out = out.transpose(0, 2, 1, 3).reshape(b, w, -1)
        return self.to_out(out), cache


class ShiftState(NamedTuple):
    """Ring buffers for cached token-shift decode: the (top, left) quarter-chunks
    of the last ``image_size`` *pre-shift* inputs (reference deque,
    transformer.py:138-153), plus the previous token's first-half channels for
    text-position decode (text shift = ½ channels from position t−1)."""
    top: jnp.ndarray    # (b, image_size, d4)
    left: jnp.ndarray   # (b, image_size, d4)
    prev: jnp.ndarray   # (b, d2) pre-shift first half of the latest token

    @classmethod
    def init(cls, batch: int, image_size: int, d4: int, dtype=jnp.float32):
        z = jnp.zeros((batch, image_size, d4), dtype)
        return cls(z, z, jnp.zeros((batch, 2 * d4), dtype))


def shift_tokens_full(x, text_len: int, image_size: int):
    """Token-shift over a full sequence (reference PreShiftToken :155-186):
    text: first ½ of channels from position t−1; image: first ¼ from the top
    grid-neighbor, next ¼ from the left grid-neighbor."""
    b, n, d = x.shape
    if n < text_len:  # no image tokens yet — shift text only (ref :160-161)
        half, rest = jnp.split(x, 2, axis=-1)
        half = jnp.pad(half, ((0, 0), (1, 0), (0, 0)))[:, :n]
        return jnp.concatenate((half, rest), axis=-1)

    img_len = n - text_len
    x_text, x_img = x[:, :text_len], x[:, text_len:]

    t_shift, t_pass = jnp.split(x_text, 2, axis=-1)
    t_shift = jnp.pad(t_shift, ((0, 0), (1, 0), (0, 0)))[:, :text_len]
    x_text = jnp.concatenate((t_shift, t_pass), axis=-1)

    pad_to = image_size * image_size - img_len
    xi = jnp.pad(x_img, ((0, 0), (0, pad_to), (0, 0)))
    xi = xi.reshape(b, image_size, image_size, d)
    d4 = d // 4
    top, left, rest = xi[..., :d4], xi[..., d4:2 * d4], xi[..., 2 * d4:]
    top = jnp.pad(top, ((0, 0), (1, 0), (0, 0), (0, 0)))[:, :image_size]
    left = jnp.pad(left, ((0, 0), (0, 0), (1, 0), (0, 0)))[:, :, :image_size]
    xi = jnp.concatenate((top, left, rest), axis=-1)
    x_img = xi.reshape(b, image_size * image_size, d)[:, :img_len]
    return jnp.concatenate((x_text, x_img), axis=1)


def shift_prefill_state(x, text_len: int, image_size: int,
                        state: ShiftState) -> ShiftState:
    """Fill the ring buffers after a full-prefix forward: slots for image
    positions get their pre-shift chunks; text slots stay zero (matching the
    reference's dummy-padded deque init, :192-197, but pre-shift — see module
    docstring)."""
    b, n, d = x.shape
    d4 = d // 4
    # writes cast to the buffer dtype (the buffers may be narrower than the
    # activations, e.g. bf16 ring buffers alongside an int8 KV cache)
    prev = x[:, -1, :2 * d4].astype(state.prev.dtype)
    img_len = max(n - text_len, 0)
    if img_len == 0:
        return ShiftState(state.top, state.left, prev)
    take = min(img_len, image_size)
    chunk = x[:, n - take:n]
    # positions n-take..n-1 → ring slots (pos - text_len) % image_size
    pos = jnp.arange(n - take, n) - text_len
    slots = pos % image_size
    top = state.top.at[:, slots].set(chunk[..., :d4].astype(state.top.dtype))
    left = state.left.at[:, slots].set(
        chunk[..., d4:2 * d4].astype(state.left.dtype))
    return ShiftState(top, left, prev)


def shift_decode_step(x_t, state: ShiftState, offset, text_len: int,
                      image_size: int):
    """Cached one-token shift (reference :138-153) at traced position
    ``offset``. Text positions (offset < text_len) take the previous token's
    first-half channels; image positions take the (top, left) grid-neighbor
    quarter-chunks from the ring buffers. Returns (shifted x_t, new state)."""
    b, _, d = x_t.shape
    d4 = d // 4
    d2 = 2 * d4
    cur = x_t[:, 0]
    cur_top, cur_left = cur[..., :d4], cur[..., d4:d2]
    img_pos = offset - text_len
    is_text = offset < text_len
    ptr = img_pos % image_size  # nonneg also while img_pos < 0 (text phase)
    # top neighbor = value written image_size steps ago = current ring slot
    top_n = jax.lax.dynamic_index_in_dim(state.top, ptr, axis=1, keepdims=False)
    prev_ptr = (ptr - 1) % image_size
    left_n = jax.lax.dynamic_index_in_dim(state.left, prev_ptr, axis=1, keepdims=False)
    # zero top for the first image row; zero left at column 0 (ref :149-150 +
    # the full path's zero padding)
    top_n = jnp.where(img_pos < image_size, 0.0, top_n)
    left_n = jnp.where(img_pos % image_size == 0, 0.0, left_n)
    img_shift = jnp.concatenate((top_n, left_n, cur[..., d2:]), axis=-1)
    txt_shift = jnp.concatenate((state.prev, cur[..., d2:]), axis=-1)
    shifted = jnp.where(is_text, txt_shift, img_shift)[:, None]
    new_top = jax.lax.dynamic_update_slice_in_dim(
        state.top, cur_top[:, None].astype(state.top.dtype), ptr, axis=1)
    new_left = jax.lax.dynamic_update_slice_in_dim(
        state.left, cur_left[:, None].astype(state.left.dtype), ptr, axis=1)
    # text-phase steps must not write into the image ring buffers
    state = ShiftState(jnp.where(is_text, state.top, new_top),
                       jnp.where(is_text, state.left, new_left),
                       cur[..., :d2].astype(state.prev.dtype))
    return shifted, state


class TransformerLayer(nn.Module):
    """PreNorm(+sandwich) → optional token-shift → fn, scaled by LayerScale,
    residual added by the caller. One instance each for attn and ff roles."""
    dim: int
    index: int                     # 1-based, for LayerScale init
    fn: nn.Module
    sandwich: bool = False
    shift: bool = False
    text_len: int = 0
    image_size: int = 0

    def setup(self):
        self.norm = nn.LayerNorm(name="norm")
        self.norm_out = nn.LayerNorm(name="norm_out") if self.sandwich else None
        eps = layerscale_init_eps(self.index)
        # explicit dtype: jnp.full of a Python float is WEAK-typed, and a
        # weak-typed param flips to strong after one pass through a jitted
        # step (outputs are strong), changing the input signature — every
        # train_step call then recompiles the whole program (graftlint
        # weak-type-promotion; graftir caught this as a per-step retrace).
        # The f32 pin is deliberate: params are created full-width by repo
        # policy (precision modes cast derived trees, never initializers)
        self.scale = self.param(  # graftlint: disable=hardcoded-dtype
            "scale", lambda k: jnp.full((1, 1, self.dim), eps, jnp.float32))

    def _post(self, y):
        if self.norm_out is not None:
            y = self.norm_out(y)
        return y * self.scale

    def __call__(self, x, **kw):
        y = self.norm(x)
        if self.shift:
            y = shift_tokens_full(y, self.text_len, self.image_size)
        y = self.fn(y, **kw)
        return self._post(y)

    def prefill(self, x, kv: Optional[KVCache], shift_state: Optional[ShiftState],
                **kw):
        y = self.norm(x)
        if self.shift:
            pre = y
            y = shift_tokens_full(y, self.text_len, self.image_size)
            shift_state = shift_prefill_state(pre, self.text_len, self.image_size,
                                              shift_state)
        if isinstance(self.fn, Attention):
            y, kv = self.fn.prefill(y, kv, **kw)
        else:
            y = self.fn(y)
        return self._post(y), kv, shift_state

    def decode(self, x_t, kv: Optional[KVCache], shift_state: Optional[ShiftState],
               offset, **kw):
        y = self.norm(x_t)
        if self.shift:
            y, shift_state = shift_decode_step(y, shift_state, offset,
                                               self.text_len, self.image_size)
        if isinstance(self.fn, Attention):
            y, kv = self.fn.decode(y, kv, offset, **kw)
        else:
            y = self.fn(y)
        return self._post(y), kv, shift_state

    def decode_window(self, x_w, kv: Optional[KVCache], offsets, **kw):
        """w-token speculative step (no token-shift: the ring buffers are
        inherently one-token-sequential — gated at the Transformer level)."""
        y = self.norm(x_w)
        if isinstance(self.fn, Attention):
            y, kv = self.fn.decode_window(y, kv, offsets, **kw)
        else:
            y = self.fn(y)
        return self._post(y), kv


class Transformer(nn.Module):
    """depth × (attn, ff) with per-layer attention kind from the cyclic
    ``attn_types`` tuple, layer sharing, rotary table, static sparse masks.
    (reference Transformer ctor :204-328)"""
    cfg: TransformerConfig
    sp_mesh: Any = None    # sequence-parallel mesh (see Attention.sp_mesh)

    def setup(self):
        c = self.cfg
        fmap = c.image_fmap_size
        img_seq = fmap * fmap
        self.text_len = c.seq_len + 1 - img_seq if c.causal else 0
        # "auto" resolves against the measured v5e crossover: flash kernels
        # for seq ≥ 2048 on TPU, dense below (ops/flash_attention.py)
        from ..ops.flash_attention import resolve_use_pallas
        use_pallas = resolve_use_pallas(c.use_pallas, c.seq_len,
                                        dim_head=c.dim_head, heads=c.heads)

        attn_types = tuple(c.attn_types) or ("full",)
        type_per_layer = list(islice(cycle(attn_types), c.depth))
        attn_ids = list(islice(cycle(c.shared_attn_ids or range(c.depth)), c.depth))
        ff_ids = list(islice(cycle(c.shared_ff_ids or range(c.depth)), c.depth))

        # static masks (None for 'full' — plain causal handled in attend);
        # kept as NUMPY (the pallas path needs host-side masks for block-list
        # construction; the dense path converts per-trace, folded by XLA).
        # Deterministic mask types share one entry per type; 'sparse' gets a
        # per-LAYER entry with seed = sparse_mask_seed + layer_index, so each
        # sparse layer draws its own random-block pattern (DeepSpeed
        # VariableSparsityConfig parity — one shared pattern would silently
        # narrow the reference semantics)
        mask_keys = [f"sparse_{ind}" if t == "sparse" else t
                     for ind, t in enumerate(type_per_layer)]
        masks: Dict[str, Optional[np.ndarray]] = {}
        specs: Dict[str, Optional[tuple]] = {}
        for ind, (mk, t) in enumerate(zip(mask_keys, type_per_layer)):
            if mk in masks:
                continue
            if t == "full" or not c.causal:
                masks[mk], specs[mk] = None, None
                continue
            masks[mk] = build_mask(
                t, self.text_len, fmap, kernel_size=c.sparse_attn_kernel,
                block=c.sparse_block_size,
                num_random_blocks=c.sparse_num_random_blocks,
                seed=c.sparse_mask_seed + ind)
            # structured-mask specs: the pallas kernels compute axial/conv
            # element visibility from iotas instead of loading a mask table
            # (ops/flash_attention.py elem_fn_from_spec)
            if t in ("axial_row", "axial_col"):
                specs[mk] = ("axial", self.text_len, fmap,
                             0 if t == "axial_row" else 1)
            elif t == "conv_like":
                specs[mk] = ("conv", self.text_len, fmap,
                             c.sparse_attn_kernel, 1)
            elif t == "sparse":
                # block-aligned random-block pattern: kernel tiles coincide
                # with the pattern's block grid, no element mask needed
                specs[mk] = ("block", c.sparse_block_size)
            else:
                specs[mk] = None
        self.np_masks = masks
        self.mask_specs = specs
        self.mask_keys = mask_keys

        shared_attn: Dict[Any, Tuple[Attention, str]] = {}
        shared_ff: Dict[Any, GEGLUFeedForward] = {}
        attn_layers, ff_layers = [], []
        layer_types = []
        for ind in range(c.depth):
            t = type_per_layer[ind]
            aid, fid = attn_ids[ind], ff_ids[ind]
            if aid in shared_attn:
                attn, prev_t = shared_attn[aid]
                if prev_t != t:
                    raise ValueError(
                        f"attn_types do not match shared_attn_ids (ind={ind}, "
                        f'attn_type="{t}", reused="{prev_t}")')
            else:
                attn = Attention(c.dim, c.heads, c.dim_head, c.attn_dropout,
                                 causal=c.causal, stable=c.stable,
                                 use_pallas=use_pallas,
                                 softmax_f32=c.attn_softmax_f32,
                                 sp_mesh=self.sp_mesh,
                                 name=f"attn_{aid}")
                shared_attn[aid] = (attn, t)
            if fid in shared_ff:
                ff = shared_ff[fid]
            else:
                ff = GEGLUFeedForward(c.dim, c.ff_mult, c.ff_dropout,
                                      name=f"ff_{fid}")
                shared_ff[fid] = ff
            attn_layers.append(TransformerLayer(
                c.dim, ind + 1, attn, sandwich=c.sandwich_norm,
                shift=c.shift_tokens, text_len=self.text_len, image_size=fmap,
                name=f"layer_attn_{ind}"))
            ff_layers.append(TransformerLayer(
                c.dim, ind + 1, ff, sandwich=c.sandwich_norm,
                shift=c.shift_tokens, text_len=self.text_len, image_size=fmap,
                name=f"layer_ff_{ind}"))
            layer_types.append(t)
        self.layer_types = layer_types
        self.attn_layers = attn_layers
        self.ff_layers = ff_layers

        self.rotary = None
        if c.rotary_emb and c.causal:
            self.rotary = jnp.asarray(
                dalle_pos_emb(self.text_len, fmap, c.dim_head))

    def _dense_mask(self, t):
        m = self.np_masks[t]
        return None if m is None else jnp.asarray(m)


    # -- training / full forward ------------------------------------------
    def __call__(self, x, key_mask=None, deterministic: bool = True):
        """Sequential execution by default; ``cfg.reversible`` switches to the
        O(1)-activation custom_vjp path (models/reversible.py) — the TPU
        equivalent of the reference's ReversibleSequence. `jax.checkpoint` at
        the train-step level is the complementary remat lever."""
        c = self.cfg
        if c.reversible:
            return self._call_reversible(x, key_mask, deterministic)
        use_remat = c.use_remat and not self.is_initializing()
        for ind in range(c.depth):
            if use_remat:
                # real jax.checkpoint per block pair: activations inside the
                # block are recomputed in backward — the memory lever that
                # lets batch/depth scale past HBM (complements `reversible`,
                # which is O(1) in depth rather than O(depth) checkpoints)
                blk = nn.remat(_block_body, prevent_cse=False,
                               static_argnums=(3, 4))
                x = blk(self, x, key_mask, ind, deterministic)
            else:
                x = _block_body(self, x, key_mask, ind, deterministic)
        return x

    def _call_reversible(self, x, key_mask, deterministic: bool):
        """Unbind each layer into (pure fn, params) pairs and run the
        reversible coupling. Dropout works through explicit key replay: every
        block fn carries its dropout key in the params pytree, so the
        custom_vjp backward's recompute uses bit-identical masks — the
        TPU-native version of the reference's RNG save/restore dance
        (reversible.py:20-50). Each block gets the base key with its depth
        index folded in: layers reused via shared_attn_ids/shared_ff_ids live
        at the same module path, so without the fold every reuse would draw
        the identical dropout mask (the sequential path decorrelates repeats
        through flax's rng call counter)."""
        from .reversible import run_reversible
        c = self.cfg
        use_dropout = (not deterministic
                       and (c.attn_dropout > 0 or c.ff_dropout > 0))
        if self.is_initializing():
            # bound calls so flax creates the params; same coupled computation
            x1 = x2 = x
            for ind in range(c.depth):
                x1 = x1 + self._apply_attn_layer(x2, ind, key_mask)
                x2 = x2 + self._apply_ff_layer(x1, ind)
            return (x1 + x2) / 2.0
        drop_key = self.make_rng("dropout") if use_dropout else None
        # Unbind the WHOLE stack once: shared layers live in their first
        # adopter's flax scope, so per-layer unbinding would lose their params.
        # Each block fn takes the full variable tree; unused-leaf cotangents
        # are symbolic zeros that XLA folds away.
        tm, variables = self.unbind()
        fns, params = [], []
        for ind in range(c.depth):
            blk_key = (None if drop_key is None
                       else jax.random.fold_in(drop_key, ind))

            def f(p, h, _ind=ind):
                var, key = p
                rngs = None if key is None else {"dropout": key}
                return tm.apply(var, h, _ind, key_mask, key is None,
                                method=Transformer._apply_attn_layer,
                                rngs=rngs)

            def g(p, h, _ind=ind):
                var, key = p
                rngs = None if key is None else {"dropout": key}
                return tm.apply(var, h, _ind, key is None,
                                method=Transformer._apply_ff_layer, rngs=rngs)

            fns.append((f, g))
            params.append(((variables, blk_key), (variables, blk_key)))
        return run_reversible(fns, params, x)

    def _apply_attn_layer(self, h, ind: int, key_mask=None,
                          deterministic: bool = True):
        t = self.mask_keys[ind]
        return self.attn_layers[ind](h, key_mask=key_mask, rotary=self.rotary,
                                     np_mask=self.np_masks[t],
                                     mask_spec=self.mask_specs[t],
                                     deterministic=deterministic)

    def _apply_ff_layer(self, h, ind: int, deterministic: bool = True):
        return self.ff_layers[ind](h, deterministic=deterministic)

    # -- cached decode -----------------------------------------------------
    def init_cache(self, batch: int, max_seq: Optional[int] = None,
                   dtype=jnp.float32) -> Dict[str, Any]:
        c = self.cfg
        max_seq = max_seq or c.seq_len + 1
        cache: Dict[str, Any] = {}
        d4 = c.dim // 4
        # int8 selects *quantized KV storage* (KVCache handles scales); the
        # token-shift ring buffers hold raw hidden slices and stay bf16
        shift_dtype = jnp.bfloat16 if dtype == jnp.int8 else dtype
        for ind in range(c.depth):
            cache[f"kv_{ind}"] = KVCache.init(batch, c.heads, max_seq,
                                              c.dim_head, dtype)
            if c.shift_tokens:
                cache[f"shift_attn_{ind}"] = ShiftState.init(
                    batch, c.image_fmap_size, d4, shift_dtype)
                cache[f"shift_ff_{ind}"] = ShiftState.init(
                    batch, c.image_fmap_size, d4, shift_dtype)
        return cache

    def init_cache_paged(self, num_blocks: int, block_tokens: int,
                         max_seq: int, dtype=jnp.float32) -> Dict[str, Any]:
        """Paged twin of ``init_cache``: per-layer block pools instead of
        per-slot slabs. The page table is NOT allocated here — the engine
        owns exactly one ``(B, max_blocks)`` table as a state leaf and
        injects it into every layer per dispatch (a per-layer copy would
        donate the same buffer depth times). Serve mode requires
        shift_tokens off (Transformer.decode_window asserts it), so no
        shift states."""
        c = self.cfg
        assert not c.shift_tokens, "paged serve cache requires shift_tokens off"
        from ..ops.paged_kv import PagedKVCache
        return {f"kv_{ind}": PagedKVCache.init(num_blocks, block_tokens,
                                               c.heads, max_seq, c.dim_head,
                                               dtype)
                for ind in range(c.depth)}

    def prefill(self, x, cache: Dict[str, Any]):
        """Run the full prefix, filling every layer's caches. Returns (y, cache)."""
        c = self.cfg
        cache = dict(cache)
        for ind in range(c.depth):
            attn_l, ff_l, t = self.attn_layers[ind], self.ff_layers[ind], self.mask_keys[ind]
            y, kv, ss = attn_l.prefill(x, cache[f"kv_{ind}"],
                                       cache.get(f"shift_attn_{ind}"),
                                       rotary=self.rotary,
                                       static_mask=self._dense_mask(t))
            cache[f"kv_{ind}"] = kv
            if ss is not None:
                cache[f"shift_attn_{ind}"] = ss
            x = x + y
            y, _, ss = ff_l.prefill(x, None, cache.get(f"shift_ff_{ind}"))
            if ss is not None:
                cache[f"shift_ff_{ind}"] = ss
            x = x + y
        return x, cache

    def decode_window(self, x_w, cache: Dict[str, Any], offsets, *,
                      use_kernel=None):
        """w tokens per row at per-row positions ``offsets`` (b,) — the
        speculative verify forward (models/dalle.py). Requires full
        attention and no token-shift (both hold for every generation config
        the samplers build; sparse masks would need per-row mask gathers and
        shift ring buffers are one-token-sequential by construction)."""
        c = self.cfg
        assert not c.shift_tokens, (
            "speculative decode does not support shift_tokens")
        assert all(k == "full" for k in self.mask_keys), (
            "speculative decode supports full attention only, got "
            f"{set(self.mask_keys)}")
        cache = dict(cache)
        for ind in range(c.depth):
            attn_l, ff_l = self.attn_layers[ind], self.ff_layers[ind]
            y, kv = attn_l.decode_window(x_w, cache[f"kv_{ind}"], offsets,
                                         rotary=self.rotary,
                                         use_kernel=use_kernel)
            cache[f"kv_{ind}"] = kv
            x_w = x_w + y
            y, _ = ff_l.decode_window(x_w, None, offsets)
            x_w = x_w + y
        return x_w, cache

    def decode_step(self, x_t, cache: Dict[str, Any], offset, *,
                    use_kernel=None):
        """One token at traced position ``offset``. Returns (y_t, cache).
        Sparse masks apply via their offset row; causality is implicit
        (reference attention.py:86 'causality is naturally enforced')."""
        c = self.cfg
        cache = dict(cache)
        for ind in range(c.depth):
            attn_l, ff_l, t = self.attn_layers[ind], self.ff_layers[ind], self.mask_keys[ind]
            y, kv, ss = attn_l.decode(x_t, cache[f"kv_{ind}"],
                                      cache.get(f"shift_attn_{ind}"), offset,
                                      rotary=self.rotary,
                                      static_mask=self._dense_mask(t),
                                      use_kernel=use_kernel)
            cache[f"kv_{ind}"] = kv
            if ss is not None:
                cache[f"shift_attn_{ind}"] = ss
            x_t = x_t + y
            y, _, ss = ff_l.decode(x_t, None, cache.get(f"shift_ff_{ind}"), offset)
            if ss is not None:
                cache[f"shift_ff_{ind}"] = ss
            x_t = x_t + y
        return x_t, cache
