"""Continuous-batching serving for DALLE image generation.

``RequestQueue`` (host FIFO, optionally bounded) → ``SlotScheduler`` (slot ↔
request bookkeeping) → ``DecodeEngine`` (the device loop: B shared-cache
decode slots, per-row lengths/offsets/RNG lanes, iteration-level refill).
``PolicyQueue`` layers priority/deadline scheduling and deadline shedding on
top for the gateway (FIFO stays the default). See docs/PERFORMANCE.md
("Serving"), docs/SERVING.md (gateway) and scripts/serve_bench.py /
scripts/serve_smoke.py.
"""

from .engine import DecodeEngine, EngineStats
from .paged import BlockPool, Match, RadixCache
from .pipeline import (CandidateGroup, ImagePipeline, PendingResult,
                       RankedGroup, prepare_clip_text)
from .queue import CompletedRequest, QueueFull, Request, RequestQueue
from .scheduler import (FifoPolicy, PolicyQueue, PriorityDeadlinePolicy,
                        SchedulingPolicy, SlotScheduler)

__all__ = ["DecodeEngine", "EngineStats", "CompletedRequest", "QueueFull",
           "Request", "RequestQueue", "SlotScheduler", "SchedulingPolicy",
           "FifoPolicy", "PriorityDeadlinePolicy", "PolicyQueue",
           "BlockPool", "Match", "RadixCache",
           "CandidateGroup", "ImagePipeline", "PendingResult", "RankedGroup",
           "prepare_clip_text"]
