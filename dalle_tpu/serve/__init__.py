"""Continuous-batching serving for DALLE image generation.

``RequestQueue`` (host FIFO) → ``SlotScheduler`` (slot ↔ request
bookkeeping) → ``DecodeEngine`` (the device loop: B shared-cache decode
slots, per-row lengths/offsets/RNG lanes, iteration-level refill). See
docs/PERFORMANCE.md ("Serving") and scripts/serve_bench.py /
scripts/serve_smoke.py.
"""

from .engine import DecodeEngine, EngineStats
from .queue import CompletedRequest, Request, RequestQueue
from .scheduler import SlotScheduler

__all__ = ["DecodeEngine", "EngineStats", "CompletedRequest", "Request",
           "RequestQueue", "SlotScheduler"]
