"""graftloom post-decode product pipeline: candidate groups → pixels → rank.

The paper's actual user flow is text → MANY candidate image-token sequences
→ dVAE pixel decode → CLIP rerank → top-k images (PAPER.md; the reference's
``generate_images`` at dalle_pytorch.py:490-557). The decode engine ends at
tokens; this module is the rest of the product: a small stage-graph runtime
that takes FINISHED candidate groups (all N candidates of one
``/v1/images`` request, collected by the gateway) and batches each group
through

  * ``decode_pixels`` — one jitted dVAE decode of the (N, image_seq_len)
    token grids → (N, H, W, C) pixels (the vae stays off the per-token
    critical path — it only ever sees whole finished groups);
  * ``rerank`` — one jitted batched CLIP score (``CLIP.score_images``: the
    text tower runs once per group, not once per candidate; pinned as the
    ``clip_rerank`` graftir entry); without an attached reranker the stage
    passes through with zero scores (candidate order = submission order);
  * ``rank`` — order candidates by score (descending, ties by candidate
    index — deterministic), emit the top-k with base64 pixel payloads.

Each stage runs on its own worker thread behind a bounded queue, so a slow
stage backs pressure up instead of buffering without bound, and the stages
of DIFFERENT groups overlap (group A reranks while group B pixel-decodes).
Per-stage spans (``pipeline/decode_pixels``, ``pipeline/rerank``) and
queue-depth gauges (``pipeline.queue_depth{stage=...}`` — stage names only,
bounded cardinality) feed ``obs_report``'s IMAGES verdict.
"""

from __future__ import annotations

import base64
import dataclasses
import queue as _queue
import threading
import time
from typing import List, Optional

import numpy as np

from ..obs import counter_add, gauge_set, record_span

_STAGES = ("decode_pixels", "rerank")


def prepare_clip_text(text: np.ndarray, clip_cfg) -> np.ndarray:
    """DALLE prompt ids → CLIP text-tower ids (the same sanitization
    ``DalleWithVae.generate_images`` applies): ids at or above CLIP's text
    vocab (DALLE's per-position pad remaps) zero back to pad, and the
    context is cropped/0-padded to CLIP's ``text_seq_len`` (an out-of-range
    position gather would fill with garbage)."""
    text = np.asarray(text, np.int32).reshape(1, -1)
    text = np.where(text >= clip_cfg.num_text_tokens, 0, text)
    n = clip_cfg.text_seq_len
    if text.shape[1] > n:
        text = text[:, :n]
    elif text.shape[1] < n:
        text = np.pad(text, ((0, 0), (0, n - text.shape[1])))
    return text


@dataclasses.dataclass
class CandidateGroup:
    """All N finished candidates of one multi-candidate request, in
    candidate order. ``tokens`` rows are the exact per-candidate grids the
    engine produced (bitwise single-request generation under each seed)."""
    group_id: int
    text: np.ndarray            # (text_seq_len,) int32 prompt ids
    tokens: np.ndarray          # (N, n_tokens) int32
    seeds: List[int]
    top_k: int
    trace_id: Optional[str] = None


@dataclasses.dataclass
class RankedGroup:
    """The pipeline's product: candidates ordered best-first."""
    group_id: int
    scores: List[float]         # per candidate, submission order
    order: List[int]            # candidate indices, best first
    top_k: List[dict]           # [{candidate, score, tokens[, pixels_b64,
                                #   pixels_shape]}]
    tokens: np.ndarray          # (N, n_tokens) all candidate grids
    reranked: bool              # CLIP actually scored (vs zero passthrough)
    trace_id: Optional[str] = None
    error: Optional[str] = None


class PendingResult:
    """Handle for one submitted group: ``result(timeout)`` blocks until the
    rank stage (or a stage failure) completes it."""

    def __init__(self):
        self._done = threading.Event()
        self._result: Optional[RankedGroup] = None

    def set(self, result: RankedGroup) -> None:
        self._result = result
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> RankedGroup:
        if not self._done.wait(timeout):
            raise TimeoutError("pipeline result not ready")
        return self._result


class ImagePipeline:
    """``submit(CandidateGroup) -> PendingResult``; ``close()`` drains.

    ``vae`` (a VAEAdapter) enables the pixel stage; ``clip``/``clip_params``
    enable rerank (requires the vae — CLIP scores pixels, not tokens).
    Without either, groups pass straight to the rank stage token-only with
    zero scores. ``encode_pixels`` controls whether top-k entries carry
    base64 uint8 RGB payloads (the gateway wants them; benches don't).
    """

    def __init__(self, vae=None, clip=None, clip_params=None, *,
                 top_k: Optional[int] = None, maxsize: int = 64,
                 encode_pixels: bool = True):
        self.vae = vae
        self.clip = clip
        self.clip_params = clip_params
        self.default_top_k = top_k
        self.encode_pixels = bool(encode_pixels)
        self._scorer = None
        if clip is not None:
            if vae is None:
                raise ValueError("CLIP rerank needs a vae: the scorer "
                                 "consumes decoded pixels, not token ids")
            self._scorer = self._build_scorer(clip)
        self._qs = {s: _queue.Queue(maxsize=max(1, int(maxsize)))
                    for s in _STAGES}
        self._threads: List[threading.Thread] = []
        self._closed = False
        self._lock = threading.Lock()

    # -- jitted programs ---------------------------------------------------
    def _build_scorer(self, clip):
        """The batched rerank program (the ``clip_rerank`` graftir entry):
        (1, T) text × (N, H, W, C) images → (N,) scores, with a resize to
        CLIP's visual resolution fused in when the dVAE decodes at a
        different size."""
        import jax

        from ..models.clip import CLIP
        cfg = clip.cfg

        def score(params, text, images):
            vs = cfg.visual_image_size
            if images.shape[1] != vs or images.shape[2] != vs:
                images = jax.image.resize(
                    images, (images.shape[0], vs, vs, images.shape[3]),
                    "bilinear")
            return clip.apply(params, text, images,
                              method=CLIP.score_images)

        return jax.jit(score)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ImagePipeline":
        with self._lock:
            if self._closed:
                # checked under the lock: a submit racing close() must not
                # spawn workers that will never see the drain sentinel
                raise RuntimeError("pipeline is closed")
            if self._threads:
                return self
            for stage in _STAGES:
                t = threading.Thread(target=self._work, args=(stage,),
                                     name=f"pipeline-{stage}", daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain: queued groups finish, then the workers exit. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            threads = list(self._threads)
        if threads:
            self._qs[_STAGES[0]].put(None)      # sentinel cascades forward
        for t in threads:
            t.join(timeout)

    # -- submission --------------------------------------------------------
    def submit(self, group: CandidateGroup, *,
               timeout: float = 30.0) -> PendingResult:
        self.start()                        # raises if closed (lock-checked)
        pending = PendingResult()
        # bounded put: a wedged stage must surface as an error to THIS
        # caller, not park the connection thread forever on a full queue
        self._put("decode_pixels", (group, pending), timeout=timeout)
        return pending

    def process(self, group: CandidateGroup) -> RankedGroup:
        """Synchronous convenience (benches, tests): run every stage inline
        on the caller's thread — identical math, no queue hops."""
        images = self._decode_stage(group)
        scores, reranked = self._rerank_stage(group, images)
        return self._rank_stage(group, images, scores, reranked)

    # -- stage workers -----------------------------------------------------
    def _put(self, stage: str, item, timeout: Optional[float] = None) -> None:
        q = self._qs[stage]
        try:
            q.put(item, timeout=timeout)
        except _queue.Full:
            raise RuntimeError(
                f"pipeline backlogged: stage {stage!r} queue full "
                f"for {timeout}s") from None
        gauge_set("pipeline.queue_depth", float(q.qsize()),
                  labels={"stage": stage})

    def _work(self, stage: str) -> None:
        q = self._qs[stage]
        while True:
            try:
                # bounded wait (graftlint: unbounded-blocking-call): the
                # drain sentinel is the normal exit, but a worker must
                # re-check the world on a cadence rather than park forever
                # on a queue nothing will ever feed again (a wedged
                # upstream stage, an abandoned pipeline)
                item = q.get(timeout=1.0)
            except _queue.Empty:
                continue
            gauge_set("pipeline.queue_depth", float(q.qsize()),
                      labels={"stage": stage})
            if item is None:                    # drain sentinel: pass on
                nxt = _STAGES.index(stage) + 1
                if nxt < len(_STAGES):
                    self._qs[_STAGES[nxt]].put(None)
                return
            group, pending = item[0], item[1]
            try:
                if stage == "decode_pixels":
                    images = self._decode_stage(group)
                    self._put("rerank", (group, pending, images))
                else:
                    images = item[2]
                    scores, reranked = self._rerank_stage(group, images)
                    pending.set(self._rank_stage(group, images, scores,
                                                 reranked))
            except Exception as exc:  # noqa: BLE001 - a stage failure must
                # complete the waiting request with an error, never strand
                # the connection thread on an event that will never fire
                # (the group is dropped; the worker keeps serving others)
                pending.set(RankedGroup(
                    group_id=group.group_id, scores=[], order=[], top_k=[],
                    tokens=group.tokens, reranked=False,
                    trace_id=group.trace_id, error=repr(exc)))

    def _decode_stage(self, group: CandidateGroup):
        if self.vae is None:
            return None
        t0 = time.perf_counter()
        images = np.asarray(self.vae.decode(group.tokens))
        record_span("pipeline/decode_pixels", t0, time.perf_counter() - t0,
                    group_id=group.group_id,
                    candidates=int(group.tokens.shape[0]),
                    trace_id=group.trace_id)
        return images

    def _rerank_stage(self, group: CandidateGroup, images):
        n = int(group.tokens.shape[0])
        if self._scorer is None or images is None:
            return [0.0] * n, False
        t0 = time.perf_counter()
        text = prepare_clip_text(group.text, self.clip.cfg)
        scores = np.asarray(self._scorer(self.clip_params, text, images))
        record_span("pipeline/rerank", t0, time.perf_counter() - t0,
                    group_id=group.group_id, candidates=n,
                    trace_id=group.trace_id)
        counter_add("gateway.images_reranked_total", float(n))
        return [float(s) for s in scores], True

    def _rank_stage(self, group: CandidateGroup, images, scores,
                    reranked: bool) -> RankedGroup:
        n = int(group.tokens.shape[0])
        # best score first; equal scores (and the rerank-off zeros) keep
        # submission order — ranking is deterministic either way
        order = sorted(range(n), key=lambda i: (-scores[i], i))
        k = group.top_k if group.top_k else (self.default_top_k or n)
        top = []
        for i in order[:k]:
            entry = {"candidate": i, "score": scores[i],
                     "tokens": [int(t) for t in group.tokens[i]]}
            if images is not None and self.encode_pixels:
                band8 = (np.clip(images[i], 0.0, 1.0) * 255).astype(np.uint8)
                entry["pixels_b64"] = base64.b64encode(
                    band8.tobytes()).decode()
                entry["pixels_shape"] = list(band8.shape)
            top.append(entry)
        return RankedGroup(group_id=group.group_id, scores=scores,
                           order=order, top_k=top, tokens=group.tokens,
                           reranked=reranked, trace_id=group.trace_id)
