"""Host-side paged-KV bookkeeping: block pool + radix prefix cache.

graftpage's control plane. The device side (ops/paged_kv.PagedKVCache) is a
dumb block pool addressed through a page table; everything that DECIDES —
which blocks a new request maps, which prefixes are resident, what gets
copy-on-write forked, what eviction may reclaim — lives here, in plain
Python on the engine thread. That split is what keeps the no-recompile
invariant trivial to audit: the host mutates numpy page tables and integer
refcounts, uploads data, and only ever dispatches the same fixed set of
compiled programs.

``BlockPool`` — free list + per-block refcounts. A block is freed when its
refcount reaches zero; holders are (a) the rows currently mapping it and
(b) the radix tree (exactly one ref per resident node), so "evict only at
refcount 0" in the radix sense is "pool refcount == 1 (the tree's own)".

``RadixCache`` — a prefix tree over REMAPPED prompt ids (bos + pad-remap,
so identical prompts key identically) at BLOCK granularity: each full edge
is the tuple of ``block_tokens`` ids one resident block covers; a partial
trailing block hangs off its parent as a TAIL node and is only shareable on
an exact full-prefix match (its block also receives the owner's decode
tokens, so a full-prefix hit must COW-fork it — the engine does, at
admission, before any divergent write). Matching walks greedily (longest
prefix); insertion adds only missing nodes; eviction removes LRU leaves
whose block no live row maps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


class BlockPool:
    """Refcounted fixed-size block allocator (host mirror of the device
    pool). Not thread-safe — engine-thread only, like the page tables."""

    def __init__(self, num_blocks: int):
        assert num_blocks >= 1
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks
        self.cow_copies = 0      # fork ledger (kv.pages_cow_copies gauge)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def shared_count(self) -> int:
        """Blocks with more than one holder — the bytes the slab design
        would have duplicated."""
        return sum(1 for r in self._ref if r >= 2)

    def refcount(self, bid: int) -> int:
        return self._ref[bid]

    def alloc(self) -> Optional[int]:
        """One fresh block at refcount 1, or None when the pool is dry
        (caller evicts via the radix tree and retries, or defers)."""
        if not self._free:
            return None
        bid = self._free.pop()
        assert self._ref[bid] == 0
        self._ref[bid] = 1
        return bid

    def retain(self, bid: int) -> None:
        assert self._ref[bid] >= 1, f"retain of free block {bid}"
        self._ref[bid] += 1

    def release(self, bid: int) -> None:
        assert self._ref[bid] >= 1, f"release of free block {bid}"
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)


@dataclasses.dataclass
class _Node:
    """One resident block: ``edge`` is the id tuple it covers (length ==
    block_tokens for full nodes, < block_tokens for tail nodes)."""
    edge: Tuple[int, ...]
    block: int
    parent: Optional["_Node"]
    tail: bool = False
    children: Dict[Tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict)
    tails: Dict[Tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict)
    last_used: int = 0

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.tails


@dataclasses.dataclass
class Match:
    """Longest-prefix match: ``blocks`` are the matched FULL blocks in
    position order (read-only shares); ``tail_block`` is the resident tail
    block on an exact full-prefix hit (COW-fork source), else None.
    ``hit_tokens`` counts prompt positions whose KV the hit makes
    recompute-free (the engine still recomputes the final prompt position
    for its logits)."""
    blocks: List[int]
    tail_block: Optional[int]
    hit_tokens: int

    @property
    def full(self) -> bool:
        return self.tail_block is not None


class RadixCache:
    """Block-granular radix tree over remapped prompt-id tuples."""

    def __init__(self, block_tokens: int, pool: BlockPool):
        assert block_tokens >= 1
        self.block_tokens = int(block_tokens)
        self.pool = pool
        self._root = _Node(edge=(), block=-1, parent=None)
        self._clock = 0
        self._nodes = 0
        # ledger (obs_report radix hit-rate line + EngineStats)
        self.lookups = 0
        self.full_hits = 0
        self.partial_hits = 0
        self.hit_tokens_total = 0
        self.evictions = 0

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    @property
    def resident_nodes(self) -> int:
        return self._nodes

    def match(self, key: Tuple[int, ...], record: bool = True) -> Match:
        """Greedy longest-prefix walk. A full-prefix hit additionally
        requires the TAIL tuple resident (exact prompt seen before); when
        the prompt length is a block multiple there is no tail and full
        coverage of the full blocks IS the full hit (the engine then forks
        the LAST full block — it contains the final prompt position the
        width-1 logits recompute rewrites).

        ``record=False`` leaves the hit ledger untouched — the engine plans
        deferred admission units afresh every retry iteration (matched
        blocks are unprotected while a unit waits, so a cached match could
        dangle across an eviction), and counting each retry would inflate
        the hit rate and the tokens-saved ledger by the retry count; it
        commits via :meth:`record` only when the unit actually admits."""
        bt = self.block_tokens
        node, blocks = self._root, []
        n_full = len(key) // bt
        for i in range(n_full):
            edge = tuple(key[i * bt:(i + 1) * bt])
            child = node.children.get(edge)
            if child is None:
                break
            self._touch(child)
            node, blocks = child, blocks + [child.block]
        tail_block = None
        tail = tuple(key[n_full * bt:])
        if len(blocks) == n_full:
            if tail:
                tnode = node.tails.get(tail)
                if tnode is not None:
                    self._touch(tnode)
                    tail_block = tnode.block
            elif blocks:
                # block-aligned prompt: the last full block doubles as the
                # COW-fork source of a full hit
                tail_block = blocks[-1]
        hit_tokens = len(blocks) * bt
        if tail_block is not None and tail:
            hit_tokens += len(tail)
        m = Match(blocks=blocks, tail_block=tail_block,
                  hit_tokens=hit_tokens if tail_block is not None
                  else len(blocks) * bt)
        if record:
            self.record(m)
        return m

    def record(self, m: Match) -> None:
        """Commit one match to the hit ledger (see ``match(record=False)``)."""
        self.lookups += 1
        if m.full:
            self.full_hits += 1
        elif m.blocks:
            self.partial_hits += 1
        self.hit_tokens_total += m.hit_tokens

    def insert(self, key: Tuple[int, ...], full_blocks: List[int],
               tail_block: Optional[int]) -> None:
        """Register a freshly prefilled prompt's blocks. Only MISSING nodes
        are added (each new node retains its block once — the tree's own
        ref); blocks already resident keep the incumbent, and the caller's
        duplicate block simply stays private to its row. ``full_blocks``
        must cover the full-block prefix of ``key`` in order."""
        bt = self.block_tokens
        node = self._root
        for i, bid in enumerate(full_blocks):
            edge = tuple(key[i * bt:(i + 1) * bt])
            child = node.children.get(edge)
            if child is None:
                child = _Node(edge=edge, block=bid, parent=node)
                self.pool.retain(bid)
                node.children[edge] = child
                self._nodes += 1
            self._touch(child)
            node = child
        tail = tuple(key[len(full_blocks) * bt:])
        if tail and tail_block is not None and tail not in node.tails:
            tnode = _Node(edge=tail, block=tail_block, parent=node,
                          tail=True)
            self.pool.retain(tail_block)
            node.tails[tail] = tnode
            self._nodes += 1
            self._touch(tnode)

    # -- eviction ----------------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            for c in n.children.values():
                stack.append(c)
                if c.is_leaf:
                    out.append(c)
            for t in n.tails.values():
                out.append(t)
        return out

    def evictable_count(self) -> int:
        """Upper bound on blocks eviction could free RIGHT NOW (leaves no
        row maps). Interior nodes become leaves as their subtrees go, so
        full pressure can eventually reclaim more — the admission loop
        re-asks after each pass."""
        return sum(1 for leaf in self._leaves()
                   if self.pool.refcount(leaf.block) == 1)

    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks, LRU leaves first, ONLY where the tree
        holds the sole reference (refcount 1 == radix refcount 0: no live
        row maps the block). Returns the number freed."""
        freed = 0
        while freed < n:
            cands = [leaf for leaf in self._leaves()
                     if self.pool.refcount(leaf.block) == 1]
            if not cands:
                break
            victim = min(cands, key=lambda nd: nd.last_used)
            parent = victim.parent
            if victim.tail:
                del parent.tails[victim.edge]
            else:
                del parent.children[victim.edge]
            self._nodes -= 1
            self.pool.release(victim.block)
            self.evictions += 1
            freed += 1
        return freed
