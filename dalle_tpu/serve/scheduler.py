"""Slot scheduler + scheduling policies for the decode batch.

The device state is B anonymous slots; ``SlotScheduler`` maps slots ↔
requests and enforces the two scheduling invariants the engine tests pin
down (tests/test_serve.py):

  * work-conserving — after every admission pass, either no slot is free or
    the queue is empty (no idle slot while the queue holds work);
  * FIFO fairness — requests are admitted strictly in submission order (the
    queue pops FIFO and ``admit`` pairs them with free slots in order), so
    no request can be overtaken while waiting.

The POLICY layer (``PolicyQueue`` + ``SchedulingPolicy``) is the gateway's
multi-tenant extension: it changes which queued request is taken next —
priority tiers, earliest-deadline-first, and shedding of requests whose
deadline has already passed (serving a guaranteed SLO miss burns slot time
a live request could use; Orca's iteration-level scheduling makes the shed
point every admission pass, not just enqueue). FIFO stays the DEFAULT and
its fairness/work-conservation invariants stay pinned — a bare
``RequestQueue`` never reorders or sheds.

Pure Python, no jax: the engine owns the device arrays, this owns the
mapping.
"""

from __future__ import annotations

import collections
import time
from typing import (Callable, Deque, Dict, List, Optional, Sequence, Tuple)

from .queue import Request, RequestQueue


class SlotScheduler:
    def __init__(self, n_slots: int):
        assert n_slots >= 1
        self.n_slots = n_slots
        self._slots: List[Optional[Request]] = [None] * n_slots
        self.admitted_total = 0
        self.completed_total = 0
        # request ids in admit order, for FIFO-fairness auditing; bounded so
        # a long-lived engine stays O(1) — the most recent window is all a
        # fairness check needs
        self._admission_order: Deque[int] = collections.deque(maxlen=10_000)

    # -- queries -----------------------------------------------------------
    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is None]

    def active_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is not None]

    def request_at(self, slot: int) -> Optional[Request]:
        return self._slots[slot]

    @property
    def any_active(self) -> bool:
        return any(r is not None for r in self._slots)

    @property
    def occupancy(self) -> float:
        """Fraction of slots holding an in-flight request."""
        return len(self.active_slots()) / self.n_slots

    @property
    def admission_order(self) -> List[int]:
        return list(self._admission_order)

    # -- transitions -------------------------------------------------------
    def admit(self, requests: Sequence[Request]) -> List[Tuple[int, Request]]:
        """Pair requests (already FIFO from the queue) with free slots in
        slot order. Raises if handed more requests than free slots — the
        engine must size its ``take`` by ``free_slots()``."""
        free = self.free_slots()
        if len(requests) > len(free):
            raise ValueError(
                f"admit({len(requests)} requests) with only {len(free)} "
                "free slots")
        pairs = []
        for slot, req in zip(free, requests):
            self._slots[slot] = req
            self._admission_order.append(req.request_id)
            self.admitted_total += 1
            pairs.append((slot, req))
        return pairs

    def complete(self, slot: int) -> Request:
        req = self._slots[slot]
        if req is None:
            raise ValueError(f"slot {slot} is not occupied")
        self._slots[slot] = None
        self.completed_total += 1
        return req


# ---------------------------------------------------------------------------
# scheduling policies (the gateway's admission-order layer)
# ---------------------------------------------------------------------------

class SchedulingPolicy:
    """Decides which queued requests are taken next. ``order_key`` sorts the
    backlog ascending (ties broken by submission order — the queue passes
    the arrival index); ``should_shed`` drops a request at take time."""

    name = "fifo"

    def order_key(self, req: Request, arrival_idx: int):
        return arrival_idx

    def should_shed(self, req: Request, now: float) -> bool:
        return False


class FifoPolicy(SchedulingPolicy):
    """Strict submission order, never sheds — the pinned default."""


class PriorityDeadlinePolicy(SchedulingPolicy):
    """Priority tiers, then earliest deadline, then FIFO — and requests
    whose deadline already passed are shed at take time instead of occupying
    a slot for a guaranteed SLO miss. ``shed_slack_s`` keeps a just-expired
    request servable when the miss is marginal (default 0: any passed
    deadline sheds)."""

    name = "priority_deadline"

    def __init__(self, shed_slack_s: float = 0.0):
        self.shed_slack_s = float(shed_slack_s)

    def order_key(self, req: Request, arrival_idx: int):
        deadline = (req.deadline_at if req.deadline_at is not None
                    else float("inf"))
        return (-req.priority, deadline, arrival_idx)

    def should_shed(self, req: Request, now: float) -> bool:
        return (req.deadline_at is not None
                and now > req.deadline_at + self.shed_slack_s)


class PolicyQueue(RequestQueue):
    """A ``RequestQueue`` whose ``take`` follows a ``SchedulingPolicy``.

    Drop-in for the engine (same submit/take/close surface), so policy
    scheduling needs no engine change: the engine still takes up to its
    free-slot count per iteration; the policy only changes WHICH requests
    those are. Shed requests are handed to ``on_shed`` (called outside the
    lock — the gateway completes their streams with a deadline error) and
    counted in ``shed_total``. With the default ``FifoPolicy`` behavior is
    bit-identical to the base queue."""

    def __init__(self, maxsize: Optional[int] = None,
                 policy: Optional[SchedulingPolicy] = None,
                 on_shed: Optional[Callable[[Request], None]] = None):
        super().__init__(maxsize=maxsize)
        self.policy = policy if policy is not None else FifoPolicy()
        self.on_shed = on_shed
        self.shed_total = 0

    def take(self, max_n: int) -> List[Request]:
        now = time.perf_counter()
        shed: List[Request] = []
        out: List[Request] = []
        with self._lock:
            keep = []
            for req in self._q:
                if self.policy.should_shed(req, now):
                    shed.append(req)
                else:
                    keep.append(req)
            # FIFO tie-break via request_id: ids are issued monotonically
            # under the queue lock (the high-water-mark rule), so they ARE
            # the arrival order — no side table to race with submit or leak
            keep.sort(key=lambda r: self.policy.order_key(r, r.request_id))
            out = keep[:max_n]
            self._q.clear()
            self._q.extend(keep[max_n:])
            self.shed_total += len(shed)
        if self.on_shed is not None:
            for req in shed:
                self.on_shed(req)
        return out
