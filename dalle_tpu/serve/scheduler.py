"""Slot scheduler: host-side bookkeeping for the fixed device decode batch.

The device state is B anonymous slots; this maps slots ↔ requests and
enforces the two scheduling invariants the engine tests pin down
(tests/test_serve.py):

  * work-conserving — after every admission pass, either no slot is free or
    the queue is empty (no idle slot while the queue holds work);
  * FIFO fairness — requests are admitted strictly in submission order (the
    queue pops FIFO and ``admit`` pairs them with free slots in order), so
    no request can be overtaken while waiting.

Pure Python, no jax: the engine owns the device arrays, this owns the
mapping.
"""

from __future__ import annotations

import collections
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from .queue import Request


class SlotScheduler:
    def __init__(self, n_slots: int):
        assert n_slots >= 1
        self.n_slots = n_slots
        self._slots: List[Optional[Request]] = [None] * n_slots
        self.admitted_total = 0
        self.completed_total = 0
        # request ids in admit order, for FIFO-fairness auditing; bounded so
        # a long-lived engine stays O(1) — the most recent window is all a
        # fairness check needs
        self._admission_order: Deque[int] = collections.deque(maxlen=10_000)

    # -- queries -----------------------------------------------------------
    def free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is None]

    def active_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slots) if r is not None]

    def request_at(self, slot: int) -> Optional[Request]:
        return self._slots[slot]

    @property
    def any_active(self) -> bool:
        return any(r is not None for r in self._slots)

    @property
    def occupancy(self) -> float:
        """Fraction of slots holding an in-flight request."""
        return len(self.active_slots()) / self.n_slots

    @property
    def admission_order(self) -> List[int]:
        return list(self._admission_order)

    # -- transitions -------------------------------------------------------
    def admit(self, requests: Sequence[Request]) -> List[Tuple[int, Request]]:
        """Pair requests (already FIFO from the queue) with free slots in
        slot order. Raises if handed more requests than free slots — the
        engine must size its ``take`` by ``free_slots()``."""
        free = self.free_slots()
        if len(requests) > len(free):
            raise ValueError(
                f"admit({len(requests)} requests) with only {len(free)} "
                "free slots")
        pairs = []
        for slot, req in zip(free, requests):
            self._slots[slot] = req
            self._admission_order.append(req.request_id)
            self.admitted_total += 1
            pairs.append((slot, req))
        return pairs

    def complete(self, slot: int) -> Request:
        req = self._slots[slot]
        if req is None:
            raise ValueError(f"slot {slot} is not occupied")
        self._slots[slot] = None
        self.completed_total += 1
        return req
