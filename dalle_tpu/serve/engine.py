"""Slot-based continuous-batching decode engine (Orca-style iteration-level
scheduling, Yu et al., OSDI '22 — adapted to static-shape TPU serving).

A fixed device batch of B decode slots shares one KV cache. Each slot
carries its own prompt, per-row cache offset, per-row length and RNG lane —
all (B,)-shaped device arrays, so rows at ragged positions ride one
compiled program and admission never recompiles. When a row emits its last
image token it is refilled from the host-side ``RequestQueue`` on the very
next iteration by prefilling the new prompt at that row's offset in one
multi-row window (``DALLE.serve_refill``); the other rows keep decoding —
no drain, no batch re-formation.

Two jitted device programs, compiled once per engine:

  * ``refill(params, state, texts, seeds, n_rows, mask)`` — admission
    prefill for the masked rows, with per-row decode lengths (parked rows'
    cache writes drop out of bounds).
  * ``step(params, state)`` — sample one token per slot under the per-row
    key discipline, then decode it at per-row offsets
    (``DALLE.serve_decode`` → ``transformer.decode_window`` →
    ``cached_attend_window``, which self-selects the windowed Pallas
    kernel on TPU).

Correctness bar (tests/test_serve.py, scripts/serve_smoke.py): each
request's tokens are BIT-EXACT against single-request
``generate_images_tokens(text[None], PRNGKey(seed))`` for any admission
order — the engine replicates the sequential path's split-chain key
discipline per row and keeps every reduction width identical (cache
max_seq == total_seq_len).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos.faults import step_hook as chaos_step_hook
from ..models.dalle import DALLE
from ..obs import (counter_add, gauge_set, histogram_observe, record_event,
                   record_span, register_state_provider,
                   unregister_state_provider)
from ..ops.sampling import gumbel_sample_rows
from .queue import CompletedRequest, Request, RequestQueue
from .scheduler import SlotScheduler


@dataclasses.dataclass
class EngineStats:
    steps: int = 0
    refills: int = 0
    # shared-prefix admissions (graftloom): cohorts of one group admitted
    # together pay ONE text prefill; ``shared_prefills_saved`` counts the
    # (N−1) per cohort the independent path would have paid — the
    # amortization ledger serve_bench reports against
    shared_refills: int = 0
    shared_prefills_saved: int = 0
    # chunked-prefill dispatches (prefill_chunk > 0): windows split into
    # bounded chunks interleaved with decode iterations
    prefill_chunks: int = 0
    # running mean of occupancy at iterations where the queue still held
    # work — the ≥90% serving bar only means something while there IS work.
    # Sum/count (not a sample list) so a long-lived serve loop stays O(1).
    occupancy_sum: float = 0.0
    occupancy_n: int = 0
    # request ids still mid-decode when a max_steps bound tripped — they
    # were consumed from the queue and will never complete (empty on drain)
    aborted_in_flight: List[int] = dataclasses.field(default_factory=list)

    def sample_occupancy(self, value: float) -> None:
        self.occupancy_sum += float(value)
        self.occupancy_n += 1

    @property
    def progress(self) -> int:
        """Monotonic engine-iteration counter (graftward): every device
        dispatch the host loop completes — decode steps, refill windows,
        prefill chunks — advances it. A BUSY engine whose progress freezes
        is wedged; an idle one is just idle. Read cross-thread by the
        in-process :class:`~dalle_tpu.degrade.WedgeWatchdog`, the health
        verb, and (remotely) the fleet transport's frozen-progress
        check."""
        return self.steps + self.refills + self.prefill_chunks

    @property
    def occupancy_while_queued(self) -> float:
        if not self.occupancy_n:
            return 1.0
        return self.occupancy_sum / self.occupancy_n


# jitted program sharing across engines (the PR 5 jit_step precedent,
# serve-side): two engines over the SAME model object with equal program
# config compile byte-identical programs, so a replica fleet on one host —
# and every test building engines off one module fixture — should pay
# trace+compile ONCE, not once per engine. Keyed by id(model) + the
# program-shaping knobs; params stay CALL arguments, so f32/bf16/int8 param
# trees ride one cache entry via jax's own per-aval retrace. The cached
# closures bind a lightweight STAND-IN (the program-shaping attrs + model,
# nothing else) rather than the first engine — binding the engine would pin
# its whole param tree for the life of the process (GBs stranded on every
# checkpoint hot-swap). The stand-in pins the model, so id(model) keys
# never go stale; the cache is process-lifetime by design, bounded by
# distinct (model, config) pairs.
_PROGRAMS: Dict[int, Dict[tuple, tuple]] = {}

# every attribute the traced program bodies (_refill/_refill_row/_step/
# _multi_step) read off self — the stand-in carries exactly these
_PROGRAM_ATTRS = ("model", "use_kernel", "cache_dtype", "n_steps",
                  "filter_thres", "temperature", "topk_approx",
                  "num_text_tokens", "prefix_len", "park", "steps_per_sync",
                  "decode_health")


def _program_key(eng: "DecodeEngine") -> tuple:
    return (eng.slots, np.dtype(eng.cache_dtype).name, eng.filter_thres,
            eng.temperature, eng.topk_approx, eng.steps_per_sync,
            eng.use_kernel, eng.decode_health)


def _shared_programs(eng: "DecodeEngine") -> tuple:
    import types
    per_model = _PROGRAMS.setdefault(id(eng.model), {})
    key = _program_key(eng)
    fns = per_model.get(key)
    if fns is None:
        standin = types.SimpleNamespace(
            **{a: getattr(eng, a) for a in _PROGRAM_ATTRS})
        standin._step = DecodeEngine._step.__get__(standin)
        fns = (jax.jit(DecodeEngine._refill.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._refill_row.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._refill_shared.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._refill_chunk.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._multi_step.__get__(standin),
                       donate_argnums=(1,)))
        per_model[key] = fns
    return fns


@dataclasses.dataclass
class _ChunkJob:
    """One in-flight chunked-prefill admission (prefill_chunk > 0): the
    remapped prompt ids of the rows admitted together, dispatched one
    bounded window per engine iteration so neighbors' decode steps
    interleave — a fat admission can no longer stall every other row for
    its full prompt length."""
    ids: np.ndarray        # (B, prefix_len) remapped+bos'd full-vocab ids
    seeds: np.ndarray      # (B,)
    n_rows: np.ndarray     # (B,)
    mask: np.ndarray       # (B,) bool
    pairs: list            # [(slot, Request)]
    t0: float              # admission wall-clock (serve/prefill span start)
    start: int = 0         # next chunk's first position


class DecodeEngine:
    """Continuous-batching image-token decode over a DALLE model.

    ``slots``: device batch size B (every compiled program is shaped by it).
    ``cache_dtype``: KV storage dtype (f32 / bf16 / int8 — same knob as
    ``generate_images_tokens``). Sampling knobs mirror the sequential path
    so the exactness contract holds per request.

    ``use_kernel`` pins Pallas attend-kernel selection for the engine's
    decode and refill programs (None = shape-gated auto on TPU, dense
    elsewhere). Bitwise token parity with ``generate_images_tokens`` is
    guaranteed when both paths resolve to the same attend implementation —
    always true on the CPU mesh (CI enforces it there). On TPU the windowed
    and single-token kernels are DISTINCT implementations (each within
    ~2e-2 of dense, not bitwise), and auto-selection is shape-dependent per
    path; for strict parity runs pin ``use_kernel=False`` here and on the
    reference ``generate_images_tokens`` call. Auto mode trades that strict
    guarantee for kernel throughput.
    """

    def __init__(self, model: DALLE, params, *, slots: int,
                 cache_dtype=jnp.float32, filter_thres: float = 0.5,
                 temperature: float = 1.0, topk_approx: bool = False,
                 steps_per_sync: int = 1, use_kernel=None,
                 decode_health: bool = False, prefill_chunk: int = 0):
        c = model.cfg
        attn_types = tuple(c.attn_types) or ("full",)
        if any(t != "full" for t in attn_types) or c.shift_tokens:
            # same constraint set as speculative decode: per-row windows
            # have no per-row sparse-mask gather and the shift ring buffers
            # are one-token-sequential by construction
            raise ValueError(
                "the serve engine requires full attention and "
                f"shift_tokens=False (got attn_types={attn_types}, "
                f"shift_tokens={c.shift_tokens})")
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.cache_dtype = cache_dtype
        self.filter_thres = filter_thres
        self.temperature = temperature
        self.topk_approx = topk_approx
        self.use_kernel = use_kernel
        # graftpulse decode-quality taps (obs/health.py): per-row token
        # entropy + top-k mass computed IN the jitted step from the logits
        # already on device, fetched in the same host sync as the tokens —
        # zero added syncs, sampling untouched (no rng consumed), so the
        # per-request bit-exactness contract holds with the taps on.
        # Program-shaping (rides _program_key and the AOT fingerprint).
        self.decode_health = bool(decode_health)

        self.text_seq_len = c.text_seq_len
        self.prefix_len = c.text_seq_len + 1          # <bos> + text
        self.n_steps = c.image_seq_len
        self.park = c.total_seq_len                   # cache max_seq
        self.num_text_tokens = c.num_text_tokens + c.text_seq_len
        # multi-step scheduling: run K device steps per host sync
        # (lax.scan inside one program). K=1 is pure iteration-level
        # scheduling — a finished row refills on the very next token. K>1
        # amortizes per-dispatch host overhead (the serving lever when the
        # per-token program is small relative to dispatch cost — this
        # sandbox's CPU mesh) at the price of admission granularity: a
        # freed slot waits up to K-1 device steps for its refill. Token
        # exactness is unaffected — the device math is identical.
        assert steps_per_sync >= 1
        self.steps_per_sync = int(steps_per_sync)

        # grid-row granularity for streaming (on_rows): one committed row of
        # the image token grid = one fmap row
        self.row_len = c.image_fmap_size

        # chunked prefill (graftloom): window AND trickle admissions of
        # prompts longer than ``prefill_chunk`` positions dispatch as
        # bounded chunks with decode iterations interleaved — TTFT isolation
        # for the neighbors (a trickle admission becomes a one-row-masked
        # window job). Shared-prefix COHORT prefills stay one-shot: their
        # b=1 prefill is already 1/B of a window's compute, the bound
        # chunking enforces. 0 (the default) keeps the one-shot programs:
        # host loop and compiled programs are byte-identical to the
        # pre-chunking engine. Chunked tokens are bitwise ≡ unchunked
        # (tests/test_serve.py): each chunk token attends exactly the cache
        # prefix the full window would have shown it, at the same reduce
        # widths.
        assert prefill_chunk >= 0
        self.prefill_chunk = int(prefill_chunk)

        (self._refill_fn, self._refill_row_fn, self._refill_shared_fn,
         self._refill_chunk_fn, self._step_fn) = _shared_programs(self)
        self.aot_loaded = False
        self.stats = EngineStats()

    def install_executables(self, *, step=None, refill=None,
                            refill_row=None, refill_shared=None) -> None:
        """Swap the engine's jitted programs for AOT-compiled executables
        (gateway/aot.py): a cold replica then serves without retracing or
        recompiling any device program. Executables must have been lowered
        from THIS engine configuration — the aot module's fingerprint check
        enforces that; calling one with mismatched shapes/dtypes fails loudly
        at dispatch, never silently."""
        if (step is None or refill is None or refill_row is None
                or refill_shared is None):
            # a partial install would leave some programs on jit while
            # health/smoke report aot_loaded=true — the flag must mean
            # "the WHOLE cold-start path is executable-backed"
            raise ValueError("install_executables requires all four "
                             "programs (step, refill, refill_row, "
                             "refill_shared)")
        self._step_fn = step
        self._refill_fn = refill
        self._refill_row_fn = refill_row
        self._refill_shared_fn = refill_shared
        self.aot_loaded = True

    # -- device programs ---------------------------------------------------
    def _init_state(self) -> Dict:
        cache = self.model.apply(self.params, self.slots, self.cache_dtype,
                                 method=DALLE.serve_init_cache)
        B = self.slots
        texts = jax.ShapeDtypeStruct((B, self.text_seq_len), jnp.int32)
        mask = jax.ShapeDtypeStruct((B,), jnp.bool_)
        # logits dtype must match what the model emits (bf16 params emit
        # bf16 logits): a f32 placeholder would silently promote the
        # jnp.where merge and break bitwise exactness vs the sequential path
        out_shape = jax.eval_shape(
            lambda p, t, cc, m: self.model.apply(
                p, t, cc, m, method=DALLE.serve_refill),
            self.params, texts, cache, mask)
        logits_dtype = out_shape[0].dtype
        return {
            "cache": cache,
            "logits": jnp.zeros((B, out_shape[0].shape[-1]), logits_dtype),
            "cur_key": jnp.zeros((B, 2), jnp.uint32),
            "orig_key": jnp.zeros((B, 2), jnp.uint32),
            # parked until admitted: j clamps to the final step, active=False
            "t_idx": jnp.full((B,), self.n_steps, jnp.int32),
            # per-row decode length (ragged service demand — partial-grid
            # requests): tokens for a row with n < image_seq_len equal the
            # first n of the full single-request generation
            "n_row": jnp.full((B,), self.n_steps, jnp.int32),
            "active": jnp.zeros((B,), jnp.bool_),
        }

    def _refill(self, params, state, texts, seeds, n_rows, mask):
        new_keys = jax.vmap(jax.random.PRNGKey)(seeds)       # (B, 2) u32
        logits_r, cache = self.model.apply(
            params, texts, state["cache"], mask, self.use_kernel,
            method=DALLE.serve_refill)
        m1 = mask[:, None]
        return {
            "cache": cache,
            "logits": jnp.where(m1, logits_r, state["logits"]),
            "cur_key": jnp.where(m1, new_keys, state["cur_key"]),
            "orig_key": jnp.where(m1, new_keys, state["orig_key"]),
            "t_idx": jnp.where(mask, 0, state["t_idx"]),
            "n_row": jnp.where(mask, n_rows, state["n_row"]),
            "active": state["active"] | mask,
        }

    def _refill_row(self, params, state, text1, seed, n_tok, row):
        """Admit ONE request into slot ``row`` (traced scalar — one
        compiled program serves every slot): a b=1 prefill (bitwise the
        sequential ``_prefill``) scattered into the shared cache. Under
        staggered completions admissions arrive one or two rows at a time;
        this costs 1/B of the multi-row refill window, which stays the
        bulk-admission path (cold start, bursts)."""
        logits1, cache1 = self.model.apply(
            params, text1, self.cache_dtype, method=DALLE.serve_prefill_row)
        cache = dict(state["cache"])
        for name, small in cache1.items():
            big = cache[name]
            kv = jax.lax.dynamic_update_slice(big.kv, small.kv, (row, 0, 0))
            if big.scale is not None:
                sc = jax.lax.dynamic_update_slice(big.scale, small.scale,
                                                  (row, 0, 0))
                cache[name] = big.replace(kv=kv, scale=sc)
            else:
                cache[name] = big.replace(kv=kv)
        key1 = jax.random.PRNGKey(seed)
        return {
            "cache": cache,
            "logits": jax.lax.dynamic_update_slice(
                state["logits"], logits1.astype(state["logits"].dtype),
                (row, 0)),
            "cur_key": jax.lax.dynamic_update_slice(
                state["cur_key"], key1[None], (row, 0)),
            "orig_key": jax.lax.dynamic_update_slice(
                state["orig_key"], key1[None], (row, 0)),
            "t_idx": state["t_idx"].at[row].set(0),
            "n_row": state["n_row"].at[row].set(n_tok),
            "active": state["active"].at[row].set(True),
        }

    # graftir: allow=precision -- the shared-prefix refill is an
    # admission-only program: it WRITES the broadcast b=1 prefill into the
    # multi-slot int8 cache but never attends over it, so the incoming
    # rows' KV scales legitimately pass through as moved data without a
    # dequantizing multiply (graftnum orphaned-scale); the scales are
    # consumed by the very next serve_decode step, whose entry pins the
    # dequant sites.
    def _refill_shared(self, params, state, text1, seeds, n_rows, mask):
        """Shared-prefix admission (graftloom): N candidates of ONE prompt
        (masked rows) pay a single b=1 text prefill, broadcast into every
        sibling row (``DALLE.serve_refill_shared``), with per-candidate RNG
        lanes seeded independently — each candidate's tokens stay BITWISE
        identical to an independent single-candidate request, (N−1) prompt
        prefills cheaper."""
        new_keys = jax.vmap(jax.random.PRNGKey)(seeds)       # (B, 2) u32
        logits1, cache = self.model.apply(
            params, text1, state["cache"], mask, self.cache_dtype,
            method=DALLE.serve_refill_shared)
        m1 = mask[:, None]
        return {
            "cache": cache,
            "logits": jnp.where(m1, logits1.astype(state["logits"].dtype),
                                state["logits"]),
            "cur_key": jnp.where(m1, new_keys, state["cur_key"]),
            "orig_key": jnp.where(m1, new_keys, state["orig_key"]),
            "t_idx": jnp.where(mask, 0, state["t_idx"]),
            "n_row": jnp.where(mask, n_rows, state["n_row"]),
            "active": state["active"] | mask,
        }

    def _refill_chunk(self, params, state, ids_chunk, start, seeds, n_rows,
                      mask, last):
        """One bounded window of a chunked prefill: ``ids_chunk`` (B, w)
        already remapped+bos'd prompt ids written at positions
        [start, start+w) of the masked rows. Rows only turn active — and
        only then consume keys/logits — on the FINAL chunk (``last``, a
        traced scalar so one program serves every chunk of a given
        width)."""
        logits_r, cache = self.model.apply(
            params, ids_chunk, state["cache"], mask, start, self.use_kernel,
            method=DALLE.serve_refill_window)
        new_keys = jax.vmap(jax.random.PRNGKey)(seeds)
        lm = mask & last
        m1 = lm[:, None]
        return {
            "cache": cache,
            "logits": jnp.where(m1, logits_r.astype(state["logits"].dtype),
                                state["logits"]),
            "cur_key": jnp.where(m1, new_keys, state["cur_key"]),
            "orig_key": jnp.where(m1, new_keys, state["orig_key"]),
            "t_idx": jnp.where(lm, 0, state["t_idx"]),
            "n_row": jnp.where(lm, n_rows, state["n_row"]),
            "active": state["active"] | lm,
        }

    def _step(self, params, state):
        n_steps = self.n_steps
        logits, t_idx, active = (state["logits"], state["t_idx"],
                                 state["active"])
        n_row = state["n_row"]
        j = jnp.minimum(t_idx, n_row - 1)
        final = j == n_row - 1

        # per-row key discipline == the sequential split chain: tokens
        # 0..image_seq_len-2 consume one split each; only the FULL
        # sequence's last token uses fold_in(orig_key, n_steps) without
        # consuming a split. A partial-length row's final token therefore
        # still comes from the split chain — its tokens are exactly the
        # first n of the full generation.
        sp = jax.vmap(jax.random.split)(state["cur_key"])    # (B, 2, 2)
        new_key, sub = sp[:, 0], sp[:, 1]
        fin_key = jax.vmap(
            lambda k: jax.random.fold_in(k, n_steps))(state["orig_key"])
        uses_fold = final & (n_row == n_steps)
        sample_key = jnp.where(uses_fold[:, None], fin_key, sub)

        img_logits = logits[:, self.num_text_tokens:]
        stats = {}
        if self.decode_health:
            # per-row quality of the distribution being sampled FROM (the
            # pre-gumbel logits): entropy + top-k mass, (B,) f32 each —
            # fetched with the tokens at the same sync
            from ..obs.health import decode_quality
            stats = decode_quality(img_logits)
        tok = gumbel_sample_rows(sample_key, img_logits,
                                 thres=self.filter_thres,
                                 temperature=self.temperature,
                                 approx=self.topk_approx)

        decode_rows = active & ~final
        offsets = jnp.where(decode_rows, self.prefix_len + j, self.park)
        new_logits, cache = self.model.apply(
            params, tok, j, offsets, state["cache"], self.use_kernel,
            method=DALLE.serve_decode)
        finished = active & final
        state = {
            "cache": cache,
            "logits": jnp.where(decode_rows[:, None], new_logits, logits),
            "cur_key": jnp.where(uses_fold[:, None], state["cur_key"],
                                 new_key),
            "orig_key": state["orig_key"],
            "t_idx": jnp.where(active, t_idx + 1, t_idx),
            "n_row": n_row,
            "active": decode_rows,
        }
        return tok, finished, stats, state

    def _multi_step(self, params, state):
        """steps_per_sync × _step in one program; (K, B) tokens/finished
        (+ (K, B) decode-quality stats when ``decode_health`` — an empty
        dict otherwise, so the program signature is stable)."""
        if self.steps_per_sync == 1:
            tok, finished, stats, state = self._step(params, state)
            return (tok[None], finished[None],
                    jax.tree.map(lambda x: x[None], stats), state)

        def body(carry, _):
            tok, finished, stats, carry = self._step(params, carry)
            return carry, (tok, finished, stats)

        state, (toks, fins, stats) = jax.lax.scan(body, state, None,
                                                  length=self.steps_per_sync)
        return toks, fins, stats, state

    # -- host loop ---------------------------------------------------------
    def _pad_text(self, text: np.ndarray) -> np.ndarray:
        out = np.zeros((self.text_seq_len,), np.int32)
        n = min(len(text), self.text_seq_len)
        out[:n] = text[:n]
        return out

    def _n_tokens(self, req: Request) -> int:
        if req.max_tokens is None:
            return self.n_steps
        return int(np.clip(req.max_tokens, 1, self.n_steps))

    def _remap_bos_host(self, texts: np.ndarray) -> np.ndarray:
        """Host-side ``remap_and_bos`` for the chunked-prefill path: 0-pads
        → unique per-position pad ids, <bos>=0 prepended. Integer-exact vs
        the device remap, so every chunk gathers the same embedding rows the
        one-shot window would."""
        B, T = texts.shape
        pad_ids = (np.arange(T, dtype=np.int32)
                   + np.int32(self.num_text_tokens - self.text_seq_len))
        out = np.where(texts == 0, pad_ids[None, :], texts).astype(np.int32)
        return np.concatenate([np.zeros((B, 1), np.int32), out], axis=1)

    @staticmethod
    def _split_cohorts(pairs):
        """Partition one admission pass into shared-prefix cohorts (≥2
        members of one group with identical text — the /v1/images fan-out)
        and singles. A group split across admission passes still shares
        within each pass; a lone straggler rides the single paths. Group
        members with mismatched text (a misuse the gateway never produces)
        are demoted to singles rather than silently prefilled with the
        first member's prompt."""
        by_gid: Dict[int, list] = {}
        singles = []
        for slot, req in pairs:
            if req.group_id is not None:
                by_gid.setdefault(req.group_id, []).append((slot, req))
            else:
                singles.append((slot, req))
        cohorts = []
        for members in by_gid.values():
            text0 = members[0][1].text
            if len(members) >= 2 and all(
                    np.array_equal(r.text, text0) for _, r in members[1:]):
                cohorts.append(members)
            else:
                singles.extend(members)
        singles.sort(key=lambda p: p[0])
        return cohorts, singles

    def run(self, queue: RequestQueue, *, max_steps: Optional[int] = None,
            poll_s: float = 0.02,
            on_complete=None, on_rows=None) -> List[CompletedRequest]:
        """Serve until the queue is drained (closed + empty + nothing in
        flight). Producers may keep submitting from other threads while
        this runs. Returns completions in completion order.

        A long-lived deployment (queue held open indefinitely) should pass
        ``on_complete``: each CompletedRequest is handed to it the moment
        its last token lands and is NOT accumulated — the return value is
        then an empty list and memory stays O(slots) for the life of the
        loop. Without it, every completion (including its full token array)
        is retained until drain.

        ``on_rows(request, row_idx, row_tokens)`` streams partial results:
        called the moment a committed GRID ROW of the image token field
        finishes (``row_len == image_fmap_size`` tokens — the slot state's
        per-row offset crossing a row boundary), plus once for a trailing
        partial row of a ``max_tokens`` request just before its completion.
        Concatenating a request's row_tokens in row_idx order reproduces its
        final token sequence exactly, so a streaming consumer (the
        gateway's SSE writer, which dVAE-decodes committed rows into
        preview pixels) needs no end-of-stream reconciliation. Callbacks
        run on the engine thread — keep them O(row) and non-blocking.

        ``max_steps`` is a harness bound (bench/smoke), not a graceful
        drain: requests still mid-decode when it trips are abandoned —
        already consumed from the queue, never completed. Their ids are
        recorded in ``stats.aborted_in_flight`` so the loss is visible."""
        B = self.slots
        sched = SlotScheduler(B)
        state = self._init_state()
        buffers: Dict[int, List[int]] = {}
        row_t0: Dict[int, float] = {}      # per-slot start of the open row
        # per-slot decode-quality accumulators [Σentropy, Σtopk_mass, n]
        # (decode_health only; reset at admission, reduced at completion)
        qual: Dict[int, List[float]] = {}
        completed: List[CompletedRequest] = []
        self.stats = EngineStats()

        # flight-recorder / watchdog state provider: while this loop is
        # live, a stall report or post-mortem bundle carries the queue
        # depth, slot occupancy and in-flight request ids — the serve-side
        # "where was everyone" snapshot. Read from other threads; every
        # value is a point-in-time copy and the collector tolerates races.
        def _engine_state() -> dict:
            inflight = []
            for s in sched.active_slots():
                r = sched.request_at(s)
                if r is not None:
                    inflight.append({
                        "slot": s, "request_id": r.request_id,
                        "trace_id": r.trace_id,
                        "tokens_done": len(buffers.get(s, ()))})
            return {"queue_depth": queue.qsize(),
                    "slot_occupancy": sched.occupancy,
                    "steps": self.stats.steps, "inflight": inflight}

        provider = register_state_provider(
            f"serve.engine[{threading.current_thread().name}]",
            _engine_state)
        try:
            return self._run(queue, sched, state, buffers, row_t0, qual,
                             completed, max_steps=max_steps, poll_s=poll_s,
                             on_complete=on_complete, on_rows=on_rows)
        finally:
            unregister_state_provider(provider)

    def _admit_shared(self, state, members, row_t0):
        """One shared-prefix cohort: a single b=1 prefill broadcast into
        every member's slot, per-candidate RNG lanes from each member's own
        seed."""
        B = self.slots
        seeds = np.zeros((B,), np.int32)
        n_rows = np.full((B,), self.n_steps, np.int32)
        mask = np.zeros((B,), bool)
        for slot, req in members:
            seeds[slot] = req.seed
            n_rows[slot] = self._n_tokens(req)
            mask[slot] = True
        text1 = self._pad_text(members[0][1].text)[None]
        t0 = time.perf_counter()
        state = self._refill_shared_fn(self.params, state, text1, seeds,
                                       n_rows, mask)
        t1 = time.perf_counter()
        self.stats.refills += 1
        self.stats.shared_refills += 1
        self.stats.shared_prefills_saved += len(members) - 1
        record_span("pipeline/prefill_shared", t0, t1 - t0,
                    group_id=members[0][1].group_id,
                    candidates=len(members),
                    trace_id=members[0][1].trace_id)
        for slot, req in members:
            record_span("serve/prefill", t0, t1 - t0,
                        request_id=req.request_id, trace_id=req.trace_id,
                        mode="shared")
            row_t0[slot] = t1
        return state

    def _dispatch_chunk(self, state, chunk_jobs, pending, row_t0):
        """Advance the oldest pending chunked prefill by ONE bounded window
        (the per-iteration budget that keeps neighbors' decode interleaved);
        on the final chunk the rows turn active and their prefill spans
        close."""
        job = chunk_jobs[0]
        prefix = job.ids.shape[1]
        w = min(self.prefill_chunk, prefix - job.start)
        last = job.start + w >= prefix
        t0 = time.perf_counter()
        state = self._refill_chunk_fn(
            self.params, state, job.ids[:, job.start:job.start + w],
            np.int32(job.start), job.seeds, job.n_rows, job.mask,
            np.bool_(last))
        t1 = time.perf_counter()
        self.stats.prefill_chunks += 1
        record_span("serve/prefill_chunk", t0, t1 - t0,
                    start=job.start, width=w,
                    step=self.stats.steps,
                    trace_id=job.pairs[0][1].trace_id)
        histogram_observe("serve.prefill_chunk_seconds", t1 - t0,
                          trace_id=job.pairs[0][1].trace_id)
        job.start += w
        if last:
            chunk_jobs.pop(0)
            self.stats.refills += 1
            for slot, req in job.pairs:
                pending.discard(slot)
                record_span("serve/prefill", job.t0, t1 - job.t0,
                            request_id=req.request_id,
                            trace_id=req.trace_id, mode="chunked")
                row_t0[slot] = t1
        return state

    def _run(self, queue, sched, state, buffers, row_t0, qual, completed, *,
             max_steps, poll_s, on_complete, on_rows):
        B = self.slots
        chunk_jobs: List[_ChunkJob] = []
        pending: set = set()       # slots admitted but mid-chunked-prefill
        while not (queue.drained and not sched.any_active):
            if max_steps is not None and self.stats.steps >= max_steps:
                break

            # admission: fill every free slot the queue can cover, FIFO
            pre_q = queue.qsize()
            free = sched.free_slots()
            admitted = 0
            if free:
                reqs = queue.take(len(free))
                admitted = len(reqs)
                if reqs:
                    pairs = sched.admit(reqs)
                    now = time.perf_counter()
                    for slot, req in pairs:
                        req.admitted_at = now
                        buffers[slot] = []
                        qual[slot] = [0.0, 0.0, 0]
                        # queue wait as its own span (admission SLO input:
                        # TTFT = queue wait + prefill + first step) + gauge
                        record_span("serve/request_queue_wait",
                                    req.submitted_at, now - req.submitted_at,
                                    request_id=req.request_id,
                                    trace_id=req.trace_id)
                        gauge_set("serve.queue_wait_s",
                                  now - req.submitted_at)
                        histogram_observe("serve.queue_wait_seconds",
                                          now - req.submitted_at,
                                          trace_id=req.trace_id)
                        record_event("request_admitted", slot=slot,
                                     request_id=req.request_id,
                                     trace_id=req.trace_id)
                    # shared-prefix cohorts first (one prefill per group),
                    # then singles through the classic window/trickle split
                    cohorts, singles = self._split_cohorts(pairs)
                    for members in cohorts:
                        state = self._admit_shared(state, members, row_t0)
                    chunk_on = 0 < self.prefill_chunk < self.prefix_len
                    if singles and (2 * len(singles) >= B or chunk_on):
                        # bulk admission: one multi-row refill window —
                        # chunked into bounded, decode-interleaved pieces
                        # when prefill_chunk caps the per-dispatch width.
                        # chunk-on also routes TRICKLE-size admissions here
                        # (a one-row-masked window): a fat single admission
                        # must obey the same per-dispatch bound, else the
                        # staggered-completion steady state reintroduces
                        # exactly the TTFT stall the knob exists to cap
                        texts = np.zeros((B, self.text_seq_len), np.int32)
                        seeds = np.zeros((B,), np.int32)
                        n_rows = np.full((B,), self.n_steps, np.int32)
                        mask = np.zeros((B,), bool)
                        for slot, req in singles:
                            texts[slot] = self._pad_text(req.text)
                            seeds[slot] = req.seed
                            n_rows[slot] = self._n_tokens(req)
                            mask[slot] = True
                        if 0 < self.prefill_chunk < self.prefix_len:
                            chunk_jobs.append(_ChunkJob(
                                ids=self._remap_bos_host(texts),
                                seeds=seeds, n_rows=n_rows, mask=mask,
                                pairs=list(singles),
                                t0=time.perf_counter()))
                            pending.update(s for s, _ in singles)
                        else:
                            t0 = time.perf_counter()
                            state = self._refill_fn(self.params, state,
                                                    texts, seeds, n_rows,
                                                    mask)
                            t1 = time.perf_counter()
                            self.stats.refills += 1
                            # one shared prefill window, one span per
                            # admitted request (each request's timeline owns
                            # its prefill segment; dur is the host dispatch
                            # wall)
                            for slot, req in singles:
                                record_span("serve/prefill", t0, t1 - t0,
                                            request_id=req.request_id,
                                            trace_id=req.trace_id,
                                            mode="window")
                                row_t0[slot] = t1
                    elif singles:
                        # trickle admission (staggered completions, chunking
                        # off): per-row scatter-prefill, 1/B the window's
                        # compute
                        for slot, req in singles:
                            t0 = time.perf_counter()
                            state = self._refill_row_fn(
                                self.params, state,
                                self._pad_text(req.text)[None],
                                np.int32(req.seed),
                                np.int32(self._n_tokens(req)),
                                np.int32(slot))
                            t1 = time.perf_counter()
                            self.stats.refills += 1
                            record_span("serve/prefill", t0, t1 - t0,
                                        request_id=req.request_id,
                                        trace_id=req.trace_id, mode="row")
                            row_t0[slot] = t1
            # work-conservation sample: requests that were already queued
            # at the take instant and still went unplaced must leave every
            # slot busy, so occupancy is sampled exactly then (an idle slot
            # here is a real violation, not tautologically 1.0). A request
            # landing after the take is admitted next iteration and is
            # deliberately excluded — arrival-bound, not an idle-slot bug.
            backlog = (pre_q - admitted) > 0
            gauge_set("serve.queue_depth", float(queue.qsize()))
            gauge_set("serve.slot_occupancy", sched.occupancy)

            if chunk_jobs:
                # one bounded prefill window per iteration, so the decode
                # step below keeps interleaving — the TTFT-isolation bar
                state = self._dispatch_chunk(state, chunk_jobs, pending,
                                             row_t0)

            if not any(s not in pending for s in sched.active_slots()):
                if chunk_jobs:
                    continue          # keep driving the pending prefill
                if queue.drained:
                    break
                queue.wait_nonempty(timeout=poll_s)
                continue

            if backlog:
                self.stats.sample_occupancy(sched.occupancy)

            # chaos hook (graftfleet): an env-installed FaultPlan can
            # kill/hang/slow a REPLICA PROCESS at decode-iteration
            # granularity — mid-stream, between row commits — which is
            # what the fleet smoke's drain/kill scenarios script. One
            # module-global None check when chaos is off (the
            # BaseTrainer.fit precedent, serve-side).
            chaos_step_hook(self.stats.steps)

            toks, fins, qstats, state = self._step_fn(self.params, state)
            toks = np.asarray(toks)               # (K, B)
            fins = np.asarray(fins)
            # decode-quality stats ride the SAME host sync as the tokens
            # (empty dict when decode_health is off)
            q_ent = np.asarray(qstats["entropy"]) if qstats else None
            q_mass = np.asarray(qstats["topk_mass"]) if qstats else None
            now = time.perf_counter()
            for k in range(toks.shape[0]):
                active = [s for s in sched.active_slots()
                          if s not in pending]
                if not active:
                    break
                for slot in active:
                    req = sched.request_at(slot)
                    if req.first_token_at is None:
                        req.first_token_at = now
                    buf = buffers[slot]
                    buf.append(int(toks[k, slot]))
                    if q_ent is not None:
                        acc = qual.setdefault(slot, [0.0, 0.0, 0])
                        acc[0] += float(q_ent[k, slot])
                        acc[1] += float(q_mass[k, slot])
                        acc[2] += 1
                    if len(buf) % self.row_len == 0:
                        row = len(buf) // self.row_len - 1
                        # one committed grid row = one timeline segment
                        # (host-sync granularity: rows finishing inside one
                        # multi-step dispatch share its sync timestamp)
                        t0r = row_t0.get(slot, now)
                        record_span("serve/decode_row", t0r, now - t0r,
                                    request_id=req.request_id,
                                    trace_id=req.trace_id, row=row)
                        histogram_observe("serve.decode_row_seconds",
                                          now - t0r,
                                          trace_id=req.trace_id)
                        row_t0[slot] = now
                        if on_rows is not None:
                            on_rows(req, row, buf[row * self.row_len:])
                counter_add("serve.tokens_emitted_total",
                            float(len(active)))
                for slot in active:
                    if not fins[k, slot]:
                        continue
                    req = sched.complete(slot)
                    tail = len(buffers[slot]) % self.row_len
                    if tail:
                        # trailing partial row of a max_tokens request
                        t0r = row_t0.get(slot, now)
                        record_span("serve/decode_row", t0r, now - t0r,
                                    request_id=req.request_id,
                                    trace_id=req.trace_id,
                                    row=len(buffers[slot]) // self.row_len,
                                    partial=True)
                        if on_rows is not None:
                            on_rows(req, len(buffers[slot]) // self.row_len,
                                    buffers[slot][-tail:])
                    row_t0.pop(slot, None)
                    cr = CompletedRequest(
                        request_id=req.request_id,
                        tokens=np.asarray(buffers.pop(slot), np.int32),
                        seed=req.seed,
                        submitted_at=req.submitted_at,
                        admitted_at=req.admitted_at,
                        first_token_at=req.first_token_at,
                        completed_at=now)
                    if on_complete is not None:
                        on_complete(cr)
                    else:
                        completed.append(cr)
                    # per-request decode quality (graftpulse): means of the
                    # in-jit entropy/top-k taps plus the host-side
                    # repeated-token ratio. Per-request values travel as
                    # SPAN ARGS tagged with the trace_id (bounded ring) and
                    # as unlabeled aggregate gauges — never as metric
                    # labels, which would be unbounded Prometheus
                    # cardinality (graftlint: unbounded-metric-label)
                    q_args = {}
                    acc = qual.pop(slot, None)
                    if acc is not None and acc[2] > 0:
                        t = cr.tokens
                        rep = (float(np.mean(t[1:] == t[:-1]))
                               if t.shape[0] > 1 else 0.0)
                        q_args = {"entropy": round(acc[0] / acc[2], 4),
                                  "topk_mass": round(acc[1] / acc[2], 4),
                                  "repeat_ratio": round(rep, 4)}
                        gauge_set("health.decode_entropy", acc[0] / acc[2])
                        gauge_set("health.decode_topk_mass", acc[1] / acc[2])
                        gauge_set("health.decode_repeat_ratio", rep)
                        record_event("decode_quality",
                                     request_id=req.request_id,
                                     trace_id=req.trace_id, **q_args)
                    # retrospective spans: requests overlap, so the
                    # stack-based span() contract cannot hold — see
                    # obs.record_span
                    record_span("serve/request", req.admitted_at,
                                now - req.admitted_at,
                                request_id=req.request_id,
                                trace_id=req.trace_id,
                                tokens=int(cr.tokens.shape[0]), **q_args)
                    record_span("serve/request_ttft", req.submitted_at,
                                cr.ttft_s, request_id=req.request_id,
                                trace_id=req.trace_id)
                    # native histogram (graftlens): the latency SHAPE a
                    # single gauge cannot carry — p50/p95 render from the
                    # cumulative buckets (obs_report), fleet-wide because
                    # the collector sums buckets across processes
                    histogram_observe("serve.ttft_seconds", cr.ttft_s,
                                      trace_id=req.trace_id)
                    record_event("request_completed",
                                 request_id=req.request_id,
                                 trace_id=req.trace_id,
                                 latency_s=cr.latency_s)
                    counter_add("serve.requests_completed_total", 1.0)
                    gauge_set("serve.request_latency_s", cr.latency_s)
                self.stats.steps += 1
        self.stats.aborted_in_flight = [
            sched.request_at(s).request_id for s in sched.active_slots()]
        return completed
