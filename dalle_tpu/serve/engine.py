"""Slot-based continuous-batching decode engine (Orca-style iteration-level
scheduling, Yu et al., OSDI '22 — adapted to static-shape TPU serving).

A fixed device batch of B decode slots shares one KV cache. Each slot
carries its own prompt, per-row cache offset, per-row length and RNG lane —
all (B,)-shaped device arrays, so rows at ragged positions ride one
compiled program and admission never recompiles. When a row emits its last
image token it is refilled from the host-side ``RequestQueue`` on the very
next iteration by prefilling the new prompt at that row's offset in one
multi-row window (``DALLE.serve_refill``); the other rows keep decoding —
no drain, no batch re-formation.

Two jitted device programs, compiled once per engine:

  * ``refill(params, state, texts, seeds, n_rows, mask)`` — admission
    prefill for the masked rows, with per-row decode lengths (parked rows'
    cache writes drop out of bounds).
  * ``step(params, state)`` — sample one token per slot under the per-row
    key discipline, then decode it at per-row offsets
    (``DALLE.serve_decode`` → ``transformer.decode_window`` →
    ``cached_attend_window``, which self-selects the windowed Pallas
    kernel on TPU).

Correctness bar (tests/test_serve.py, scripts/serve_smoke.py): each
request's tokens are BIT-EXACT against single-request
``generate_images_tokens(text[None], PRNGKey(seed))`` for any admission
order — the engine replicates the sequential path's split-chain key
discipline per row and keeps every reduction width identical (cache
max_seq == total_seq_len).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos.faults import step_hook as chaos_step_hook
from ..models.dalle import DALLE
from ..obs import (counter_add, gauge_set, histogram_observe, record_event,
                   record_span, register_state_provider,
                   unregister_state_provider)
from ..ops.sampling import gumbel_sample_rows
from .paged import BlockPool, RadixCache
from .queue import CompletedRequest, Request, RequestQueue
from .scheduler import SlotScheduler


@dataclasses.dataclass
class EngineStats:
    steps: int = 0
    refills: int = 0
    # shared-prefix admissions (graftloom): cohorts of one group admitted
    # together pay ONE text prefill; ``shared_prefills_saved`` counts the
    # (N−1) per cohort the independent path would have paid — the
    # amortization ledger serve_bench reports against
    shared_refills: int = 0
    shared_prefills_saved: int = 0
    # chunked-prefill dispatches (prefill_chunk > 0): windows split into
    # bounded chunks interleaved with decode iterations
    prefill_chunks: int = 0
    # running mean of occupancy at iterations where the queue still held
    # work — the ≥90% serving bar only means something while there IS work.
    # Sum/count (not a sample list) so a long-lived serve loop stays O(1).
    occupancy_sum: float = 0.0
    occupancy_n: int = 0
    # paged-KV ledger (graftpage): radix prefix-cache outcomes, COW forks
    # and LRU evictions of the block pool. ``prefix_hit_tokens`` counts the
    # prompt positions admission mapped from resident blocks instead of
    # recomputing — the prefill compute the radix cache saved, in tokens.
    radix_full_hits: int = 0
    radix_partial_hits: int = 0
    radix_misses: int = 0
    prefix_hit_tokens: int = 0
    cow_forks: int = 0
    pages_evicted: int = 0
    # request ids still mid-decode when a max_steps bound tripped — they
    # were consumed from the queue and will never complete (empty on drain)
    aborted_in_flight: List[int] = dataclasses.field(default_factory=list)

    def sample_occupancy(self, value: float) -> None:
        self.occupancy_sum += float(value)
        self.occupancy_n += 1

    @property
    def progress(self) -> int:
        """Monotonic engine-iteration counter (graftward): every device
        dispatch the host loop completes — decode steps, refill windows,
        prefill chunks — advances it. A BUSY engine whose progress freezes
        is wedged; an idle one is just idle. Read cross-thread by the
        in-process :class:`~dalle_tpu.degrade.WedgeWatchdog`, the health
        verb, and (remotely) the fleet transport's frozen-progress
        check."""
        return self.steps + self.refills + self.prefill_chunks

    @property
    def occupancy_while_queued(self) -> float:
        if not self.occupancy_n:
            return 1.0
        return self.occupancy_sum / self.occupancy_n


# jitted program sharing across engines (the PR 5 jit_step precedent,
# serve-side): two engines over the SAME model object with equal program
# config compile byte-identical programs, so a replica fleet on one host —
# and every test building engines off one module fixture — should pay
# trace+compile ONCE, not once per engine. Keyed by id(model) + the
# program-shaping knobs; params stay CALL arguments, so f32/bf16/int8 param
# trees ride one cache entry via jax's own per-aval retrace. The cached
# closures bind a lightweight STAND-IN (the program-shaping attrs + model,
# nothing else) rather than the first engine — binding the engine would pin
# its whole param tree for the life of the process (GBs stranded on every
# checkpoint hot-swap). The stand-in pins the model, so id(model) keys
# never go stale; the cache is process-lifetime by design, bounded by
# distinct (model, config) pairs.
_PROGRAMS: Dict[int, Dict[tuple, tuple]] = {}

# every attribute the traced program bodies (_refill/_refill_row/_step/
# _multi_step) read off self — the stand-in carries exactly these
_PROGRAM_ATTRS = ("model", "use_kernel", "cache_dtype", "n_steps",
                  "filter_thres", "temperature", "topk_approx",
                  "num_text_tokens", "prefix_len", "park", "steps_per_sync",
                  "decode_health")


def _program_key(eng: "DecodeEngine") -> tuple:
    return (eng.slots, np.dtype(eng.cache_dtype).name, eng.filter_thres,
            eng.temperature, eng.topk_approx, eng.steps_per_sync,
            eng.use_kernel, eng.decode_health)


def _shared_programs(eng: "DecodeEngine") -> tuple:
    import types
    per_model = _PROGRAMS.setdefault(id(eng.model), {})
    key = _program_key(eng)
    fns = per_model.get(key)
    if fns is None:
        standin = types.SimpleNamespace(
            **{a: getattr(eng, a) for a in _PROGRAM_ATTRS})
        standin._step = DecodeEngine._step.__get__(standin)
        fns = (jax.jit(DecodeEngine._refill.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._refill_row.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._refill_shared.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._refill_chunk.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._multi_step.__get__(standin),
                       donate_argnums=(1,)),
               jax.jit(DecodeEngine._cow_copy.__get__(standin),
                       donate_argnums=(0,)))
        per_model[key] = fns
    return fns


# -- paged-state plumbing (graftpage) ---------------------------------------
# The page table is ONE state leaf (``state["pages"]``), bound into every
# layer's PagedKVCache inside the traced program bodies and stripped before
# the state is returned: a per-layer pages field would make donation alias
# the same buffer ``depth`` times, and the host would have to upload depth
# copies per admission instead of one. Dense engines have no "pages" key and
# both helpers are identity on their cache.

def _bind_cache(state):
    pages = state.get("pages")
    if pages is None:
        return state["cache"]
    return {name: c.replace(pages=pages)
            for name, c in state["cache"].items()}


def _unbind_cache(cache):
    return {name: (c.replace(pages=None) if hasattr(c, "pool") else c)
            for name, c in cache.items()}


def _carry(state, new):
    """Program-body return helper: the explicit per-program updates plus
    pass-through of the admission-data leaves (page table, CFG pairing) the
    host mutates between dispatches. Keeping them state leaves — data, not
    shape — is what lets admission, COW forks and radix hits happen with
    zero recompiles."""
    out = dict(new)
    for k in ("pages", "pair", "cfg", "uncond"):
        if k in state:
            out[k] = state[k]
    return out


@dataclasses.dataclass
class _ChunkJob:
    """One in-flight chunked-prefill admission (prefill_chunk > 0): the
    remapped prompt ids of the rows admitted together, dispatched one
    bounded window per engine iteration so neighbors' decode steps
    interleave — a fat admission can no longer stall every other row for
    its full prompt length."""
    ids: np.ndarray        # (B, prefix_len) remapped+bos'd full-vocab ids
    seeds: np.ndarray      # (B,)
    n_rows: np.ndarray     # (B,)
    mask: np.ndarray       # (B,) bool
    pairs: list            # [(slot, Request)]
    t0: float              # admission wall-clock (serve/prefill span start)
    start: int = 0         # next chunk's first position


class DecodeEngine:
    """Continuous-batching image-token decode over a DALLE model.

    ``slots``: device batch size B (every compiled program is shaped by it).
    ``cache_dtype``: KV storage dtype (f32 / bf16 / int8 — same knob as
    ``generate_images_tokens``). Sampling knobs mirror the sequential path
    so the exactness contract holds per request.

    ``use_kernel`` pins Pallas attend-kernel selection for the engine's
    decode and refill programs (None = shape-gated auto on TPU, dense
    elsewhere). Bitwise token parity with ``generate_images_tokens`` is
    guaranteed when both paths resolve to the same attend implementation —
    always true on the CPU mesh (CI enforces it there). On TPU the windowed
    and single-token kernels are DISTINCT implementations (each within
    ~2e-2 of dense, not bitwise), and auto-selection is shape-dependent per
    path; for strict parity runs pin ``use_kernel=False`` here and on the
    reference ``generate_images_tokens`` call. Auto mode trades that strict
    guarantee for kernel throughput.
    """

    def __init__(self, model: DALLE, params, *, slots: int,
                 cache_dtype=jnp.float32, filter_thres: float = 0.5,
                 temperature: float = 1.0, topk_approx: bool = False,
                 steps_per_sync: int = 1, use_kernel=None,
                 decode_health: bool = False, prefill_chunk: int = 0,
                 kv_block_tokens: int = 0,
                 kv_pool_blocks: Optional[int] = None,
                 radix_cache: bool = True):
        c = model.cfg
        attn_types = tuple(c.attn_types) or ("full",)
        if any(t != "full" for t in attn_types) or c.shift_tokens:
            # same constraint set as speculative decode: per-row windows
            # have no per-row sparse-mask gather and the shift ring buffers
            # are one-token-sequential by construction
            raise ValueError(
                "the serve engine requires full attention and "
                f"shift_tokens=False (got attn_types={attn_types}, "
                f"shift_tokens={c.shift_tokens})")
        self.model = model
        self.params = params
        self.slots = int(slots)
        self.cache_dtype = cache_dtype
        self.filter_thres = filter_thres
        self.temperature = temperature
        self.topk_approx = topk_approx
        self.use_kernel = use_kernel
        # graftpulse decode-quality taps (obs/health.py): per-row token
        # entropy + top-k mass computed IN the jitted step from the logits
        # already on device, fetched in the same host sync as the tokens —
        # zero added syncs, sampling untouched (no rng consumed), so the
        # per-request bit-exactness contract holds with the taps on.
        # Program-shaping (rides _program_key and the AOT fingerprint).
        self.decode_health = bool(decode_health)

        self.text_seq_len = c.text_seq_len
        self.prefix_len = c.text_seq_len + 1          # <bos> + text
        self.n_steps = c.image_seq_len
        self.park = c.total_seq_len                   # cache max_seq
        self.num_text_tokens = c.num_text_tokens + c.text_seq_len
        # multi-step scheduling: run K device steps per host sync
        # (lax.scan inside one program). K=1 is pure iteration-level
        # scheduling — a finished row refills on the very next token. K>1
        # amortizes per-dispatch host overhead (the serving lever when the
        # per-token program is small relative to dispatch cost — this
        # sandbox's CPU mesh) at the price of admission granularity: a
        # freed slot waits up to K-1 device steps for its refill. Token
        # exactness is unaffected — the device math is identical.
        assert steps_per_sync >= 1
        self.steps_per_sync = int(steps_per_sync)

        # grid-row granularity for streaming (on_rows): one committed row of
        # the image token grid = one fmap row
        self.row_len = c.image_fmap_size

        # chunked prefill (graftloom): window AND trickle admissions of
        # prompts longer than ``prefill_chunk`` positions dispatch as
        # bounded chunks with decode iterations interleaved — TTFT isolation
        # for the neighbors (a trickle admission becomes a one-row-masked
        # window job). Shared-prefix COHORT prefills stay one-shot: their
        # b=1 prefill is already 1/B of a window's compute, the bound
        # chunking enforces. 0 (the default) keeps the one-shot programs:
        # host loop and compiled programs are byte-identical to the
        # pre-chunking engine. Chunked tokens are bitwise ≡ unchunked
        # (tests/test_serve.py): each chunk token attends exactly the cache
        # prefix the full window would have shown it, at the same reduce
        # widths.
        assert prefill_chunk >= 0
        self.prefill_chunk = int(prefill_chunk)

        # paged KV (graftpage): kv_block_tokens > 0 swaps the dense per-slot
        # slab for a shared block pool + (B, max_blocks) page table. Pool
        # size is in BLOCKS (the HBM knob: blocks × block_tokens × 2hd ×
        # itemsize bytes per layer); the default gives every slot its full
        # private footprint — the interesting deployments size it SMALLER
        # and let the radix cache make up the difference. Admission walks
        # the radix tree per prompt, maps resident blocks, COW-forks the
        # divergent tail and prefills only the miss suffix; the admission
        # suffix rides _refill_chunk at the fixed width set {block_tokens,
        # prefix_len % block_tokens, 1}, so paged engines and the explicit
        # prefill_chunk knob are mutually exclusive (the block size IS the
        # chunk bound).
        assert kv_block_tokens >= 0
        self.kv_block_tokens = int(kv_block_tokens)
        self.paged = self.kv_block_tokens > 0
        self.radix_cache = bool(radix_cache)
        if self.paged:
            if self.prefill_chunk:
                raise ValueError(
                    "kv_block_tokens and prefill_chunk are mutually "
                    "exclusive: paged admission already dispatches prefill "
                    "in block-width chunks")
            bt = self.kv_block_tokens
            self.max_blocks = -(-self.park // bt)      # blocks per slot
            pool_blocks = (int(kv_pool_blocks) if kv_pool_blocks
                           else self.slots * self.max_blocks)
            # progress guarantee: the largest admission unit (a CFG pair =
            # two full rows) must fit the pool outright, else it can never
            # be admitted no matter what eviction frees
            min_need = self.max_blocks * (2 if self.slots >= 2 else 1)
            if pool_blocks < min_need:
                raise ValueError(
                    f"kv_pool_blocks={pool_blocks} cannot hold one "
                    f"admission unit ({min_need} blocks of "
                    f"{bt} tokens)")
            self.kv_pool_blocks = pool_blocks
        else:
            self.max_blocks = 0
            self.kv_pool_blocks = 0

        (self._refill_fn, self._refill_row_fn, self._refill_shared_fn,
         self._refill_chunk_fn, self._step_fn,
         self._cow_copy_fn) = _shared_programs(self)
        self.aot_loaded = False
        self.stats = EngineStats()
        # host-side paged control plane — (re)built per run()
        self.block_pool: Optional[BlockPool] = None
        self.radix: Optional[RadixCache] = None

    def install_executables(self, *, step=None, refill=None,
                            refill_row=None, refill_shared=None,
                            refill_chunks=None, cow_copy=None) -> None:
        """Swap the engine's jitted programs for AOT-compiled executables
        (gateway/aot.py): a cold replica then serves without retracing or
        recompiling any device program. Executables must have been lowered
        from THIS engine configuration — the aot module's fingerprint check
        enforces that; calling one with mismatched shapes/dtypes fails loudly
        at dispatch, never silently.

        ``refill_chunks`` maps chunk WIDTH → executable for every width the
        engine's admission path can dispatch (the fixed set
        ``chunk_widths()``); ``cow_copy`` is the paged fork program. Both
        are required exactly when the engine's configuration uses them —
        the aot_loaded flag must mean the WHOLE cold-start path is
        executable-backed."""
        if step is None or refill is None:
            raise ValueError("install_executables requires the step and "
                             "refill programs")
        if not self.paged and (refill_row is None or refill_shared is None):
            # dense engines dispatch the trickle and shared-prefix programs;
            # paged ones never do (radix hits subsume shared prefills,
            # staggered admission goes through the chunk programs), and
            # their bodies assume a dense slab — so paged bundles omit them
            raise ValueError("install_executables requires refill_row and "
                             "refill_shared for dense engines")
        widths = self.chunk_widths()
        if widths:
            missing = [w for w in widths if w not in (refill_chunks or {})]
            if missing:
                raise ValueError(
                    f"install_executables: refill_chunk widths {missing} "
                    f"required by this engine (chunk_widths={widths})")
            exes = dict(refill_chunks)

            def _chunk_dispatch(params, state, ids_chunk, start, seeds,
                                n_rows, mask, last, _exes=exes):
                return _exes[int(ids_chunk.shape[1])](
                    params, state, ids_chunk, start, seeds, n_rows, mask,
                    last)

            self._refill_chunk_fn = _chunk_dispatch
        if self.paged:
            if cow_copy is None:
                raise ValueError("install_executables: paged engines "
                                 "require the cow_copy program")
            self._cow_copy_fn = cow_copy
        self._step_fn = step
        self._refill_fn = refill
        if refill_row is not None:
            self._refill_row_fn = refill_row
        if refill_shared is not None:
            self._refill_shared_fn = refill_shared
        self.aot_loaded = True

    def chunk_widths(self) -> tuple:
        """The FIXED set of prefill-chunk widths this engine can dispatch —
        what makes chunk-on and paged engines AOT-serializable: every
        admission decomposes into windows from this set, so the aot bundle
        carries one executable per width and a cold replica never compiles.
        Dense chunk-off engines return () (the one-shot programs cover
        admission)."""
        if self.paged:
            bt = self.kv_block_tokens
            widths = {1}                        # full-hit logits recompute
            if bt < self.prefix_len:
                widths.add(bt)                  # miss-suffix body chunks
                if self.prefix_len % bt:
                    widths.add(self.prefix_len % bt)   # suffix tail
            return tuple(sorted(widths))
        if 0 < self.prefill_chunk < self.prefix_len:
            widths = {self.prefill_chunk}
            if self.prefix_len % self.prefill_chunk:
                widths.add(self.prefix_len % self.prefill_chunk)
            return tuple(sorted(widths))
        return ()

    # -- device programs ---------------------------------------------------
    def _init_state(self) -> Dict:
        B = self.slots
        if self.paged:
            cache = self.model.apply(
                self.params, self.kv_pool_blocks, self.kv_block_tokens,
                self.cache_dtype, method=DALLE.serve_init_cache_paged)
            pages = jnp.full((B, self.max_blocks), -1, jnp.int32)
            probe_cache = {n: c.replace(pages=pages)
                           for n, c in cache.items()}
        else:
            cache = self.model.apply(self.params, self.slots,
                                     self.cache_dtype,
                                     method=DALLE.serve_init_cache)
            pages = None
            probe_cache = cache
        texts = jax.ShapeDtypeStruct((B, self.text_seq_len), jnp.int32)
        mask = jax.ShapeDtypeStruct((B,), jnp.bool_)
        # logits dtype must match what the model emits (bf16 params emit
        # bf16 logits): a f32 placeholder would silently promote the
        # jnp.where merge and break bitwise exactness vs the sequential path
        out_shape = jax.eval_shape(
            lambda p, t, cc, m: self.model.apply(
                p, t, cc, m, method=DALLE.serve_refill),
            self.params, texts, probe_cache, mask)
        logits_dtype = out_shape[0].dtype
        state = {
            "cache": cache,
            "logits": jnp.zeros((B, out_shape[0].shape[-1]), logits_dtype),
            "cur_key": jnp.zeros((B, 2), jnp.uint32),
            "orig_key": jnp.zeros((B, 2), jnp.uint32),
            # parked until admitted: j clamps to the final step, active=False
            "t_idx": jnp.full((B,), self.n_steps, jnp.int32),
            # per-row decode length (ragged service demand — partial-grid
            # requests): tokens for a row with n < image_seq_len equal the
            # first n of the full single-request generation
            "n_row": jnp.full((B,), self.n_steps, jnp.int32),
            "active": jnp.zeros((B,), jnp.bool_),
            # CFG pairing (graftpage satellite): per-row partner index, cond
            # scale and uncond flag — DATA leaves the host rewrites at
            # admission. pair[i] == i / cfg == 1.0 rows sample their raw
            # logits bitwise unchanged, so non-CFG traffic is untouched.
            "pair": jnp.arange(B, dtype=jnp.int32),
            "cfg": jnp.ones((B,), jnp.float32),
            "uncond": jnp.zeros((B,), jnp.bool_),
        }
        if pages is not None:
            state["pages"] = pages
        return state

    def _refill(self, params, state, texts, seeds, n_rows, mask):
        new_keys = jax.vmap(jax.random.PRNGKey)(seeds)       # (B, 2) u32
        logits_r, cache = self.model.apply(
            params, texts, _bind_cache(state), mask, self.use_kernel,
            method=DALLE.serve_refill)
        m1 = mask[:, None]
        return _carry(state, {
            "cache": _unbind_cache(cache),
            "logits": jnp.where(m1, logits_r, state["logits"]),
            "cur_key": jnp.where(m1, new_keys, state["cur_key"]),
            "orig_key": jnp.where(m1, new_keys, state["orig_key"]),
            "t_idx": jnp.where(mask, 0, state["t_idx"]),
            "n_row": jnp.where(mask, n_rows, state["n_row"]),
            "active": state["active"] | mask,
        })

    def _refill_row(self, params, state, text1, seed, n_tok, row):
        """Admit ONE request into slot ``row`` (traced scalar — one
        compiled program serves every slot): a b=1 prefill (bitwise the
        sequential ``_prefill``) scattered into the shared cache. Under
        staggered completions admissions arrive one or two rows at a time;
        this costs 1/B of the multi-row refill window, which stays the
        bulk-admission path (cold start, bursts)."""
        logits1, cache1 = self.model.apply(
            params, text1, self.cache_dtype, method=DALLE.serve_prefill_row)
        cache = dict(state["cache"])
        for name, small in cache1.items():
            big = cache[name]
            kv = jax.lax.dynamic_update_slice(big.kv, small.kv, (row, 0, 0))
            if big.scale is not None:
                sc = jax.lax.dynamic_update_slice(big.scale, small.scale,
                                                  (row, 0, 0))
                cache[name] = big.replace(kv=kv, scale=sc)
            else:
                cache[name] = big.replace(kv=kv)
        key1 = jax.random.PRNGKey(seed)
        return _carry(state, {
            "cache": cache,
            "logits": jax.lax.dynamic_update_slice(
                state["logits"], logits1.astype(state["logits"].dtype),
                (row, 0)),
            "cur_key": jax.lax.dynamic_update_slice(
                state["cur_key"], key1[None], (row, 0)),
            "orig_key": jax.lax.dynamic_update_slice(
                state["orig_key"], key1[None], (row, 0)),
            "t_idx": state["t_idx"].at[row].set(0),
            "n_row": state["n_row"].at[row].set(n_tok),
            "active": state["active"].at[row].set(True),
        })

    # graftir: allow=precision -- the shared-prefix refill and the paged
    # COW fork are admission-only programs: they WRITE (or block-move) KV
    # into the int8 cache but never attend over it, so the rows' quant
    # scales legitimately pass through as moved data without a
    # dequantizing multiply (graftnum orphaned-scale); the scales are
    # consumed by the very next serve_decode step, whose entry pins the
    # dequant sites.
    def _refill_shared(self, params, state, text1, seeds, n_rows, mask):
        """Shared-prefix admission (graftloom): N candidates of ONE prompt
        (masked rows) pay a single b=1 text prefill, broadcast into every
        sibling row (``DALLE.serve_refill_shared``), with per-candidate RNG
        lanes seeded independently — each candidate's tokens stay BITWISE
        identical to an independent single-candidate request, (N−1) prompt
        prefills cheaper."""
        new_keys = jax.vmap(jax.random.PRNGKey)(seeds)       # (B, 2) u32
        logits1, cache = self.model.apply(
            params, text1, state["cache"], mask, self.cache_dtype,
            method=DALLE.serve_refill_shared)
        m1 = mask[:, None]
        return _carry(state, {
            "cache": cache,
            "logits": jnp.where(m1, logits1.astype(state["logits"].dtype),
                                state["logits"]),
            "cur_key": jnp.where(m1, new_keys, state["cur_key"]),
            "orig_key": jnp.where(m1, new_keys, state["orig_key"]),
            "t_idx": jnp.where(mask, 0, state["t_idx"]),
            "n_row": jnp.where(mask, n_rows, state["n_row"]),
            "active": state["active"] | mask,
        })

    def _refill_chunk(self, params, state, ids_chunk, start, seeds, n_rows,
                      mask, last):
        """One bounded window of a chunked prefill: ``ids_chunk`` (B, w)
        already remapped+bos'd prompt ids written at positions
        [start, start+w) of the masked rows. Rows only turn active — and
        only then consume keys/logits — on the FINAL chunk (``last``, a
        traced scalar so one program serves every chunk of a given
        width)."""
        logits_r, cache = self.model.apply(
            params, ids_chunk, _bind_cache(state), mask, start,
            self.use_kernel, method=DALLE.serve_refill_window)
        new_keys = jax.vmap(jax.random.PRNGKey)(seeds)
        lm = mask & last
        m1 = lm[:, None]
        return _carry(state, {
            "cache": _unbind_cache(cache),
            "logits": jnp.where(m1, logits_r.astype(state["logits"].dtype),
                                state["logits"]),
            "cur_key": jnp.where(m1, new_keys, state["cur_key"]),
            "orig_key": jnp.where(m1, new_keys, state["orig_key"]),
            "t_idx": jnp.where(lm, 0, state["t_idx"]),
            "n_row": jnp.where(lm, n_rows, state["n_row"]),
            "active": state["active"] | lm,
        })

    def _cow_copy(self, state, src, dst):
        """Copy-on-write fork (graftpage): duplicate shared blocks into
        fresh ones in every layer's pool — ``pool[dst[i]] = pool[src[i]]``,
        fixed lane count B with inactive lanes' dst out of bounds (scatter
        drop). Runs BEFORE the forked row's first write, so radix-resident
        blocks are never mutated; int8 scale planes ride with their
        blocks."""
        cache = {name: (c.copy_blocks(src, dst) if hasattr(c, "pool")
                        else c)
                 for name, c in state["cache"].items()}
        out = dict(state)
        out["cache"] = cache
        return out

    def _step(self, params, state):
        n_steps = self.n_steps
        logits, t_idx, active = (state["logits"], state["t_idx"],
                                 state["active"])
        n_row = state["n_row"]
        j = jnp.minimum(t_idx, n_row - 1)
        final = j == n_row - 1

        # per-row key discipline == the sequential split chain: tokens
        # 0..image_seq_len-2 consume one split each; only the FULL
        # sequence's last token uses fold_in(orig_key, n_steps) without
        # consuming a split. A partial-length row's final token therefore
        # still comes from the split chain — its tokens are exactly the
        # first n of the full generation.
        sp = jax.vmap(jax.random.split)(state["cur_key"])    # (B, 2, 2)
        new_key, sub = sp[:, 0], sp[:, 1]
        fin_key = jax.vmap(
            lambda k: jax.random.fold_in(k, n_steps))(state["orig_key"])
        uses_fold = final & (n_row == n_steps)
        sample_key = jnp.where(uses_fold[:, None], fin_key, sub)

        img_logits = logits[:, self.num_text_tokens:]
        # classifier-free guidance on paired rows: the stored per-row logits
        # stay RAW (cond rows hold conditioned logits, their partners hold
        # null-text logits); the merge is recomputed at every sample site —
        # exactly the sequential ``null + (cond − null) * cond_scale`` on
        # the image band (slicing commutes with the elementwise merge).
        # Both rows of a pair sample from the COND row's merged logits with
        # the same key chain (same seed), so they emit identical tokens in
        # lockstep and free together. The scale is cast to the logits dtype
        # first: a strong f32 scalar would promote bf16 logits and break
        # bitwise parity with the weak-typed sequential constant. cfg==1.0
        # rows keep their raw logits bitwise untouched (x + 0*s is NOT a
        # bitwise identity for -0.0 — hence the where, not the arithmetic).
        pair, cfg, uncond = state["pair"], state["cfg"], state["uncond"]
        s = cfg.astype(img_logits.dtype)[:, None]
        partner = img_logits[pair]
        merged = partner + (img_logits - partner) * s
        merged = jnp.where(uncond[:, None], merged[pair], merged)
        img_logits = jnp.where((cfg == 1.0)[:, None], img_logits, merged)
        stats = {}
        if self.decode_health:
            # per-row quality of the distribution being sampled FROM (the
            # pre-gumbel logits): entropy + top-k mass, (B,) f32 each —
            # fetched with the tokens at the same sync
            from ..obs.health import decode_quality
            stats = decode_quality(img_logits)
        tok = gumbel_sample_rows(sample_key, img_logits,
                                 thres=self.filter_thres,
                                 temperature=self.temperature,
                                 approx=self.topk_approx)

        decode_rows = active & ~final
        offsets = jnp.where(decode_rows, self.prefix_len + j, self.park)
        new_logits, cache = self.model.apply(
            params, tok, j, offsets, _bind_cache(state), self.use_kernel,
            method=DALLE.serve_decode)
        finished = active & final
        state = _carry(state, {
            "cache": _unbind_cache(cache),
            "logits": jnp.where(decode_rows[:, None], new_logits, logits),
            "cur_key": jnp.where(uses_fold[:, None], state["cur_key"],
                                 new_key),
            "orig_key": state["orig_key"],
            "t_idx": jnp.where(active, t_idx + 1, t_idx),
            "n_row": n_row,
            "active": decode_rows,
        })
        return tok, finished, stats, state

    def _multi_step(self, params, state):
        """steps_per_sync × _step in one program; (K, B) tokens/finished
        (+ (K, B) decode-quality stats when ``decode_health`` — an empty
        dict otherwise, so the program signature is stable)."""
        if self.steps_per_sync == 1:
            tok, finished, stats, state = self._step(params, state)
            return (tok[None], finished[None],
                    jax.tree.map(lambda x: x[None], stats), state)

        def body(carry, _):
            tok, finished, stats, carry = self._step(params, carry)
            return carry, (tok, finished, stats)

        state, (toks, fins, stats) = jax.lax.scan(body, state, None,
                                                  length=self.steps_per_sync)
        return toks, fins, stats, state

    # -- host loop ---------------------------------------------------------
    def _pad_text(self, text: np.ndarray) -> np.ndarray:
        out = np.zeros((self.text_seq_len,), np.int32)
        n = min(len(text), self.text_seq_len)
        out[:n] = text[:n]
        return out

    def _n_tokens(self, req: Request) -> int:
        if req.max_tokens is None:
            return self.n_steps
        return int(np.clip(req.max_tokens, 1, self.n_steps))

    def _remap_bos_host(self, texts: np.ndarray) -> np.ndarray:
        """Host-side ``remap_and_bos`` for the chunked-prefill path: 0-pads
        → unique per-position pad ids, <bos>=0 prepended. Integer-exact vs
        the device remap, so every chunk gathers the same embedding rows the
        one-shot window would."""
        B, T = texts.shape
        pad_ids = (np.arange(T, dtype=np.int32)
                   + np.int32(self.num_text_tokens - self.text_seq_len))
        out = np.where(texts == 0, pad_ids[None, :], texts).astype(np.int32)
        return np.concatenate([np.zeros((B, 1), np.int32), out], axis=1)

    # -- admission units (CFG pairing + paged planning) --------------------
    def _expand_unit(self, req: Request) -> List[Request]:
        """A request is admitted as a UNIT of slots that must activate in
        lockstep: one row normally, two for cond_scale != 1.0 — the request
        itself plus a synthetic null-text partner (negative request_id,
        never surfaced to callers) whose logits feed the per-step CFG
        merge. The null row shares the seed so both rows' key chains — and
        therefore their sampled tokens — stay bitwise identical."""
        if req.cond_scale == 1.0:
            return [req]
        if self.slots < 2:
            raise ValueError(
                "cond_scale != 1.0 needs an engine with slots >= 2 (the "
                "CFG pair occupies two decode slots)")
        null = dataclasses.replace(
            req, request_id=-req.request_id - 1,
            text=np.zeros_like(np.asarray(req.text)),
            group_id=None, group_size=1, group_index=0)
        return [req, null]

    def _take_units(self, queue, n_free: int):
        """Deferred units first (strict FIFO — a deferred CFG pair or
        pool-starved unit is never overtaken), then fresh queue takes,
        expanded into units. Units that don't fit ``n_free`` rows go back
        to the overflow deque intact. Returns (placeable units, number of
        requests newly taken from the queue)."""
        units = self._overflow
        self._overflow = []
        taken = 0
        have = sum(len(u) for u in units)
        if have < n_free:
            for req in queue.take(n_free - have):
                taken += 1
                units.append(self._expand_unit(req))
        placed, rows = [], 0
        for i, u in enumerate(units):
            if rows + len(u) > n_free:
                self._overflow = units[i:]
                break
            placed.append(u)
            rows += len(u)
        return placed, taken

    def _set_pair_state(self, pairs_u) -> None:
        """Write the CFG pairing mirrors for one admitted unit; dirty only
        when something actually changes, so non-CFG workloads never upload
        (their admission path is byte-identical to the pre-CFG engine)."""
        if len(pairs_u) == 2:
            (cs, creq), (ns, _) = pairs_u
            self._pair_host[cs], self._pair_host[ns] = ns, cs
            self._cfg_host[cs] = self._cfg_host[ns] = creq.cond_scale
            self._uncond_host[cs], self._uncond_host[ns] = False, True
            self._cfg_dirty = True
        else:
            slot = pairs_u[0][0]
            if (self._pair_host[slot] != slot
                    or self._cfg_host[slot] != 1.0
                    or self._uncond_host[slot]):
                self._pair_host[slot] = slot
                self._cfg_host[slot] = 1.0
                self._uncond_host[slot] = False
                self._cfg_dirty = True

    def _upload_cfg(self, state):
        if self._cfg_dirty:
            state["pair"] = jnp.asarray(self._pair_host)
            state["cfg"] = jnp.asarray(self._cfg_host)
            state["uncond"] = jnp.asarray(self._uncond_host)
            self._cfg_dirty = False
        return state

    # -- paged admission (graftpage) ---------------------------------------
    def _plan_row(self, req: Request) -> dict:
        """Radix-match one row's prompt and size its block demand: the
        blocks it can MAP from resident KV (read-only shares), the block it
        must COW-fork (full hit), and the fresh blocks it needs for the
        unmatched prompt suffix plus its decode tokens. Written positions
        span [0, prefix_len + n_tok - 1) — the final token's KV is never
        written (the dense engine's decode_rows contract), so a full-length
        row needs ceil((total_seq_len - 1) / bt) blocks."""
        bt = self.kv_block_tokens
        ids = self._remap_bos_host(self._pad_text(req.text)[None])[0]
        key = tuple(int(x) for x in ids)
        n_tok = self._n_tokens(req)
        total = -(-(self.prefix_len + n_tok - 1) // bt)
        pr = {"req": req, "key": key, "ids": ids, "n_tok": n_tok,
              "shared": [], "fork_src": None, "fresh_n": total,
              "full": False, "hit_tok": 0, "match": None}
        if not self.radix_cache:
            return pr
        # record=False: a unit the pool defers is re-planned every retry
        # iteration (its matched blocks are unprotected while it waits, so
        # the match CANNOT be cached across evictions) — the ledger commits
        # once, in _plan_unit, when the unit actually admits
        m = self.radix.match(key, record=False)
        pr["match"] = m
        if m.full:
            # the block holding position prefix_len-1 must be forked before
            # the width-1 logits recompute rewrites it: with a partial tail
            # that's the tail block, with a block-aligned prompt it's the
            # LAST full block — either way the fork dst is the row's first
            # fresh block and the remaining matched blocks stay shared
            shared = list(m.blocks) if self.prefix_len % bt else \
                list(m.blocks[:-1])
            pr.update(shared=shared, fork_src=m.tail_block,
                      fresh_n=total - len(shared), full=True,
                      hit_tok=m.hit_tokens)
        elif m.blocks:
            pr.update(shared=list(m.blocks),
                      fresh_n=total - len(m.blocks), hit_tok=m.hit_tokens)
        return pr

    def _plan_unit(self, unit) -> Optional[dict]:
        """Block-feasibility for one admission unit, atomically: retain
        every block the unit reads FIRST (matched shares and fork sources
        — protecting them from the eviction this very pass may run), evict
        radix-only leaves for the remainder, then allocate every fresh
        block the unit's rows will ever write (prompt suffix AND decode) up
        front — a row that starts decoding can never run out mid-stream.
        Returns None (with retains rolled back) when the pool can't cover
        the unit; the caller defers the whole unit FIFO-fairly."""
        pool = self.block_pool
        rows = [self._plan_row(r) for r in unit]
        retained = []
        for pr in rows:
            for bid in pr["shared"]:
                pool.retain(bid)
                retained.append(bid)
            if pr["fork_src"] is not None:
                pool.retain(pr["fork_src"])
                retained.append(pr["fork_src"])
        need = sum(pr["fresh_n"] for pr in rows)
        if pool.free_count < need and self.radix_cache:
            self.stats.pages_evicted += self.radix.evict(
                need - pool.free_count)
        if pool.free_count < need:
            for bid in retained:
                pool.release(bid)
            return None
        bt = self.kv_block_tokens
        n_full = self.prefix_len // bt
        t = self.prefix_len % bt
        tmp = []
        for pr in rows:
            pr["fresh"] = [pool.alloc() for _ in range(pr["fresh_n"])]
            if pr["fork_src"] is not None:
                pr["fork_dst"] = pr["fresh"][0]
                tmp.append(pr["fork_src"])   # held only until the copy runs
            elif self.radix_cache:
                # register the prompt's blocks NOW (content is prompt-
                # deterministic; this pass's dispatches write it), so
                # same-pass siblings — candidate fan-outs, repeated
                # templates — already hit; insert() retains one tree ref
                # per NEW node and keeps incumbents for already-resident
                # prefixes
                combined = pr["shared"] + pr["fresh"]
                self.radix.insert(pr["key"], combined[:n_full],
                                  combined[n_full] if t else None)
        # the unit is definitely admitting: commit its matches to the hit
        # ledgers exactly once (planning retries of deferred units don't
        # count — see _plan_row)
        for pr in rows:
            if pr["match"] is not None:
                self.radix.record(pr["match"])
            if pr["full"]:
                self.stats.radix_full_hits += 1
                self.stats.shared_prefills_saved += 1
                self.stats.prefix_hit_tokens += pr["hit_tok"]
            elif pr["shared"]:
                self.stats.radix_partial_hits += 1
                self.stats.prefix_hit_tokens += pr["hit_tok"]
            else:
                self.stats.radix_misses += 1
        return {"rows": rows, "tmp": tmp}

    def _admit_paged(self, state, placed, row_t0):
        """Dispatch one paged admission pass. Order is load-bearing:
        page-table upload → full-miss windows → partial-hit suffix chunks →
        COW forks → full-hit width-1 recomputes. Forks must follow every
        prefill that WRITES a block being forked (same-pass siblings fork
        blocks the pass itself fills) and precede the full-hit write into
        the fork; device dispatch order makes each step see the previous
        one's pool."""
        B = self.slots
        bt = self.kv_block_tokens
        pool = self.block_pool
        tmp = []
        miss_mask = np.zeros((B,), bool)
        texts = np.zeros((B, self.text_seq_len), np.int32)
        seeds = np.zeros((B,), np.int32)
        n_rows_arr = np.full((B,), self.n_steps, np.int32)
        suffix: Dict[int, list] = {}
        forks = []
        hit_rows = []
        all_rows = []
        for pairs_u, plan in placed:
            tmp.extend(plan["tmp"])
            for (slot, req), pr in zip(pairs_u, plan["rows"]):
                blocks = pr["shared"] + pr["fresh"]
                self._pages_host[slot, :] = -1
                self._pages_host[slot, :len(blocks)] = blocks
                self._slot_blocks[slot] = blocks
                seeds[slot] = req.seed
                n_rows_arr[slot] = pr["n_tok"]
                all_rows.append((slot, req, pr))
                if pr["full"]:
                    forks.append((pr["fork_src"], pr["fork_dst"]))
                    hit_rows.append((slot, pr))
                elif pr["shared"]:
                    suffix.setdefault(len(pr["shared"]) * bt,
                                      []).append((slot, pr))
                else:
                    miss_mask[slot] = True
                    texts[slot] = self._pad_text(req.text)
        # one upload covers every layer and every dispatch below — the
        # page table is device DATA, so nothing here can recompile
        state["pages"] = jnp.asarray(self._pages_host)
        state = self._upload_cfg(state)
        t0 = time.perf_counter()
        if miss_mask.any():
            state = self._refill_fn(self.params, state, texts, seeds,
                                    n_rows_arr, miss_mask)
            self.stats.refills += 1
        for start in sorted(suffix):
            mask = np.zeros((B,), bool)
            ids = np.zeros((B, self.prefix_len), np.int32)
            for slot, pr in suffix[start]:
                mask[slot] = True
                ids[slot] = pr["ids"]
            pos = start
            while pos < self.prefix_len:
                w = min(bt, self.prefix_len - pos)
                last = pos + w >= self.prefix_len
                state = self._refill_chunk_fn(
                    self.params, state, ids[:, pos:pos + w], np.int32(pos),
                    seeds, n_rows_arr, mask, np.bool_(last))
                self.stats.prefill_chunks += 1
                pos += w
            self.stats.refills += 1
        if forks:
            src = np.zeros((B,), np.int32)
            # unused lanes get UNIQUE out-of-range dst (scatter drop)
            dst = pool.num_blocks + np.arange(B, dtype=np.int32)
            for i, (s, d) in enumerate(forks):
                src[i] = s
                dst[i] = d
            state = self._cow_copy_fn(state, src, dst)
            self.stats.cow_forks += len(forks)
            pool.cow_copies += len(forks)
        if hit_rows:
            # full-prefix hits recompute ONLY position prefix_len-1 — a
            # width-1 window whose logits are bitwise the one-shot window's
            # last position (same gathered prefix, same reduce widths); its
            # KV write is an idempotent rewrite into the row's private fork
            mask = np.zeros((B,), bool)
            ids = np.zeros((B, self.prefix_len), np.int32)
            for slot, pr in hit_rows:
                mask[slot] = True
                ids[slot] = pr["ids"]
            state = self._refill_chunk_fn(
                self.params, state, ids[:, self.prefix_len - 1:],
                np.int32(self.prefix_len - 1), seeds, n_rows_arr, mask,
                np.bool_(True))
            self.stats.refills += 1
        t1 = time.perf_counter()
        for bid in tmp:
            pool.release(bid)
        for slot, req, pr in all_rows:
            if req.request_id >= 0:
                mode = ("paged-hit" if pr["full"] else
                        "paged-partial" if pr["shared"] else "paged")
                record_span("serve/prefill", t0, t1 - t0,
                            request_id=req.request_id,
                            trace_id=req.trace_id, mode=mode)
            row_t0[slot] = t1
        gauge_set("kv.pages_free", float(pool.free_count))
        gauge_set("kv.pages_used", float(pool.used_count))
        gauge_set("kv.pages_shared", float(pool.shared_count))
        gauge_set("kv.pages_cow_copies", float(pool.cow_copies))
        counter_add("kv.prefix_hit_tokens_total",
                    float(sum(pr["hit_tok"] for _, _, pr in all_rows)))
        return state

    def _release_slot_blocks(self, slot: int) -> None:
        """Completion: drop the row's refs on every block it mapped —
        shared blocks fall back to tree-only (evictable), private blocks
        free outright unless the radix tree adopted them at admission. The
        device page table keeps its stale row until the slot's next
        admission overwrites it: an inactive row's writes drop at the park
        offset and its outputs are discarded, so stale mappings are
        unreachable."""
        for bid in self._slot_blocks.pop(slot, ()):
            self.block_pool.release(bid)
        self._pages_host[slot, :] = -1

    def kv_stats(self) -> dict:
        """Page-pool + radix counters for the health verb and obs_report."""
        if not self.paged:
            return {"paged": False}
        out = {"paged": True, "block_tokens": self.kv_block_tokens,
               "pool_blocks": self.kv_pool_blocks,
               "blocks_per_slot": self.max_blocks,
               "radix_cache": self.radix_cache}
        pool, rx = self.block_pool, self.radix
        if pool is not None:
            out.update(pages_free=pool.free_count,
                       pages_used=pool.used_count,
                       pages_shared=pool.shared_count,
                       cow_copies=pool.cow_copies)
        if rx is not None:
            out.update(radix_nodes=rx.resident_nodes,
                       radix_lookups=rx.lookups,
                       radix_full_hits=rx.full_hits,
                       radix_partial_hits=rx.partial_hits,
                       prefix_hit_tokens=rx.hit_tokens_total,
                       radix_evictions=rx.evictions)
        return out

    @staticmethod
    def _split_cohorts(pairs):
        """Partition one admission pass into shared-prefix cohorts (≥2
        members of one group with identical text — the /v1/images fan-out)
        and singles. A group split across admission passes still shares
        within each pass; a lone straggler rides the single paths. Group
        members with mismatched text (a misuse the gateway never produces)
        are demoted to singles rather than silently prefilled with the
        first member's prompt."""
        by_gid: Dict[int, list] = {}
        singles = []
        for slot, req in pairs:
            # CFG members (cond_scale != 1.0) ride the single paths: the
            # broadcast-prefill cohort would activate a cond row in one
            # dispatch and its synthetic null partner in another, breaking
            # the pair's lockstep key chain
            if req.group_id is not None and req.cond_scale == 1.0:
                by_gid.setdefault(req.group_id, []).append((slot, req))
            else:
                singles.append((slot, req))
        cohorts = []
        for members in by_gid.values():
            text0 = members[0][1].text
            if len(members) >= 2 and all(
                    np.array_equal(r.text, text0) for _, r in members[1:]):
                cohorts.append(members)
            else:
                singles.extend(members)
        singles.sort(key=lambda p: p[0])
        return cohorts, singles

    def run(self, queue: RequestQueue, *, max_steps: Optional[int] = None,
            poll_s: float = 0.02,
            on_complete=None, on_rows=None) -> List[CompletedRequest]:
        """Serve until the queue is drained (closed + empty + nothing in
        flight). Producers may keep submitting from other threads while
        this runs. Returns completions in completion order.

        A long-lived deployment (queue held open indefinitely) should pass
        ``on_complete``: each CompletedRequest is handed to it the moment
        its last token lands and is NOT accumulated — the return value is
        then an empty list and memory stays O(slots) for the life of the
        loop. Without it, every completion (including its full token array)
        is retained until drain.

        ``on_rows(request, row_idx, row_tokens)`` streams partial results:
        called the moment a committed GRID ROW of the image token field
        finishes (``row_len == image_fmap_size`` tokens — the slot state's
        per-row offset crossing a row boundary), plus once for a trailing
        partial row of a ``max_tokens`` request just before its completion.
        Concatenating a request's row_tokens in row_idx order reproduces its
        final token sequence exactly, so a streaming consumer (the
        gateway's SSE writer, which dVAE-decodes committed rows into
        preview pixels) needs no end-of-stream reconciliation. Callbacks
        run on the engine thread — keep them O(row) and non-blocking.

        ``max_steps`` is a harness bound (bench/smoke), not a graceful
        drain: requests still mid-decode when it trips are abandoned —
        already consumed from the queue, never completed. Their ids are
        recorded in ``stats.aborted_in_flight`` so the loss is visible."""
        B = self.slots
        sched = SlotScheduler(B)
        # paged control plane + CFG mirrors, fresh per serve loop (the
        # device cache below starts empty, so host residency must too)
        if self.paged:
            self.block_pool = BlockPool(self.kv_pool_blocks)
            self.radix = RadixCache(self.kv_block_tokens, self.block_pool)
            self._pages_host = np.full((B, self.max_blocks), -1, np.int32)
            self._slot_blocks: Dict[int, List[int]] = {}
        self._pair_host = np.arange(B, dtype=np.int32)
        self._cfg_host = np.ones((B,), np.float32)
        self._uncond_host = np.zeros((B,), bool)
        self._cfg_dirty = False
        self._overflow: List[List[Request]] = []
        state = self._init_state()
        buffers: Dict[int, List[int]] = {}
        row_t0: Dict[int, float] = {}      # per-slot start of the open row
        # per-slot decode-quality accumulators [Σentropy, Σtopk_mass, n]
        # (decode_health only; reset at admission, reduced at completion)
        qual: Dict[int, List[float]] = {}
        completed: List[CompletedRequest] = []
        self.stats = EngineStats()

        # flight-recorder / watchdog state provider: while this loop is
        # live, a stall report or post-mortem bundle carries the queue
        # depth, slot occupancy and in-flight request ids — the serve-side
        # "where was everyone" snapshot. Read from other threads; every
        # value is a point-in-time copy and the collector tolerates races.
        def _engine_state() -> dict:
            inflight = []
            for s in sched.active_slots():
                r = sched.request_at(s)
                if r is not None:
                    inflight.append({
                        "slot": s, "request_id": r.request_id,
                        "trace_id": r.trace_id,
                        "tokens_done": len(buffers.get(s, ()))})
            return {"queue_depth": queue.qsize(),
                    "slot_occupancy": sched.occupancy,
                    "steps": self.stats.steps, "inflight": inflight}

        provider = register_state_provider(
            f"serve.engine[{threading.current_thread().name}]",
            _engine_state)
        try:
            return self._run(queue, sched, state, buffers, row_t0, qual,
                             completed, max_steps=max_steps, poll_s=poll_s,
                             on_complete=on_complete, on_rows=on_rows)
        finally:
            unregister_state_provider(provider)

    def _admit_shared(self, state, members, row_t0):
        """One shared-prefix cohort: a single b=1 prefill broadcast into
        every member's slot, per-candidate RNG lanes from each member's own
        seed."""
        B = self.slots
        seeds = np.zeros((B,), np.int32)
        n_rows = np.full((B,), self.n_steps, np.int32)
        mask = np.zeros((B,), bool)
        for slot, req in members:
            seeds[slot] = req.seed
            n_rows[slot] = self._n_tokens(req)
            mask[slot] = True
        text1 = self._pad_text(members[0][1].text)[None]
        t0 = time.perf_counter()
        state = self._refill_shared_fn(self.params, state, text1, seeds,
                                       n_rows, mask)
        t1 = time.perf_counter()
        self.stats.refills += 1
        self.stats.shared_refills += 1
        self.stats.shared_prefills_saved += len(members) - 1
        record_span("pipeline/prefill_shared", t0, t1 - t0,
                    group_id=members[0][1].group_id,
                    candidates=len(members),
                    trace_id=members[0][1].trace_id)
        for slot, req in members:
            record_span("serve/prefill", t0, t1 - t0,
                        request_id=req.request_id, trace_id=req.trace_id,
                        mode="shared")
            row_t0[slot] = t1
        return state

    def _dispatch_chunk(self, state, chunk_jobs, pending, row_t0):
        """Advance the oldest pending chunked prefill by ONE bounded window
        (the per-iteration budget that keeps neighbors' decode interleaved);
        on the final chunk the rows turn active and their prefill spans
        close."""
        job = chunk_jobs[0]
        prefix = job.ids.shape[1]
        w = min(self.prefill_chunk, prefix - job.start)
        last = job.start + w >= prefix
        t0 = time.perf_counter()
        state = self._refill_chunk_fn(
            self.params, state, job.ids[:, job.start:job.start + w],
            np.int32(job.start), job.seeds, job.n_rows, job.mask,
            np.bool_(last))
        t1 = time.perf_counter()
        self.stats.prefill_chunks += 1
        record_span("serve/prefill_chunk", t0, t1 - t0,
                    start=job.start, width=w,
                    step=self.stats.steps,
                    trace_id=job.pairs[0][1].trace_id)
        histogram_observe("serve.prefill_chunk_seconds", t1 - t0,
                          trace_id=job.pairs[0][1].trace_id)
        job.start += w
        if last:
            chunk_jobs.pop(0)
            self.stats.refills += 1
            for slot, req in job.pairs:
                pending.discard(slot)
                record_span("serve/prefill", job.t0, t1 - job.t0,
                            request_id=req.request_id,
                            trace_id=req.trace_id, mode="chunked")
                row_t0[slot] = t1
        return state

    def _run(self, queue, sched, state, buffers, row_t0, qual, completed, *,
             max_steps, poll_s, on_complete, on_rows):
        B = self.slots
        chunk_jobs: List[_ChunkJob] = []
        pending: set = set()       # slots admitted but mid-chunked-prefill
        # drain also requires the overflow deque empty: units deferred for
        # slots (a CFG pair against one free slot) or for pool pressure were
        # already consumed from the queue and still owe completions
        while not (queue.drained and not sched.any_active
                   and not self._overflow):
            if max_steps is not None and self.stats.steps >= max_steps:
                break

            # admission: fill every free slot the queue can cover, FIFO,
            # in lockstep UNITS (single rows, or cond+null CFG pairs)
            pre_q = queue.qsize()
            free = sched.free_slots()
            admitted = 0
            if free:
                units, admitted = self._take_units(queue, len(free))
                placed = []
                for i, unit in enumerate(units):
                    plan = None
                    if self.paged:
                        plan = self._plan_unit(unit)
                        if plan is None:
                            # pool can't cover the unit even after
                            # eviction: defer it AND everything behind it
                            # (FIFO — no overtaking), retry when
                            # completions release blocks
                            self._overflow = units[i:] + self._overflow
                            break
                    placed.append((sched.admit(unit), plan))
                if placed:
                    pairs = []
                    now = time.perf_counter()
                    for pairs_u, _ in placed:
                        self._set_pair_state(pairs_u)
                        for slot, req in pairs_u:
                            req.admitted_at = now
                            buffers[slot] = []
                            qual[slot] = [0.0, 0.0, 0]
                            pairs.append((slot, req))
                            if req.request_id < 0:
                                continue   # synthetic CFG-null row
                            # queue wait as its own span (admission SLO
                            # input: TTFT = queue wait + prefill + first
                            # step) + gauge
                            record_span("serve/request_queue_wait",
                                        req.submitted_at,
                                        now - req.submitted_at,
                                        request_id=req.request_id,
                                        trace_id=req.trace_id)
                            gauge_set("serve.queue_wait_s",
                                      now - req.submitted_at)
                            histogram_observe("serve.queue_wait_seconds",
                                              now - req.submitted_at,
                                              trace_id=req.trace_id)
                            record_event("request_admitted", slot=slot,
                                         request_id=req.request_id,
                                         trace_id=req.trace_id)
                if placed and self.paged:
                    state = self._admit_paged(state, placed, row_t0)
                elif placed:
                    state = self._upload_cfg(state)
                    # shared-prefix cohorts first (one prefill per group),
                    # then singles through the classic window/trickle split
                    cohorts, singles = self._split_cohorts(pairs)
                    for members in cohorts:
                        state = self._admit_shared(state, members, row_t0)
                    chunk_on = 0 < self.prefill_chunk < self.prefix_len
                    if singles and (2 * len(singles) >= B or chunk_on):
                        # bulk admission: one multi-row refill window —
                        # chunked into bounded, decode-interleaved pieces
                        # when prefill_chunk caps the per-dispatch width.
                        # chunk-on also routes TRICKLE-size admissions here
                        # (a one-row-masked window): a fat single admission
                        # must obey the same per-dispatch bound, else the
                        # staggered-completion steady state reintroduces
                        # exactly the TTFT stall the knob exists to cap
                        texts = np.zeros((B, self.text_seq_len), np.int32)
                        seeds = np.zeros((B,), np.int32)
                        n_rows = np.full((B,), self.n_steps, np.int32)
                        mask = np.zeros((B,), bool)
                        for slot, req in singles:
                            texts[slot] = self._pad_text(req.text)
                            seeds[slot] = req.seed
                            n_rows[slot] = self._n_tokens(req)
                            mask[slot] = True
                        if 0 < self.prefill_chunk < self.prefix_len:
                            chunk_jobs.append(_ChunkJob(
                                ids=self._remap_bos_host(texts),
                                seeds=seeds, n_rows=n_rows, mask=mask,
                                pairs=list(singles),
                                t0=time.perf_counter()))
                            pending.update(s for s, _ in singles)
                        else:
                            t0 = time.perf_counter()
                            state = self._refill_fn(self.params, state,
                                                    texts, seeds, n_rows,
                                                    mask)
                            t1 = time.perf_counter()
                            self.stats.refills += 1
                            # one shared prefill window, one span per
                            # admitted request (each request's timeline owns
                            # its prefill segment; dur is the host dispatch
                            # wall)
                            for slot, req in singles:
                                record_span("serve/prefill", t0, t1 - t0,
                                            request_id=req.request_id,
                                            trace_id=req.trace_id,
                                            mode="window")
                                row_t0[slot] = t1
                    elif singles:
                        # trickle admission (staggered completions, chunking
                        # off): per-row scatter-prefill, 1/B the window's
                        # compute
                        for slot, req in singles:
                            t0 = time.perf_counter()
                            state = self._refill_row_fn(
                                self.params, state,
                                self._pad_text(req.text)[None],
                                np.int32(req.seed),
                                np.int32(self._n_tokens(req)),
                                np.int32(slot))
                            t1 = time.perf_counter()
                            self.stats.refills += 1
                            record_span("serve/prefill", t0, t1 - t0,
                                        request_id=req.request_id,
                                        trace_id=req.trace_id, mode="row")
                            row_t0[slot] = t1
            # work-conservation sample: requests that were already queued
            # at the take instant and still went unplaced must leave every
            # slot busy, so occupancy is sampled exactly then (an idle slot
            # here is a real violation, not tautologically 1.0). A request
            # landing after the take is admitted next iteration and is
            # deliberately excluded — arrival-bound, not an idle-slot bug.
            backlog = (pre_q - admitted) > 0
            gauge_set("serve.queue_depth", float(queue.qsize()))
            gauge_set("serve.slot_occupancy", sched.occupancy)

            if chunk_jobs:
                # one bounded prefill window per iteration, so the decode
                # step below keeps interleaving — the TTFT-isolation bar
                state = self._dispatch_chunk(state, chunk_jobs, pending,
                                             row_t0)

            if not any(s not in pending for s in sched.active_slots()):
                if chunk_jobs:
                    continue          # keep driving the pending prefill
                if self._overflow:
                    continue          # free slots admit the deferred units
                if queue.drained:
                    break
                queue.wait_nonempty(timeout=poll_s)
                continue

            if backlog:
                self.stats.sample_occupancy(sched.occupancy)

            # chaos hook (graftfleet): an env-installed FaultPlan can
            # kill/hang/slow a REPLICA PROCESS at decode-iteration
            # granularity — mid-stream, between row commits — which is
            # what the fleet smoke's drain/kill scenarios script. One
            # module-global None check when chaos is off (the
            # BaseTrainer.fit precedent, serve-side).
            chaos_step_hook(self.stats.steps)

            toks, fins, qstats, state = self._step_fn(self.params, state)
            toks = np.asarray(toks)               # (K, B)
            fins = np.asarray(fins)
            # decode-quality stats ride the SAME host sync as the tokens
            # (empty dict when decode_health is off)
            q_ent = np.asarray(qstats["entropy"]) if qstats else None
            q_mass = np.asarray(qstats["topk_mass"]) if qstats else None
            now = time.perf_counter()
            for k in range(toks.shape[0]):
                active = [s for s in sched.active_slots()
                          if s not in pending]
                if not active:
                    break
                for slot in active:
                    req = sched.request_at(slot)
                    if req.first_token_at is None:
                        req.first_token_at = now
                    buf = buffers[slot]
                    buf.append(int(toks[k, slot]))
                    if q_ent is not None:
                        acc = qual.setdefault(slot, [0.0, 0.0, 0])
                        acc[0] += float(q_ent[k, slot])
                        acc[1] += float(q_mass[k, slot])
                        acc[2] += 1
                    if (len(buf) % self.row_len == 0
                            and req.request_id >= 0):
                        row = len(buf) // self.row_len - 1
                        # one committed grid row = one timeline segment
                        # (host-sync granularity: rows finishing inside one
                        # multi-step dispatch share its sync timestamp)
                        t0r = row_t0.get(slot, now)
                        record_span("serve/decode_row", t0r, now - t0r,
                                    request_id=req.request_id,
                                    trace_id=req.trace_id, row=row)
                        histogram_observe("serve.decode_row_seconds",
                                          now - t0r,
                                          trace_id=req.trace_id)
                        row_t0[slot] = now
                        if on_rows is not None:
                            on_rows(req, row, buf[row * self.row_len:])
                # synthetic CFG-null rows burn device work but emit no
                # caller-visible tokens — keep the throughput counter an
                # honest goodput number
                counter_add("serve.tokens_emitted_total",
                            float(sum(1 for s in active
                                      if sched.request_at(s).request_id
                                      >= 0)))
                for slot in active:
                    if not fins[k, slot]:
                        continue
                    req = sched.complete(slot)
                    if self.paged:
                        self._release_slot_blocks(slot)
                    if req.request_id < 0:
                        # synthetic CFG-null row: its tokens are bitwise
                        # duplicates of the cond partner's — nothing to
                        # surface, just free the slot
                        buffers.pop(slot, None)
                        qual.pop(slot, None)
                        row_t0.pop(slot, None)
                        continue
                    tail = len(buffers[slot]) % self.row_len
                    if tail:
                        # trailing partial row of a max_tokens request
                        t0r = row_t0.get(slot, now)
                        record_span("serve/decode_row", t0r, now - t0r,
                                    request_id=req.request_id,
                                    trace_id=req.trace_id,
                                    row=len(buffers[slot]) // self.row_len,
                                    partial=True)
                        if on_rows is not None:
                            on_rows(req, len(buffers[slot]) // self.row_len,
                                    buffers[slot][-tail:])
                    row_t0.pop(slot, None)
                    cr = CompletedRequest(
                        request_id=req.request_id,
                        tokens=np.asarray(buffers.pop(slot), np.int32),
                        seed=req.seed,
                        submitted_at=req.submitted_at,
                        admitted_at=req.admitted_at,
                        first_token_at=req.first_token_at,
                        completed_at=now)
                    if on_complete is not None:
                        on_complete(cr)
                    else:
                        completed.append(cr)
                    # per-request decode quality (graftpulse): means of the
                    # in-jit entropy/top-k taps plus the host-side
                    # repeated-token ratio. Per-request values travel as
                    # SPAN ARGS tagged with the trace_id (bounded ring) and
                    # as unlabeled aggregate gauges — never as metric
                    # labels, which would be unbounded Prometheus
                    # cardinality (graftlint: unbounded-metric-label)
                    q_args = {}
                    acc = qual.pop(slot, None)
                    if acc is not None and acc[2] > 0:
                        t = cr.tokens
                        rep = (float(np.mean(t[1:] == t[:-1]))
                               if t.shape[0] > 1 else 0.0)
                        q_args = {"entropy": round(acc[0] / acc[2], 4),
                                  "topk_mass": round(acc[1] / acc[2], 4),
                                  "repeat_ratio": round(rep, 4)}
                        gauge_set("health.decode_entropy", acc[0] / acc[2])
                        gauge_set("health.decode_topk_mass", acc[1] / acc[2])
                        gauge_set("health.decode_repeat_ratio", rep)
                        record_event("decode_quality",
                                     request_id=req.request_id,
                                     trace_id=req.trace_id, **q_args)
                    # retrospective spans: requests overlap, so the
                    # stack-based span() contract cannot hold — see
                    # obs.record_span
                    record_span("serve/request", req.admitted_at,
                                now - req.admitted_at,
                                request_id=req.request_id,
                                trace_id=req.trace_id,
                                tokens=int(cr.tokens.shape[0]), **q_args)
                    record_span("serve/request_ttft", req.submitted_at,
                                cr.ttft_s, request_id=req.request_id,
                                trace_id=req.trace_id)
                    # native histogram (graftlens): the latency SHAPE a
                    # single gauge cannot carry — p50/p95 render from the
                    # cumulative buckets (obs_report), fleet-wide because
                    # the collector sums buckets across processes
                    histogram_observe("serve.ttft_seconds", cr.ttft_s,
                                      trace_id=req.trace_id)
                    record_event("request_completed",
                                 request_id=req.request_id,
                                 trace_id=req.trace_id,
                                 latency_s=cr.latency_s)
                    counter_add("serve.requests_completed_total", 1.0)
                    gauge_set("serve.request_latency_s", cr.latency_s)
                self.stats.steps += 1
        self.stats.aborted_in_flight = [
            sched.request_at(s).request_id for s in sched.active_slots()
            if sched.request_at(s).request_id >= 0]
        return completed
