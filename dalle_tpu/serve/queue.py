"""Host-side request queue for the continuous-batching decode engine.

A thread-safe FIFO of generation requests. Producers (an RPC handler, the
offered-load bench) ``submit`` from any thread; the engine loop ``take``s up
to its free-slot count per iteration and blocks on ``wait_nonempty`` only
when every slot is idle. ``close()`` marks the end of the workload: the
engine drains what is queued plus what is in flight, then returns.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np


class QueueFull(RuntimeError):
    """Raised by ``submit`` on a bounded queue at capacity. The gateway maps
    it to HTTP 429: rejecting at admission keeps a traffic spike from
    queueing into TTFT death — a request that would wait seconds for a slot
    is better retried against another replica (or later) than accepted."""


@dataclasses.dataclass
class Request:
    """One generation request: a text prompt (token ids, 0-padded to
    text_seq_len) and the per-request PRNG seed. ``seed`` defines the
    request's whole sampling stream — the engine's output for this request
    is bit-identical to ``generate_images_tokens(text[None],
    jax.random.PRNGKey(seed))``."""
    request_id: int
    text: np.ndarray            # (text_seq_len,) int32
    seed: int
    # decode only the first ``max_tokens`` of the image grid (None = the
    # full image_seq_len). Partial-grid serving — previews, progressive
    # decode, top-rows-for-inpainting — is what makes per-request service
    # demand ragged; the engine's tokens for a partial request equal the
    # FIRST max_tokens of the full single-request generation.
    max_tokens: Optional[int] = None
    submitted_at: float = dataclasses.field(
        default_factory=time.perf_counter)
    # gateway-layer policy fields (dalle_tpu/gateway): ignored by the FIFO
    # queue and the engine itself, consumed by PolicyQueue ordering and the
    # admission controller. ``deadline_at`` is in the ``submitted_at``
    # timebase (perf_counter seconds).
    tenant: str = "default"
    priority: int = 0           # higher = served sooner under PolicyQueue
    deadline_at: Optional[float] = None
    # graftscope trace context (obs/context.py): the request's one identity
    # across gateway → router → replica → engine slot — and across a
    # failover resubmission, which reuses the original id. Minted at the
    # HTTP door (gateway/server.py) or by ``submit`` for CLI/bench
    # producers; every span the request touches is tagged with it.
    trace_id: Optional[str] = None
    # shared-prefix candidate groups (graftloom): candidates of ONE
    # ``/v1/images`` request carry the same ``group_id`` and identical text;
    # members of a group admitted in the same engine pass share one text
    # prefill (DALLE.serve_refill_shared) instead of paying N. Per-candidate
    # seeds keep every candidate's sampling stream independent — tokens stay
    # bitwise what N separate single-candidate requests would produce.
    group_id: Optional[int] = None
    group_size: int = 1
    group_index: int = 0
    # classifier-free guidance (graftpage): cond_scale != 1.0 makes the
    # engine admit this request as a COHORT of two slots — the conditioned
    # row plus a synthetic null-text row (negative request_id, never
    # surfaced) — merging logits per step exactly like the sequential
    # ``generate_images_tokens(cond_scale=...)`` path. Requires an engine
    # with slots >= 2.
    cond_scale: float = 1.0
    # stamped by the engine
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None


@dataclasses.dataclass
class CompletedRequest:
    request_id: int
    tokens: np.ndarray          # (image_seq_len,) int32
    seed: int
    submitted_at: float
    admitted_at: float
    first_token_at: float
    completed_at: float

    @property
    def ttft_s(self) -> float:
        """Submission → first sampled token (queue wait included — the
        number a caller actually experiences)."""
        return self.first_token_at - self.submitted_at

    @property
    def latency_s(self) -> float:
        return self.completed_at - self.submitted_at

    @property
    def decode_s(self) -> float:
        """Admission → completion: the slot-time the request actually
        consumed, queue wait excluded — the SloEstimator's observation
        unit (tokens / decode_s = per-request service rate). Shipped on
        the wire ``done`` frame so REMOTE completions feed the gateway's
        admission estimator exactly like local ones."""
        return self.completed_at - self.admitted_at


class RequestQueue:
    """FIFO with close semantics. All methods are thread-safe.

    ``maxsize`` bounds the backlog: ``submit`` on a full queue raises
    ``QueueFull`` instead of growing without bound (None = unbounded, the
    pre-gateway behavior). The bound counts QUEUED requests only — in-flight
    slots are the engine's capacity, the queue's job is to cap wait."""

    def __init__(self, maxsize: Optional[int] = None):
        if maxsize is not None and maxsize < 1:
            raise ValueError(f"maxsize must be >= 1 or None, got {maxsize}")
        self.maxsize = maxsize
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._next_id = 0

    def submit(self, text, seed: int,
               request_id: Optional[int] = None,
               max_tokens: Optional[int] = None,
               tenant: str = "default", priority: int = 0,
               deadline_at: Optional[float] = None,
               trace_id: Optional[str] = None,
               group_id: Optional[int] = None,
               group_size: int = 1,
               group_index: int = 0,
               cond_scale: float = 1.0) -> Request:
        """Enqueue a request; returns it (with its assigned id). An explicit
        ``request_id`` must be fresh: ids at or below the high-water mark of
        previously issued ids are rejected rather than tracked individually,
        so a duplicate can never silently alias another request's results
        (consumers key completions, spans and bench lookups by id)."""
        text = np.asarray(text, np.int32)
        assert text.ndim == 1, f"one prompt per request, got {text.shape}"
        if max_tokens is not None and max_tokens < 1:
            # the engine clamps to [1, image_seq_len]; 0/negative would
            # silently come back as a 1-token generation
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if trace_id is None:
            # the queue is the CLI/bench edge of the system: a producer
            # that didn't propagate a trace context still gets one identity
            # per request (the gateway mints at the HTTP door and passes it)
            from ..obs.context import new_trace_id
            trace_id = new_trace_id()
        with self._cond:
            if self._closed:
                raise RuntimeError("queue is closed")
            if self.maxsize is not None and len(self._q) >= self.maxsize:
                raise QueueFull(
                    f"queue at capacity ({self.maxsize} requests waiting)")
            if request_id is None:
                request_id = self._next_id
            elif request_id < self._next_id:
                raise ValueError(
                    f"request_id {request_id} is not fresh (ids below "
                    f"{self._next_id} may already be in flight); omit "
                    "request_id or pass one above the high-water mark")
            self._next_id = request_id + 1
            req = Request(request_id=request_id, text=text, seed=seed,
                          max_tokens=max_tokens, tenant=tenant,
                          priority=priority, deadline_at=deadline_at,
                          trace_id=trace_id, group_id=group_id,
                          group_size=group_size, group_index=group_index,
                          cond_scale=float(cond_scale))
            self._q.append(req)
            self._cond.notify_all()
        return req

    @property
    def next_request_id(self) -> int:
        """The id the next auto-assigned submission will get. A consumer
        that must index per-request state BEFORE the request becomes
        takeable (the gateway replica registers the result stream first,
        then submits with this explicit id) reads this and passes it to
        ``submit(request_id=...)`` — serializing its own submitters, since
        two concurrent reservations would collide."""
        with self._lock:
            return self._next_id

    def take(self, max_n: int) -> List[Request]:
        """Dequeue up to ``max_n`` requests in FIFO order (non-blocking)."""
        out: List[Request] = []
        with self._lock:
            while self._q and len(out) < max_n:
                out.append(self._q.popleft())
        return out

    def wait_nonempty(self, timeout: Optional[float] = None,
                      _poll_s: float = 0.5) -> bool:
        """Block until a request is queued or the queue is closed. Returns
        True when a request is available.

        Every park is bounded by ``_poll_s`` and re-checks the predicate:
        drain must not rely on close()'s final notify — a producer/closer
        thread that dies before notifying (or a close() the interpreter
        never reaches during teardown) degrades to one poll interval of
        extra latency here, never an unbounded hang."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not (self._q or self._closed):
                remaining = _poll_s
                if deadline is not None:
                    remaining = min(_poll_s, deadline - time.monotonic())
                    if remaining <= 0:
                        break
                self._cond.wait(remaining)
            return bool(self._q)

    def close(self) -> None:
        """No further submissions; the engine drains and returns."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def qsize(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def drained(self) -> bool:
        """Closed AND empty — nothing left to admit."""
        with self._lock:
            return self._closed and not self._q
