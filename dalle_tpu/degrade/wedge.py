"""Wedged-engine self-detection: the liveness probe a replica runs on
itself (graftward, serving plane).

A decode engine that hangs mid-iteration — a stuck device call, a poisoned
host callback, a chaos ``wedge`` fault — leaves a process that still
accepts connections and answers the health verb: process-liveness
supervision (heartbeats, exit codes) sees a perfectly healthy replica
while every in-flight stream starves. The missing signal is **engine
progress**: a monotonic iteration counter that only the decode loop
advances. :class:`WedgeWatchdog` polls a probe returning
``(progress, busy)`` and declares a wedge when the engine is *busy*
(work admitted or queued) but *progress has frozen* past the timeout.

Discipline (mirrors ``elastic.hung_workers``):

  * **arm gate** — no trip while the counter still reads 0: a cold
    engine paying its first trace+compile inside its first dispatch is
    slow, not wedged (the ``elastic.hung_workers`` "≥1 completed step"
    rule). The counter's own value is the evidence — a change observed
    between two polls is NOT required, because a request can race the
    engine from idle to wedged inside one poll interval.
  * **idle is healthy** — ``busy=False`` resets the clock: an idle replica
    with a frozen counter is just idle, never a false page (the
    fresh-heartbeat-but-frozen-step distinction, serve-side).
  * **edge-triggered** — ``on_wedge`` fires once per frozen episode; the
    counter advancing re-arms it. The sink typically marks the replica
    unhealthy (``Replica.mark_wedged``) so the health verb self-reports
    ``wedged`` and the fleet controller runs its drain→replace path with
    no operator ``request_drain``.

The timeout bounds the longest *legitimate* single dispatch: one decode
iteration (steps_per_sync device steps) or one prefill window. Chunked
prefill (``prefill_chunk``) exists precisely to bound the latter, and each
chunk bumps the progress counter. Pure stdlib; the probe is a callable so
tests drive it without an engine.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple


class WedgeWatchdog:
    """``probe() -> (progress: int, busy: bool)`` polled every ``poll_s``;
    ``on_wedge(detail: str)`` fired on each healthy→wedged edge."""

    def __init__(self, probe: Callable[[], Tuple[int, bool]],
                 timeout_s: float, *,
                 on_wedge: Optional[Callable[[str], None]] = None,
                 poll_s: float = 0.25, clock=time.monotonic, log=print):
        assert timeout_s > 0
        self.probe = probe
        self.timeout_s = float(timeout_s)
        self.on_wedge = on_wedge
        self.poll_s = float(poll_s)
        self.clock = clock
        self.log = log
        self.wedged = False
        self.trips = 0
        self._armed = False
        self._last_progress: Optional[int] = None
        self._frozen_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the check (called by the thread; public for deterministic tests) --
    def check(self, now: Optional[float] = None) -> bool:
        """One poll. Returns True on a NEW healthy→wedged edge."""
        now = self.clock() if now is None else now
        try:
            progress, busy = self.probe()
        except Exception as exc:  # noqa: BLE001 - a dying probe must not
            # take the watchdog thread with it; the engine's own failure
            # path (worker death → replica_failed) owns that case
            self.log(f"[wedge-watchdog] probe failed: {exc!r}")
            return False
        # arm gate = the COUNTER's own evidence (progress > 0 means the
        # engine completed at least one dispatch this run — the
        # hung_workers "≥1 step" rule), NOT "changed between two polls":
        # a request can race the engine from idle to wedged inside one
        # poll interval, and a first-observation baseline at the frozen
        # value would then never arm
        if progress > 0:
            self._armed = True
        if self._last_progress is None:
            self._last_progress = progress
            self._frozen_since = now
            return False
        if progress != self._last_progress:
            self._last_progress = progress
            self._frozen_since = now
            if self.wedged:
                self.wedged = False            # progress resumed: re-arm
            return False
        if not busy:
            self._frozen_since = now           # idle ≠ wedged
            return False
        if (self._armed and not self.wedged
                and now - self._frozen_since > self.timeout_s):
            self.wedged = True
            self.trips += 1
            detail = (f"engine busy with no iteration progress for "
                      f"{now - self._frozen_since:.1f}s "
                      f"(> {self.timeout_s}s) at counter {progress}")
            if self.on_wedge is not None:
                try:
                    self.on_wedge(detail)
                except Exception as exc:  # noqa: BLE001 - the sink must
                    # not kill the watchdog; the wedge is already latched
                    self.log(f"[wedge-watchdog] on_wedge failed: {exc!r}")
            return True
        return False

    # -- thread lifecycle --------------------------------------------------
    def start(self) -> "WedgeWatchdog":
        assert self._thread is None
        self._thread = threading.Thread(target=self._loop,
                                        name="wedge-watchdog", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
