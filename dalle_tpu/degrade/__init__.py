"""graftward: proactive degradation response, shared by both planes.

graftmend (training) and graftfleet (serving) already survive components
that *die* — a SIGKILLed worker reshapes the pod, a crashed replica fails
over bitwise. This package closes the loop for components that are *sick
but alive* (Dean & Barroso, "The Tail at Scale"): the straggling training
worker that drags every lockstep collective, the worker whose graftpulse
sentries page while it keeps heartbeating, the serving replica whose
decode loop wedges while its process keeps answering health RPCs.

Three building blocks, all pure stdlib (the elastic agent imports before
jax initializes; the wedge watchdog runs inside replica processes):

  * :class:`~.detector.StragglerDetector` — flags a worker whose per-step
    completion *arrival* lags the fleet median by a sustained factor of
    the step interval (EWMA-smoothed, hysteresis-guarded, edge-triggered).
  * :class:`~.ladder.DegradeMonitor` — the page → drain response ladder
    the :class:`~..parallel.elastic.ElasticAgent` runs each poll over the
    fleet's heartbeat files (straggler verdicts + health-page markers).
  * :class:`~.wedge.WedgeWatchdog` — the engine-iteration liveness probe a
    replica process runs against its own decode loop: busy + frozen
    progress past a timeout = wedged, self-reported through the health
    verb so the fleet controller drains it with no operator page.

Consumed by ``parallel/elastic.py`` (agent-side ladder, heartbeat pages),
``fleet/controller.py`` / ``fleet/transport.py`` (wedge drains, the
outside-in frozen-progress check) and ``scripts/serve_replica.py`` (the
in-process watchdog). docs/RESILIENCE.md "Degradation ladder" is the
operator guide.
"""

from .detector import StragglerDetector, StragglerVerdict, frozen_progress
from .ladder import DegradeAction, DegradeMonitor, install_breach_pager
from .wedge import WedgeWatchdog

__all__ = [
    "DegradeAction",
    "DegradeMonitor",
    "StragglerDetector",
    "StragglerVerdict",
    "WedgeWatchdog",
    "frozen_progress",
    "install_breach_pager",
]
