"""Straggler detection over heartbeat progress (the graftward detect leg).

The signal problem is specific to lockstep SPMD: once one worker slows
down, *every* worker's step wall time stretches to match it (each step
ends at a collective), so per-worker step rate — and even per-worker step
*arrival* time, since dispatch blocks on the collective — is identical
across the fleet and cannot name the victim. What does differ is the
complement: **how long each worker spent blocked waiting for the
collective**. The healthy peers dispatch immediately and then park,
waiting for the straggler; the straggler arrives late and waits for
nobody. The straggler is the worker that never waits — the classic
wait-inversion signal (measured empirically in this repo: with a 0.8 s
host-side slow fault on a 2-process gloo pod, the victim's per-step
blocked time is ~0.03 s while its peer's is ~0.84 s).

Heartbeats therefore carry, alongside ``step`` + ``step_time``, an
optional ``blocked_s`` — the worker's self-measured device/collective
wait for its last step (``t_dispatch_s + t_sync_s`` from the grafttrace
step breakdown; the elastic worker's ``on_step`` hook forwards it).
:class:`StragglerDetector` aligns the fleet on common completed steps and
computes each worker's **wait deficit**: the median of the *other*
workers' blocked time minus its own (with two workers that is simply the
peer — the n=2 case where a whole-fleet median would split the signal
across both and flag nobody). The deficit is EWMA-smoothed, normalized by
the fleet's observed step interval, and a verdict requires the excess to
SUSTAIN for several steps with a hysteresis band below the trip threshold
— a single GC pause or checkpoint boundary never pages, and a flagged
worker must come back well under the threshold to clear. Verdicts are
edge-triggered: one per ok→straggling transition, consumed by the
:class:`~.ladder.DegradeMonitor` response ladder.

Heartbeats without ``blocked_s`` (older workers, setup phases) make the
detector inert rather than wrong — no deficit, no verdict.

Pure stdlib; time enters only through the heartbeat docs, so tests drive
it deterministically.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


def frozen_progress(step, step_time, now: float, timeout_s: float) -> bool:
    """The fresh-but-frozen core shared by training liveness
    (``parallel/elastic.py hung_workers``) and the fleet's outside-in
    replica check (``fleet/transport.py``): a progress counter that has
    completed at least one unit (``step is not None`` — the arm gate that
    keeps a long first-step compile from reading as a hang) but has not
    advanced for ``timeout_s``."""
    return (step is not None and step_time is not None
            and now - float(step_time) > timeout_s)


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    if n % 2:
        return s[mid]
    return 0.5 * (s[mid - 1] + s[mid])


@dataclasses.dataclass(frozen=True)
class StragglerVerdict:
    """One ok→straggling edge: ``deficit_s`` is the worker's EWMA wait
    deficit behind the median of its peers' collective waits,
    ``interval_s`` the fleet's EWMA step interval, ``ratio`` their
    quotient (≥ the detector's ``factor``)."""

    worker_id: int
    step: int
    deficit_s: float
    interval_s: float
    ratio: float


class _WorkerTrack:
    __slots__ = ("samples", "last_step", "deficit_ewma", "streak", "flagged")

    def __init__(self):
        # step -> (arrival wall clock, blocked_s or None)
        self.samples: Dict[int, Tuple[float, Optional[float]]] = {}
        self.last_step: Optional[int] = None
        self.deficit_ewma: Optional[float] = None
        self.streak = 0
        self.flagged = False


class StragglerDetector:
    """Feed :meth:`observe` the fleet's heartbeat docs every poll; it
    returns the NEW straggler verdicts (edge-triggered, empty most polls).

    Knobs:
      * ``factor`` — trip when EWMA wait deficit > ``factor`` × EWMA step
        interval (and > ``min_deficit_s`` absolute, so millisecond jitter
        on fast steps never trips). A host-side slowdown of ``d`` per step
        gives the victim a deficit of ≈ ``d`` against a coupled interval
        of ≈ ``base + d`` — the default 0.4 flags a worker responsible for
        ≳40% of every fleet step.
      * ``sustain`` — consecutive over-threshold steps required (the
        single-spike guard).
      * ``recover_ratio`` — a flagged worker clears only when its deficit
        falls under ``recover_ratio`` × the trip threshold (hysteresis:
        between the two thresholds the current state holds).
      * ``warmup_steps`` — completed fleet steps before any verdict (EWMAs
        need a baseline, and the symmetric first-step compile must not
        seed them; restore/compile phases are excluded by construction
        since samples only exist once steps advance).
    """

    def __init__(self, *, factor: float = 0.4, sustain: int = 3,
                 recover_ratio: float = 0.5, warmup_steps: int = 2,
                 alpha: float = 0.4, min_deficit_s: float = 0.05,
                 history: int = 64):
        assert factor > 0 and 0 < recover_ratio <= 1.0
        self.factor = float(factor)
        self.sustain = int(sustain)
        self.recover_ratio = float(recover_ratio)
        self.warmup_steps = int(warmup_steps)
        self.alpha = float(alpha)
        self.min_deficit_s = float(min_deficit_s)
        self.history = int(history)
        self._tracks: Dict[int, _WorkerTrack] = {}
        self._processed: int = 0               # completed fleet steps seen
        self._last_step: Optional[int] = None  # newest processed step
        self._last_median: Optional[float] = None
        self.interval_ewma: Optional[float] = None

    def reset(self) -> None:
        """Forget everything — a membership epoch change replaces the
        worker set and restarts the clocks; stale EWMAs from the previous
        gang must not pre-trip (or pre-clear) anyone in the new one."""
        self._tracks.clear()
        self._processed = 0
        self._last_step = None
        self._last_median = None
        self.interval_ewma = None

    @property
    def processed(self) -> int:
        """Completed fleet steps processed so far — the ladder's
        escalation clock (wall time would couple escalation speed to step
        speed exactly when a straggler has stretched the steps)."""
        return self._processed

    # -- per-worker state reads -------------------------------------------
    def deficit_of(self, worker_id: int) -> Optional[float]:
        t = self._tracks.get(worker_id)
        return t.deficit_ewma if t is not None else None

    def is_flagged(self, worker_id: int) -> bool:
        t = self._tracks.get(worker_id)
        return bool(t is not None and t.flagged)

    # -- the poll ----------------------------------------------------------
    def observe(self, beats: Dict[int, dict],
                members: List[int]) -> List[StragglerVerdict]:
        """Ingest one heartbeat snapshot (``elastic.read_heartbeats``
        shape: ``{wid: {"step": .., "step_time": .., "blocked_s": ..}}``)
        scoped to ``members``. Returns new verdicts (edges only)."""
        if len(members) < 2:
            return []                 # nobody to wait for
        for wid in members:
            doc = beats.get(wid)
            if doc is None:
                continue
            step, st = doc.get("step"), doc.get("step_time")
            if step is None or st is None:
                continue
            track = self._tracks.setdefault(int(wid), _WorkerTrack())
            if track.last_step is None or int(step) > track.last_step:
                track.last_step = int(step)
                blocked = doc.get("blocked_s")
                track.samples[int(step)] = (
                    float(st), float(blocked) if blocked is not None
                    else None)
                if len(track.samples) > self.history:
                    for s in sorted(track.samples)[:-self.history]:
                        del track.samples[s]
        return self._process(members)

    def _process(self, members: List[int]) -> List[StragglerVerdict]:
        tracks = {w: self._tracks.get(w) for w in members}
        if any(t is None for t in tracks.values()):
            return []
        verdicts: List[StragglerVerdict] = []
        while True:
            # the next fleet step every member has completed
            common = set.intersection(
                *(set(t.samples) for t in tracks.values()))
            pending = sorted(s for s in common
                             if self._last_step is None
                             or s > self._last_step)
            if not pending:
                return verdicts
            step = pending[0]
            arrivals = {w: t.samples[step][0] for w, t in tracks.items()}
            blocked = {w: t.samples[step][1] for w, t in tracks.items()}
            med_all = _median(list(arrivals.values()))
            if self._last_median is not None and self._last_step is not None:
                d_med = ((med_all - self._last_median)
                         / max(step - self._last_step, 1))
                if d_med > 0:
                    self.interval_ewma = (
                        d_med if self.interval_ewma is None
                        else self.interval_ewma
                        + self.alpha * (d_med - self.interval_ewma))
            self._last_step, self._last_median = step, med_all
            self._processed += 1
            if any(b is None for b in blocked.values()):
                continue              # no wait signal this step: inert
            for wid, t in tracks.items():
                others = [b for w, b in blocked.items() if w != wid]
                # median of the OTHERS: with n=2 this is the peer, so the
                # victim carries the full inversion instead of half of it
                # (and its peer goes negative rather than being dragged up)
                deficit = _median(others) - blocked[wid]
                t.deficit_ewma = (deficit if t.deficit_ewma is None
                                  else t.deficit_ewma
                                  + self.alpha
                                  * (deficit - t.deficit_ewma))
                if (self._processed <= self.warmup_steps
                        or self.interval_ewma is None):
                    continue
                thresh = max(self.min_deficit_s,
                             self.factor * self.interval_ewma)
                if t.flagged:
                    if t.deficit_ewma < self.recover_ratio * thresh:
                        t.flagged = False
                        t.streak = 0
                    continue
                if t.deficit_ewma > thresh:
                    t.streak += 1
                    if t.streak >= self.sustain:
                        t.flagged = True
                        verdicts.append(StragglerVerdict(
                            worker_id=wid, step=step,
                            deficit_s=t.deficit_ewma,
                            interval_s=self.interval_ewma,
                            ratio=t.deficit_ewma / self.interval_ewma))
                else:
                    t.streak = 0
