"""The response ladder: degradation verdicts → paged → drained (graftward).

:class:`DegradeMonitor` is the decide leg between the detectors and the
:class:`~..parallel.elastic.ElasticAgent`'s act leg. Each agent poll
feeds it the fleet's heartbeat snapshot; it returns :class:`DegradeAction`
rows — each emitted exactly ONCE per ok→degraded edge:

  * **straggler ladder** — a :class:`~.detector.StragglerDetector` verdict
    first **pages** (``DegradeAction(kind="page")``: log + counter +
    flight event, no membership change). If the worker stays flagged for
    ``straggler_escalate`` further completed fleet steps, the ladder
    escalates to **drain** — the agent then SIGTERMs the gang (everyone
    takes the graceful-preemption save at the next checkpoint boundary)
    and starts the next epoch *without* the straggler (the PR 10 shrink
    path; a slow host is hardware-suspect, so it loses its slot). A worker
    that recovers between the rungs resets to rung 0; a later relapse
    re-pages (edge semantics, never a page storm).
  * **health page** — a worker whose graftpulse sentry breached writes the
    breach into its heartbeat file (``Heartbeat.page``); the monitor
    treats the marker like a detector verdict already past its own
    hysteresis and goes straight to **drain** with
    ``reason="health_page"`` — the agent reshapes around it and
    **quarantine-respawns** (policy ``respawn``: the sick process is torn
    down and a fresh one takes the same slot; ``max_reconfigures`` bounds
    the crash loop if the fresh one pages again).

Pure stdlib. ``reset()`` on every epoch change — verdict state must never
outlive the membership it was computed over.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from .detector import StragglerDetector

# bounded reason tokens: these ride metric labels
# (``degrade.actions_total{reason=}``) and the agent's event log
REASON_STRAGGLER = "straggler"
REASON_HEALTH_PAGE = "health_page"


@dataclasses.dataclass(frozen=True)
class DegradeAction:
    kind: str              # "page" | "drain"
    worker_id: int
    reason: str            # REASON_STRAGGLER | REASON_HEALTH_PAGE
    detail: str = ""


class DegradeMonitor:
    def __init__(self, detector: Optional[StragglerDetector] = None, *,
                 straggler_escalate: int = 2, page_drain: bool = True):
        self.detector = (detector if detector is not None
                         else StragglerDetector())
        self.straggler_escalate = int(straggler_escalate)
        self.page_drain = bool(page_drain)
        # worker -> detector.processed at page time (escalation baseline)
        self._paged_at: Dict[int, int] = {}
        self._drained: set = set()
        self._health_paged: set = set()

    def reset(self) -> None:
        self.detector.reset()
        self._paged_at.clear()
        self._drained.clear()
        self._health_paged.clear()

    def observe(self, beats: Dict[int, dict],
                members: List[int]) -> List[DegradeAction]:
        actions: List[DegradeAction] = []
        # health pages first: a breach marker is a detector verdict that
        # already served its hysteresis inside the sentry
        if self.page_drain:
            for wid in members:
                page = (beats.get(wid) or {}).get("page")
                if not page or wid in self._health_paged:
                    continue
                self._health_paged.add(wid)
                actions.append(DegradeAction("page", wid, REASON_HEALTH_PAGE,
                                             detail=str(page)))
                if wid not in self._drained:
                    self._drained.add(wid)
                    actions.append(DegradeAction(
                        "drain", wid, REASON_HEALTH_PAGE, detail=str(page)))
        for v in self.detector.observe(beats, members):
            if v.worker_id in self._drained:
                continue
            self._paged_at[v.worker_id] = self.detector.processed
            actions.append(DegradeAction(
                "page", v.worker_id, REASON_STRAGGLER,
                detail=(f"wait deficit {v.deficit_s:.3f}s = {v.ratio:.2f}x "
                        f"the fleet step interval {v.interval_s:.3f}s "
                        f"at step {v.step}")))
        # escalation: still flagged straggler_escalate completed steps
        # after its page → drain (once)
        for wid, paged_at in list(self._paged_at.items()):
            if not self.detector.is_flagged(wid):
                self._paged_at.pop(wid)        # recovered between rungs
                continue
            if (self.detector.processed - paged_at
                    >= self.straggler_escalate and wid not in self._drained):
                self._drained.add(wid)
                self._paged_at.pop(wid)
                deficit = self.detector.deficit_of(wid)
                actions.append(DegradeAction(
                    "drain", wid, REASON_STRAGGLER,
                    detail=(f"sustained straggler after page "
                            f"(wait-deficit EWMA {deficit:.3f}s)"
                            if deficit is not None
                            else "sustained straggler after page")))
        return actions


def install_breach_pager(worker, sentry) -> None:
    """Chain a graftpulse :class:`~..obs.anomaly.HealthSentry`'s
    ``on_breach`` to the elastic worker's heartbeat page: a breach on THIS
    worker becomes a fleet-visible marker the agent's
    :class:`DegradeMonitor` drains on. Chains — never replaces — an
    existing sink (the ``train/actions.py BreachActions`` precedent), so
    local remediations and the fleet page both fire."""
    prev = sentry.on_breach

    def paged(breach, _prev=prev):
        if _prev is not None:
            _prev(breach)
        worker.page(f"{breach.detector}:{getattr(breach, 'group', '')}")

    sentry.on_breach = paged
