"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's process-group bootstrap
(deepspeed.init_distributed() / hvd.init(), reference
dalle_pytorch/distributed_backends/deepspeed_backend.py:36-39,
horovod_backend.py:20-23). Instead of one process per GPU with NCCL process
groups, we build one `jax.sharding.Mesh` over all addressable devices and let
XLA insert collectives over ICI/DCN.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MeshConfig

# The mesh for "not distributed": 1 device, all axes size 1. This is the
# JaxBackend analogue of the reference's DummyBackend (world_size=1 no-op,
# distributed_backends/dummy_backend.py).


def build_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh. If axis sizes don't cover all devices, the `dp` axis is
    auto-scaled to absorb the remainder (mirrors how DP world size in the reference
    is implied by the launcher, not the script)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = {"dp": cfg.dp, "fsdp": cfg.fsdp, "tp": cfg.tp, "sp": cfg.sp}
    fixed = sizes["fsdp"] * sizes["tp"] * sizes["sp"]
    if cfg.dp * fixed != n:
        if n % fixed != 0:
            raise ValueError(
                f"mesh axes fsdp*tp*sp={fixed} do not divide device count {n}")
        sizes["dp"] = n // fixed
    shape = tuple(sizes[a] for a in cfg.axis_names)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, cfg.axis_names)


def single_device_mesh() -> Mesh:
    cfg = MeshConfig()
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1, 1), cfg.axis_names)


def batch_spec(mesh: Mesh) -> P:
    """Batch dims shard over (dp, fsdp): fsdp acts as extra data parallelism for
    activations, like ZeRO's data-parallel groups."""
    axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    return P(axes if len(axes) > 1 else axes[0])


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, leading_replicated: int = 0):
    """Place a host batch onto the mesh, sharded along the batch dimension.
    ``leading_replicated`` axes before the batch dim stay replicated (e.g. the
    scan/step axis of a (k, b, ...) microbatch stack)."""
    spec = batch_spec(mesh)
    lead = (None,) * leading_replicated

    def put(x):
        if x.ndim <= leading_replicated:
            # per-step scalar/key leaves of a (k, ...) stack have no batch
            # axis to shard — replicate them instead of building a spec with
            # more axes than the array has
            pspec = P()
        else:
            pspec = P(*lead,
                      *(spec + (None,) * (x.ndim - 1 - leading_replicated)))
        return jax.device_put(x, NamedSharding(mesh, pspec))

    return jax.tree.map(put, batch)


def shard_stacked_batch(mesh: Mesh, batch):
    """(k, b, ...) microbatch stacks: axis 0 = scan step (replicated),
    axis 1 = batch (sharded) — the input layout of ``train_steps``."""
    return shard_batch(mesh, batch, leading_replicated=1)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    with mesh:
        yield mesh


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    dp = 1
    for a in ("dp", "fsdp"):
        if a in mesh.axis_names:
            dp *= mesh.shape[a]
    if global_batch % dp != 0:
        # reference enforces batch >= world size (distributed_backend.py:56-60)
        raise ValueError(f"global batch {global_batch} not divisible by data-parallel size {dp}")
    return global_batch // dp
