"""Pluggable distributed backend — the reference's `DistributedBackend` contract,
re-grounded on JAX collectives.

The reference (dalle_pytorch/distributed_backends/distributed_backend.py:12-178)
defines an ABC with eight overridables plus a registry/CLI layer
(distributed_utils.py:22-76). Transports were DeepSpeed→NCCL and Horovod→MPI, with a
`DummyBackend` no-op for single-process runs. Here the same surface is implemented
on `jax.distributed` + device meshes:

  * ``initialize`` → ``jax.distributed.initialize()`` (multi-host) + mesh build over
    ICI/DCN, instead of NCCL process groups.
  * ``average_all`` → on-host ``jax.pmean``-style mean via ``jax.device_get`` of an
    already-replicated scalar, or psum inside the jitted step (the idiomatic place —
    see parallel/partition.py; gradient averaging never happens post-hoc here).
  * ``local_barrier`` → ``multihost_utils.sync_global_devices``.
  * ``distribute`` → returns a sharded train-step + sharded params rather than a
    wrapped module (JAX has no mutable module to wrap).

`DummyBackend` parity = a 1-device mesh.
"""

from __future__ import annotations

import argparse
import os
from abc import ABC, abstractmethod
from typing import Any, Optional

import jax
import numpy as np

from ..chaos import io_hook
from ..config import MeshConfig
from ..utils.retry import TRANSIENT, with_retry
from .mesh import build_mesh, single_device_mesh


class DistributedBackend(ABC):
    """Same eight-method contract as the reference ABC
    (distributed_backends/distributed_backend.py:12-28)."""

    BACKEND_MODULE_NAME: str = "jax"
    BACKEND_NAME: str = "Base"

    ROOT_RANK = 0

    def __init__(self):
        self.mesh = None
        self._initialized = False

    # -- lifecycle ---------------------------------------------------------
    def has_backend(self) -> bool:
        return True

    def initialize(self, mesh_config: Optional[MeshConfig] = None):
        self._backend_initialize(mesh_config or MeshConfig())
        self._initialized = True
        return self

    def require_init(self):
        assert self._initialized, f"{self.BACKEND_NAME} backend used before initialize()"

    # -- abstract surface --------------------------------------------------
    @abstractmethod
    def wrap_arg_parser(self, parser: argparse.ArgumentParser) -> argparse.ArgumentParser: ...

    @abstractmethod
    def _backend_initialize(self, mesh_config: MeshConfig): ...

    @abstractmethod
    def _get_world_size(self) -> int: ...

    @abstractmethod
    def _get_rank(self) -> int: ...

    @abstractmethod
    def _get_local_rank(self) -> int: ...

    @abstractmethod
    def _local_barrier(self): ...

    @abstractmethod
    def _distribute(self, *, params=None, optimizer_state=None, train_step=None, **kw): ...

    @abstractmethod
    def _average_all(self, value): ...

    # -- public wrappers (mirror reference names) -------------------------
    def get_world_size(self) -> int:
        self.require_init()
        return self._get_world_size()

    def get_rank(self) -> int:
        self.require_init()
        return self._get_rank()

    def get_local_rank(self) -> int:
        self.require_init()
        return self._get_local_rank()

    def is_root_worker(self) -> bool:
        return self.get_rank() == self.ROOT_RANK

    def is_local_root_worker(self) -> bool:
        return self.get_local_rank() == self.ROOT_RANK

    def local_barrier(self):
        self.require_init()
        self._local_barrier()

    def distribute(self, **kw):
        self.require_init()
        return self._distribute(**kw)

    def average_all(self, value):
        self.require_init()
        return self._average_all(value)

    def check_batch_size(self, batch_size: int):
        # reference: batch must be >= world size (distributed_backend.py:56-60)
        assert batch_size >= self.get_world_size(), (
            f"batch size {batch_size} < world size {self.get_world_size()}")


class JaxBackend(DistributedBackend):
    """The TPU backend: one process per host, a global mesh over all chips."""

    BACKEND_NAME = "jax"

    # coordinator-connect retry policy (utils/retry.py); class-level so the
    # elastic runtime / tests can widen or pin it fleet-wide
    connect_retry_kw = {"attempts": 5, "base_delay_s": 0.2,
                        "max_delay_s": 2.0}

    def wrap_arg_parser(self, parser):
        grp = parser.add_argument_group("jax distributed backend")
        grp.add_argument("--coordinator_address", type=str, default=None,
                         help="host:port of process 0 (multi-host only)")
        grp.add_argument("--num_processes", type=int, default=None)
        grp.add_argument("--process_id", type=int, default=None)
        return parser

    def __init__(self):
        super().__init__()
        self._coordinator_address = None
        self._num_processes = None
        self._process_id = None

    def configure_from_args(self, args):
        """Stash multi-host flags parsed by wrap_arg_parser (CLI wins over env)."""
        self._coordinator_address = getattr(args, "coordinator_address", None)
        self._num_processes = getattr(args, "num_processes", None)
        self._process_id = getattr(args, "process_id", None)
        return self

    def _backend_initialize(self, mesh_config: MeshConfig):
        coord = self._coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
        nproc = self._num_processes or os.environ.get("JAX_NUM_PROCESSES")
        if coord and nproc and int(nproc) > 1:
            pid = self._process_id
            if pid is None:
                env_pid = os.environ.get("JAX_PROCESS_ID")
                pid = int(env_pid) if env_pid is not None else None
            # CPU fleets (the DCN tests / local multi-process dev) need an
            # explicit collectives implementation — jax's CPU backend has no
            # default one and multi-process programs fail at the first
            # collective with "Multiprocess computations aren't implemented".
            # Read the *configured* platform, not default_backend(): the
            # latter would instantiate the client before distributed init.
            # An explicit user/env choice (e.g. mpi) wins — only the "none"
            # default is upgraded.
            platforms = (jax.config.jax_platforms or "").lower()
            try:
                # config.read, not an attribute: jax 0.4.x doesn't expose
                # this option as a jax.config attr even after an update
                current = jax.config.read("jax_cpu_collectives_implementation")
            except Exception:  # noqa: BLE001 - option absent on this jax
                current = None
            if ("cpu" in platforms.split(",")
                    and "JAX_CPU_COLLECTIVES_IMPLEMENTATION" not in os.environ
                    and current in (None, "none")):
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            # pid None → jax.distributed.initialize infers it from platform
            # metadata (the TPU-pod norm); forcing 0 would collide across
            # hosts. The connect is retried with jittered backoff
            # (utils/retry.py): worker N dialing in before the coordinator
            # listens — routine during elastic reconfiguration, when every
            # survivor restarts at once — used to be a single attempt and a
            # dead worker. XlaRuntimeError (DEADLINE_EXCEEDED and friends)
            # is a RuntimeError, hence the widened retry_on; a genuinely
            # unreachable coordinator still fails after the budget, which
            # the elastic agent treats as a failed epoch.
            def _connect():
                io_hook("coordinator_connect")   # chaos injection point
                try:
                    jax.distributed.initialize(
                        coordinator_address=coord,
                        num_processes=int(nproc),
                        process_id=pid,
                    )
                except Exception:  # noqa: BLE001 - any failed dial must
                    # reset the process-global distributed state before
                    # re-raising: jax assigns the client BEFORE connecting,
                    # so without the shutdown every later attempt would die
                    # on "initialize should only be called once" instead of
                    # actually redialing
                    try:
                        jax.distributed.shutdown()
                    except Exception:  # noqa: BLE001 - nothing was
                        pass           # initialized; keep the real error
                    raise

            with_retry("coordinator_connect", _connect,
                       retry_kw=dict(self.connect_retry_kw,
                                     retry_on=TRANSIENT + (RuntimeError,)))
        self.mesh = build_mesh(mesh_config)

    def _get_world_size(self) -> int:
        return jax.device_count()

    def _get_rank(self) -> int:
        # global rank of this host's first worker slot = number of devices on
        # lower-indexed processes (correct even when hosts own unequal device
        # counts, unlike process_index * local_device_count)
        me = jax.process_index()
        return sum(1 for d in jax.devices() if d.process_index < me)

    def _get_local_rank(self) -> int:
        return 0  # one process per host; local root == this process

    def is_root_worker(self) -> bool:
        return jax.process_index() == 0

    def is_local_root_worker(self) -> bool:
        return True

    def _local_barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("dalle_tpu_barrier")
        # single host: nothing to synchronize

    def _distribute(self, *, params=None, optimizer_state=None, train_step=None,
                    partition_rules=None, **kw):
        """Shard params/opt-state onto the mesh and return (params, opt_state, step).

        Unlike DeepSpeed's engine wrapper (deepspeed_backend.py:135-163), the
        gradient allreduce lives *inside* the jitted step as a psum induced by
        sharding annotations; nothing is wrapped.
        """
        from .partition import shard_params
        out = []
        if params is not None:
            params = shard_params(self.mesh, params, partition_rules)
            out.append(params)
        if optimizer_state is not None:
            optimizer_state = shard_params(self.mesh, optimizer_state, partition_rules)
            out.append(optimizer_state)
        if train_step is not None:
            out.append(train_step)
        return tuple(out) if len(out) != 1 else out[0]

    def _average_all(self, value):
        """Mean over data-parallel replicas. For values produced by the jitted step
        this is already a global mean (psum in-graph); host-side scalars in a
        multi-host run go through process_allgather."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            arr = multihost_utils.process_allgather(np.asarray(value))
            return np.mean(arr)
        return np.asarray(jax.device_get(value)).mean()


class DummyBackend(DistributedBackend):
    """1-device no-op backend — parity with the reference's DummyBackend
    (distributed_backends/dummy_backend.py): lets every 'distributed' script run
    single-process with no cluster."""

    BACKEND_NAME = "Dummy"

    def wrap_arg_parser(self, parser):
        return parser

    def _backend_initialize(self, mesh_config: MeshConfig):
        self.mesh = single_device_mesh()

    def _get_world_size(self): return 1
    def _get_rank(self): return self.ROOT_RANK
    def _get_local_rank(self): return self.ROOT_RANK
    def _local_barrier(self): pass

    def _distribute(self, *, params=None, optimizer_state=None, train_step=None, **kw):
        out = [x for x in (params, optimizer_state, train_step) if x is not None]
        return tuple(out) if len(out) != 1 else out[0]

    def _average_all(self, value):
        return np.asarray(jax.device_get(value)).mean()


# --------------------------------------------------------------------------
# Registry + CLI selection (reference: distributed_utils.py:22-96)
# --------------------------------------------------------------------------

BACKENDS = {
    JaxBackend.BACKEND_NAME.lower(): JaxBackend,
    DummyBackend.BACKEND_NAME.lower(): DummyBackend,
    # reference CLI names (distributed_utils.py:22-26): the GPU engines don't
    # exist on TPU — both map onto the jax mesh backend, which covers their
    # used surface (allreduce/barrier/rank queries/distribute)
    "deepspeed": JaxBackend,
    "horovod": JaxBackend,
}

is_distributed: Optional[bool] = None
backend: Optional[DistributedBackend] = None


def wrap_arg_parser(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    parser.add_argument(
        "--distributed_backend", "--distr_backend", type=str, default=None,
        help=f"which distributed backend to use: {list(BACKENDS)}")
    # aliases map several names onto one class — add each class's flags once
    for cls in dict.fromkeys(BACKENDS.values()):
        cls().wrap_arg_parser(parser)
    return parser


def set_backend_from_args(args) -> DistributedBackend:
    """Select & validate the backend from parsed args (ref distributed_utils.py:48-76)."""
    global is_distributed, backend
    name = (getattr(args, "distributed_backend", None) or "dummy").lower()
    if name not in BACKENDS:
        raise ValueError(f"unknown distributed backend {name!r}; options: {list(BACKENDS)}")
    if (BACKENDS[name] is JaxBackend
            and name != JaxBackend.BACKEND_NAME.lower()):
        print(f"[distributed] backend {name!r} is a GPU engine; using the "
              f"TPU-native jax mesh backend (same collective surface)")
    backend = BACKENDS[name]()
    if not backend.has_backend():
        raise ModuleNotFoundError(f"backend {name} is not available")
    if hasattr(backend, "configure_from_args"):
        backend.configure_from_args(args)
    is_distributed = name != "dummy"
    return backend


def using_backend(test_backend) -> bool:
    """Type-or-name check (ref distributed_utils.py:87-96)."""
    assert backend is not None, "select a backend first"
    if isinstance(test_backend, str):
        return backend.BACKEND_NAME.lower() == test_backend.lower()
    return isinstance(backend, test_backend)
