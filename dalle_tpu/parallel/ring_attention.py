"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has NO sequence parallelism (SURVEY.md §5.7): its sequence-
scaling levers are sparse masks and reversible layers. For the TPU framework
long-context is first-class: activations shard along the sequence dimension
over the mesh's ``sp`` axis, each device holds its q chunk permanently, and
k/v chunks rotate around the ring via `lax.ppermute` (one ICI hop per step)
while a flash-style online softmax accumulates partial results — attention
over sequences P× longer than one chip's memory, with communication fully
overlappable with the chunk matmuls (XLA schedules the ppermute DMA against
the einsums).

Causality is enforced by *global* position comparison (chunk origin × chunk
size + local offset), so the math is exact for any P. Chunks wholly in a
query's future still traverse the ring but contribute only masked work — the
standard trade for keeping the schedule static; a zigzag chunk assignment can
rebalance this later.

Collectives ride the mesh exactly like the scaling-book recipe: shard_map
gives per-device code, ppermute lowers to ICI neighbor exchange.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ring_body(q, k, v, *, axis: str, nper: int, causal: bool, scale: float,
               n_valid: int):
    """Per-device program: q stays, k/v rotate. q/k/v: (b, h, n_local, d).
    ``n_valid``: true sequence length — keys at padded positions ≥ n_valid are
    masked (under causal masking valid queries already exclude them, but the
    non-causal path needs the explicit test)."""
    P_size = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    n_local = q.shape[2]
    qf = q.astype(jnp.float32) * scale
    qpos = idx * n_local + jnp.arange(n_local)                     # global q pos

    acc = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((*q.shape[:3], 1), -1e9, jnp.float32)
    l = jnp.zeros((*q.shape[:3], 1), jnp.float32)
    perm = [(i, (i + 1) % nper) for i in range(nper)]

    k_cur, v_cur = k.astype(jnp.float32), v.astype(jnp.float32)
    for t in range(nper):
        src = (idx - t) % P_size            # ring origin of the current chunk
        s = jnp.einsum("bhid,bhjd->bhij", qf, k_cur)
        kpos = src * n_local + jnp.arange(n_local)
        vis = kpos[None, :] < n_valid
        if causal:
            vis &= kpos[None, :] <= qpos[:, None]                  # (i, j)
        s = jnp.where(vis[None, None], s, -1e9)   # (1,1,i|1,j) broadcasts
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s > -0.5e9, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhij,bhjd->bhid", p, v_cur)
        m = m_new
        if t + 1 < nper:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
    safe_l = jnp.where(l > 0, l, 1.0)
    return (acc / safe_l).astype(q.dtype)


def _ring_body_zigzag(q, k, v, *, axis: str, nper: int, scale: float,
                      n_valid: int):
    """Causal ring with zigzag chunk assignment: the sequence is split into
    2P sub-chunks of m rows and device i holds sub-chunks (i, 2P-1-i), so
    every device owns one early and one late chunk — the causal workload is
    uniform instead of triangular. Each (q-sub, k-sub) quadrant whose k
    origin is wholly in the q sub's future is skipped via ``lax.cond``;
    because the early/late mix is the same on every device, the skipped work
    is ~half of every device's steps (in the plain layout device 0 would
    idle while device P-1 never skips — no critical-path win)."""
    idx = jax.lax.axis_index(axis)
    m = q.shape[2] // 2
    qf = q.astype(jnp.float32) * scale
    origins_here = (idx, 2 * nper - 1 - idx)                  # sub-chunk ids
    perm = [(i, (i + 1) % nper) for i in range(nper)]

    def quadrant(acc, mx, l, q_sub, qpos, k_sub, v_sub, kpos):
        s = jnp.einsum("bhid,bhjd->bhij", q_sub, k_sub)
        vis = (kpos[None, :] < n_valid) & (kpos[None, :] <= qpos[:, None])
        s = jnp.where(vis[None, None], s, -1e9)
        m_new = jnp.maximum(mx, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s > -0.5e9, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(mx - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhij,bhjd->bhid", p, v_sub)
        return acc, m_new, l

    # per-q-sub accumulators, derived from q so they carry the same
    # varying-over-axis type as the cond's true branch (plain constants are
    # unvarying and shard_map rejects the branch mismatch)
    state = []
    for r in range(2):
        z = qf[:, :, r * m:(r + 1) * m] * 0.0
        state.append((z, z[..., :1] - 1e9, z[..., :1]))

    k_cur, v_cur = k.astype(jnp.float32), v.astype(jnp.float32)
    for t in range(nper):
        src = (idx - t) % nper
        k_origins = (src, 2 * nper - 1 - src)
        for s_i in range(2):
            o_k = k_origins[s_i]
            k_sub = k_cur[:, :, s_i * m:(s_i + 1) * m]
            v_sub = v_cur[:, :, s_i * m:(s_i + 1) * m]
            kpos = o_k * m + jnp.arange(m)
            for r in range(2):
                o_q = origins_here[r]
                q_sub = qf[:, :, r * m:(r + 1) * m]
                qpos = o_q * m + jnp.arange(m)
                acc, mx, l = state[r]
                state[r] = jax.lax.cond(
                    o_k <= o_q,              # any visible entry in quadrant
                    lambda a, b, c: quadrant(a, b, c, q_sub, qpos,
                                             k_sub, v_sub, kpos),
                    lambda a, b, c: (a, b, c),
                    acc, mx, l)
        if t + 1 < nper:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    outs = []
    for acc, mx, l in state:
        safe_l = jnp.where(l > 0, l, 1.0)
        outs.append((acc / safe_l).astype(q.dtype))
    return jnp.concatenate(outs, axis=2)


def zigzag_perm(nper: int, m: int) -> "np.ndarray":
    """Sequence permutation placing sub-chunks (i, 2P-1-i) on device i."""
    import numpy as np
    parts = []
    for i in range(nper):
        parts.append(np.arange(i * m, (i + 1) * m))
        j = 2 * nper - 1 - i
        parts.append(np.arange(j * m, (j + 1) * m))
    return np.concatenate(parts)


@functools.lru_cache(maxsize=16)
def _make_ring_fn(mesh: Mesh, axis: str, causal: bool, nper: int, scale: float,
                  n_valid: int, zigzag: bool):
    spec = P(None, None, axis, None)
    if zigzag:
        body = functools.partial(_ring_body_zigzag, axis=axis, nper=nper,
                                 scale=scale, n_valid=n_valid)
    else:
        body = functools.partial(_ring_body, axis=axis, nper=nper,
                                 causal=causal, scale=scale, n_valid=n_valid)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   mesh: Mesh, axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   zigzag: bool = False) -> jnp.ndarray:
    """Sequence-parallel attention over (b, h, n, d) arrays whose sequence dim
    is (or will be) sharded along ``mesh[axis]``. Sequences that don't divide
    the axis are zero-padded; padded keys are masked, padded query rows are
    sliced off. ``zigzag`` (causal only) balances the causal workload by
    interleaving early/late sub-chunks per device and skipping
    wholly-invisible quadrants — exact, ~2x less attention compute at the
    critical path for large P."""
    nper = mesh.shape[axis]
    n = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if zigzag:
        assert causal, "zigzag is a causal-balancing layout"
        n_pad = -(-n // (2 * nper)) * (2 * nper)
    else:
        n_pad = -(-n // nper) * nper
    if n_pad != n:
        pad = ((0, 0), (0, 0), (0, n_pad - n), (0, 0))
        q, k, v = (jnp.pad(t, pad) for t in (q, k, v))
    fn = _make_ring_fn(mesh, axis, causal, nper, float(scale), n, zigzag)
    if zigzag:
        import numpy as np
        perm = zigzag_perm(nper, n_pad // (2 * nper))
        inv = np.argsort(perm)
        qz, kz, vz = (jnp.take(t, perm, axis=2) for t in (q, k, v))
        out = jnp.take(fn(qz, kz, vz), inv, axis=2)
    else:
        out = fn(q, k, v)
    return out[:, :, :n] if n_pad != n else out


def shard_seq(mesh: Mesh, x, axis: str = "sp"):
    """Place (b, h, n, d) with the sequence dim sharded over ``axis``."""
    return jax.device_put(x, NamedSharding(mesh, P(None, None, axis, None)))
