"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has NO sequence parallelism (SURVEY.md §5.7): its sequence-
scaling levers are sparse masks and reversible layers. For the TPU framework
long-context is first-class: activations shard along the sequence dimension
over the mesh's ``sp`` axis, each device holds its q chunk permanently, and
k/v chunks rotate around the ring via `lax.ppermute` (one ICI hop per step)
while a flash-style online softmax accumulates partial results — attention
over sequences P× longer than one chip's memory, with communication fully
overlappable with the chunk matmuls (XLA schedules the ppermute DMA against
the chunk work).

Two inner-loop implementations share the ring schedule:

  * ``kernel=True`` (default on TPU for chunks ≥ 512): each (q-chunk,
    k-chunk) pair runs the offset-parameterized Pallas flash kernels
    (ops/chunk_attention.py) — scores never materialize, per-device memory
    is O(n_local · d), and a whole-ring `jax.custom_vjp` recomputes chunks
    in a second ring pass for backward, saving only (q, k, v, o, lse).
    k/v rotate in their input dtype (bf16 halves ICI bytes vs the dense
    body's f32 rotation).
  * ``kernel=False``: the original dense einsum online-softmax body —
    reference semantics for tiny/odd chunk sizes and a cross-check oracle.

Causality is enforced by *global* position comparison (chunk origin × chunk
size + local offset), so the math is exact for any P. The ``zigzag`` layout
places sub-chunks (i, 2P-1-i) on device i: every device owns one early and
one late chunk, making the causal workload uniform; wholly-future quadrants
are skipped (dense: `lax.cond`; kernel: zero-trip in-kernel block bounds).

Structured sparse masks (axial/conv — pure functions of global (qpos, kpos),
ops/flash_attention.elem_fn_from_spec) compose with the ring in both bodies,
extending sequence parallelism beyond the full-causal pattern.

Collectives ride the mesh exactly like the scaling-book recipe: shard_map
gives per-device code, ppermute lowers to ICI neighbor exchange.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.chunk_attention import (chunk_flash_dkv, chunk_flash_dq,
                                   chunk_flash_fwd, merge_chunk, pick_block)
from ..ops.flash_attention import elem_fn_from_spec

# jax moved shard_map out of experimental (and renamed check_rep→check_vma)
# in 0.6; support both so the ring runs on every jax the repo targets
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SM_NO_CHECK = {"check_vma": False}
else:  # jax < 0.6
    from jax.experimental.shard_map import shard_map as _shard_map
    _SM_NO_CHECK = {"check_rep": False}

NEG_INF = -1e9


def _ring_body(q, k, v, *, axis: str, nper: int, causal: bool, scale: float,
               n_valid: int, elem_fn=None):
    """Per-device program: q stays, k/v rotate. q/k/v: (b, h, n_local, d).
    ``n_valid``: true sequence length — keys at padded positions ≥ n_valid are
    masked (under causal masking valid queries already exclude them, but the
    non-causal path needs the explicit test)."""
    P_size = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    n_local = q.shape[2]
    qf = q.astype(jnp.float32) * scale
    qpos = idx * n_local + jnp.arange(n_local)                     # global q pos

    acc = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((*q.shape[:3], 1), -1e9, jnp.float32)
    l = jnp.zeros((*q.shape[:3], 1), jnp.float32)
    perm = [(i, (i + 1) % nper) for i in range(nper)]

    k_cur, v_cur = k.astype(jnp.float32), v.astype(jnp.float32)
    for t in range(nper):
        src = (idx - t) % P_size            # ring origin of the current chunk
        s = jnp.einsum("bhid,bhjd->bhij", qf, k_cur)
        kpos = src * n_local + jnp.arange(n_local)
        vis = kpos[None, :] < n_valid
        if causal:
            vis &= kpos[None, :] <= qpos[:, None]                  # (i, j)
        if elem_fn is not None:
            vis &= elem_fn(qpos[:, None], kpos[None, :])
        s = jnp.where(vis[None, None], s, -1e9)   # (1,1,i|1,j) broadcasts
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s > -0.5e9, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhij,bhjd->bhid", p, v_cur)
        m = m_new
        if t + 1 < nper:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
    safe_l = jnp.where(l > 0, l, 1.0)
    return (acc / safe_l).astype(q.dtype)


def _ring_body_zigzag(q, k, v, *, axis: str, nper: int, scale: float,
                      n_valid: int, elem_fn=None):
    """Causal ring with zigzag chunk assignment: the sequence is split into
    2P sub-chunks of m rows and device i holds sub-chunks (i, 2P-1-i), so
    every device owns one early and one late chunk — the causal workload is
    uniform instead of triangular. Each (q-sub, k-sub) quadrant whose k
    origin is wholly in the q sub's future is skipped via ``lax.cond``;
    because the early/late mix is the same on every device, the skipped work
    is ~half of every device's steps (in the plain layout device 0 would
    idle while device P-1 never skips — no critical-path win)."""
    idx = jax.lax.axis_index(axis)
    m = q.shape[2] // 2
    qf = q.astype(jnp.float32) * scale
    origins_here = (idx, 2 * nper - 1 - idx)                  # sub-chunk ids
    perm = [(i, (i + 1) % nper) for i in range(nper)]

    def quadrant(acc, mx, l, q_sub, qpos, k_sub, v_sub, kpos):
        s = jnp.einsum("bhid,bhjd->bhij", q_sub, k_sub)
        vis = (kpos[None, :] < n_valid) & (kpos[None, :] <= qpos[:, None])
        if elem_fn is not None:
            vis &= elem_fn(qpos[:, None], kpos[None, :])
        s = jnp.where(vis[None, None], s, -1e9)
        m_new = jnp.maximum(mx, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s > -0.5e9, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(mx - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhij,bhjd->bhid", p, v_sub)
        return acc, m_new, l

    # per-q-sub accumulators, derived from q so they carry the same
    # varying-over-axis type as the cond's true branch (plain constants are
    # unvarying and shard_map rejects the branch mismatch)
    state = []
    for r in range(2):
        z = qf[:, :, r * m:(r + 1) * m] * 0.0
        state.append((z, z[..., :1] - 1e9, z[..., :1]))

    k_cur, v_cur = k.astype(jnp.float32), v.astype(jnp.float32)
    for t in range(nper):
        src = (idx - t) % nper
        k_origins = (src, 2 * nper - 1 - src)
        for s_i in range(2):
            o_k = k_origins[s_i]
            k_sub = k_cur[:, :, s_i * m:(s_i + 1) * m]
            v_sub = v_cur[:, :, s_i * m:(s_i + 1) * m]
            kpos = o_k * m + jnp.arange(m)
            for r in range(2):
                o_q = origins_here[r]
                q_sub = qf[:, :, r * m:(r + 1) * m]
                qpos = o_q * m + jnp.arange(m)
                acc, mx, l = state[r]
                state[r] = jax.lax.cond(
                    o_k <= o_q,              # any visible entry in quadrant
                    lambda a, b, c: quadrant(a, b, c, q_sub, qpos,
                                             k_sub, v_sub, kpos),
                    lambda a, b, c: (a, b, c),
                    acc, mx, l)
        if t + 1 < nper:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    outs = []
    for acc, mx, l in state:
        safe_l = jnp.where(l > 0, l, 1.0)
        outs.append((acc / safe_l).astype(q.dtype))
    return jnp.concatenate(outs, axis=2)


# ---------------------------------------------------------------------------
# kernelized ring: Pallas chunk kernels inside the ring schedule, whole-ring
# custom_vjp (backward = second ring pass, recomputing chunks flash-style)
# ---------------------------------------------------------------------------

def _make_flash_ring_body(axis: str, nper: int, causal: bool, scale: float,
                          n_valid: int, block: int, interpret: bool,
                          mask_spec, zigzag: bool):
    """Per-device ring program using the chunk kernels. Saves only
    (q, k, v, o, lse) for backward — the O(n_local) residual footprint that
    the dense body (autodiff through the unrolled loop) cannot give."""
    elem_fn = elem_fn_from_spec(mask_spec)
    kw = dict(scale=scale, n_valid=n_valid, causal=causal, block_q=block,
              block_k=block, elem_fn=elem_fn, interpret=interpret)
    perm = [(i, (i + 1) % nper) for i in range(nper)]

    def fwd_math(q, k, v):
        idx = jax.lax.axis_index(axis)
        n_local = q.shape[2]
        if zigzag:
            m = n_local // 2
            q_origins = (idx, 2 * nper - 1 - idx)
            state = [(jnp.zeros((*q.shape[:2], m, q.shape[3]), jnp.float32),
                      jnp.full((*q.shape[:2], m), NEG_INF, jnp.float32))
                     for _ in range(2)]
            k_cur, v_cur = k, v
            for t in range(nper):
                src = (idx - t) % nper
                k_origins = (src, 2 * nper - 1 - src)
                for s_i in range(2):
                    k_sub = k_cur[:, :, s_i * m:(s_i + 1) * m]
                    v_sub = v_cur[:, :, s_i * m:(s_i + 1) * m]
                    for r in range(2):
                        q_sub = q[:, :, r * m:(r + 1) * m]
                        o_t, lse_t = chunk_flash_fwd(
                            q_sub, k_sub, v_sub, q_origins[r] * m,
                            k_origins[s_i] * m, **kw)
                        state[r] = merge_chunk(*state[r], o_t, lse_t)
                if t + 1 < nper:
                    k_cur = jax.lax.ppermute(k_cur, axis, perm)
                    v_cur = jax.lax.ppermute(v_cur, axis, perm)
            o = jnp.concatenate([s[0] for s in state], axis=2)
            lse = jnp.concatenate([s[1] for s in state], axis=2)
        else:
            o = jnp.zeros(q.shape, jnp.float32)
            lse = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
            k_cur, v_cur = k, v
            for t in range(nper):
                src = (idx - t) % nper
                o_t, lse_t = chunk_flash_fwd(q, k_cur, v_cur, idx * n_local,
                                             src * n_local, **kw)
                o, lse = merge_chunk(o, lse, o_t, lse_t)
                if t + 1 < nper:
                    k_cur = jax.lax.ppermute(k_cur, axis, perm)
                    v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return o, lse

    @jax.custom_vjp
    def f(q, k, v):
        o, _ = fwd_math(q, k, v)
        return o.astype(q.dtype)

    def f_fwd(q, k, v):
        o, lse = fwd_math(q, k, v)
        o = o.astype(q.dtype)
        # empty rows: -1e9 (merge weight 0) → +1e9 so backward's
        # p = exp(s - lse) is exactly 0 (matches ops/flash_attention.py)
        lse = jnp.where(lse <= 0.5 * NEG_INF, -NEG_INF, lse)
        return o, (q, k, v, o, lse)

    def f_bwd(res, do):
        q, k, v, o, lse = res
        idx = jax.lax.axis_index(axis)
        n_local = q.shape[2]
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1)
        k_cur, v_cur = k, v
        dk_cur = jnp.zeros(k.shape, jnp.float32)
        dv_cur = jnp.zeros_like(dk_cur)
        if zigzag:
            m = n_local // 2
            q_origins = (idx, 2 * nper - 1 - idx)
            dq_subs = [jnp.zeros((*q.shape[:2], m, q.shape[3]), jnp.float32)
                       for _ in range(2)]
            for t in range(nper):
                src = (idx - t) % nper
                k_origins = (src, 2 * nper - 1 - src)
                dk_parts, dv_parts = [], []
                for s_i in range(2):
                    k_sub = k_cur[:, :, s_i * m:(s_i + 1) * m]
                    v_sub = v_cur[:, :, s_i * m:(s_i + 1) * m]
                    dk_inc = jnp.zeros((*q.shape[:2], m, q.shape[3]),
                                       jnp.float32)
                    dv_inc = jnp.zeros_like(dk_inc)
                    for r in range(2):
                        sl = slice(r * m, (r + 1) * m)
                        args = (q[:, :, sl], k_sub, v_sub, do[:, :, sl],
                                lse[:, :, sl], delta[:, :, sl],
                                q_origins[r] * m, k_origins[s_i] * m)
                        dq_subs[r] = dq_subs[r] + chunk_flash_dq(*args, **kw)
                        dkc, dvc = chunk_flash_dkv(*args, **kw)
                        dk_inc = dk_inc + dkc
                        dv_inc = dv_inc + dvc
                    dk_parts.append(dk_inc)
                    dv_parts.append(dv_inc)
                dk_cur = dk_cur + jnp.concatenate(dk_parts, axis=2)
                dv_cur = dv_cur + jnp.concatenate(dv_parts, axis=2)
                if t + 1 < nper:
                    k_cur = jax.lax.ppermute(k_cur, axis, perm)
                    v_cur = jax.lax.ppermute(v_cur, axis, perm)
                # dk/dv ride every hop (nper total) so each chunk's gradient
                # finishes the full circle back to its home device
                dk_cur = jax.lax.ppermute(dk_cur, axis, perm)
                dv_cur = jax.lax.ppermute(dv_cur, axis, perm)
            dq = jnp.concatenate(dq_subs, axis=2)
        else:
            dq = jnp.zeros(q.shape, jnp.float32)
            for t in range(nper):
                src = (idx - t) % nper
                args = (q, k_cur, v_cur, do, lse, delta,
                        idx * n_local, src * n_local)
                dq = dq + chunk_flash_dq(*args, **kw)
                dkc, dvc = chunk_flash_dkv(*args, **kw)
                dk_cur = dk_cur + dkc
                dv_cur = dv_cur + dvc
                if t + 1 < nper:
                    k_cur = jax.lax.ppermute(k_cur, axis, perm)
                    v_cur = jax.lax.ppermute(v_cur, axis, perm)
                dk_cur = jax.lax.ppermute(dk_cur, axis, perm)
                dv_cur = jax.lax.ppermute(dv_cur, axis, perm)
        return (dq.astype(q.dtype), dk_cur.astype(k.dtype),
                dv_cur.astype(v.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


def zigzag_perm(nper: int, m: int) -> "np.ndarray":
    """Sequence permutation placing sub-chunks (i, 2P-1-i) on device i."""
    import numpy as np
    parts = []
    for i in range(nper):
        parts.append(np.arange(i * m, (i + 1) * m))
        j = 2 * nper - 1 - i
        parts.append(np.arange(j * m, (j + 1) * m))
    return np.concatenate(parts)


@functools.lru_cache(maxsize=32)
def _make_ring_fn(mesh: Mesh, axis: str, causal: bool, nper: int, scale: float,
                  n_valid: int, zigzag: bool, kernel: bool, block: int,
                  interpret: bool, mask_spec):
    spec = P(None, None, axis, None)
    if kernel:
        body = _make_flash_ring_body(axis, nper, causal, scale, n_valid,
                                     block, interpret, mask_spec, zigzag)
        # pallas_call out_shapes carry no varying-manual-axes metadata;
        # correctness is covered by the numerics tests against the dense body
        return _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, **_SM_NO_CHECK)
    if zigzag:
        body = functools.partial(_ring_body_zigzag, axis=axis, nper=nper,
                                 scale=scale, n_valid=n_valid,
                                 elem_fn=elem_fn_from_spec(mask_spec))
    else:
        body = functools.partial(_ring_body, axis=axis, nper=nper,
                                 causal=causal, scale=scale, n_valid=n_valid,
                                 elem_fn=elem_fn_from_spec(mask_spec))
    return _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   mesh: Mesh, axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   zigzag: bool = False,
                   kernel: Optional[bool] = None,
                   block: Optional[int] = None,
                   mask_spec=None,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """Sequence-parallel attention over (b, h, n, d) arrays whose sequence dim
    is (or will be) sharded along ``mesh[axis]``. Sequences that don't divide
    the axis are zero-padded; padded keys are masked, padded query rows are
    sliced off.

    ``zigzag`` (causal only) balances the causal workload by interleaving
    early/late sub-chunks per device and skipping wholly-invisible quadrants —
    exact, ~2x less attention compute at the critical path for large P.

    ``kernel``: run each chunk pair through the Pallas flash chunk kernels
    (O(n_local·d) memory, whole-ring custom_vjp) instead of the dense einsum
    body. Default: auto — on for TPU when the chunk size tiles cleanly and is
    ≥ 512 (below that the dense body's single fused einsum wins).

    ``mask_spec``: structured sparse pattern (axial/conv tuples accepted by
    ops/flash_attention.elem_fn_from_spec) applied on top of causal masking —
    evaluated on global positions, so sp composes with the DALL·E sparse
    attention mix. Block-aligned ('block') and arbitrary tabled masks are not
    supported under the ring (they need host-side block lists).
    """
    nper = mesh.shape[axis]
    n = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if mask_spec is not None:
        assert mask_spec[0] in ("axial", "conv"), (
            "ring attention supports structured (axial/conv) mask specs only")
    if zigzag:
        assert causal, "zigzag is a causal-balancing layout"
        n_pad = -(-n // (2 * nper)) * (2 * nper)
        chunk = n_pad // (2 * nper)
    else:
        n_pad = -(-n // nper) * nper
        chunk = n_pad // nper
    blk = pick_block(chunk) if block is None else block
    if kernel is None:
        kernel = (blk is not None and chunk >= 512
                  and jax.default_backend() == "tpu")
    if kernel and blk is None:
        raise ValueError(f"chunk size {chunk} has no valid kernel tiling; "
                         "use kernel=False")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if n_pad != n:
        pad = ((0, 0), (0, 0), (0, n_pad - n), (0, 0))
        q, k, v = (jnp.pad(t, pad) for t in (q, k, v))
    fn = _make_ring_fn(mesh, axis, causal, nper, float(scale), n, zigzag,
                       bool(kernel), blk or 0, bool(interpret), mask_spec)
    if zigzag:
        import numpy as np
        perm = zigzag_perm(nper, n_pad // (2 * nper))
        inv = np.argsort(perm)
        qz, kz, vz = (jnp.take(t, perm, axis=2) for t in (q, k, v))
        out = jnp.take(fn(qz, kz, vz), inv, axis=2)
    else:
        out = fn(q, k, v)
    return out[:, :, :n] if n_pad != n else out


def shard_seq(mesh: Mesh, x, axis: str = "sp"):
    """Place (b, h, n, d) with the sequence dim sharded over ``axis``."""
    return jax.device_put(x, NamedSharding(mesh, P(None, None, axis, None)))
