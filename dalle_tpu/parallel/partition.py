"""Parameter partitioning: regex path rules → NamedSharding over the mesh.

This is where the reference's entire parallelism story (replicated model +
allreduced grads via DeepSpeed/Horovod, SURVEY.md §2.6) collapses into sharding
annotations: with params replicated and the batch sharded over ``dp``, XLA's SPMD
partitioner inserts the gradient psum over ICI automatically — there is no
explicit allreduce anywhere in the framework.

On top of DP parity we add:
  * ``fsdp`` — ZeRO-like sharding of params/grads/optimizer state along the model's
    largest dimension (reference got this from DeepSpeed ZeRO config,
    legacy/train_dalle.py:502-507).
  * ``tp`` — Megatron-style tensor parallelism on attention heads and FF hidden dim.
  * ``sp`` — sequence parallelism; activations shard along sequence (ring attention
    in parallel/ring_attention.py).
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Rules: (path_regex, PartitionSpec). First match wins. Paths are '/'-joined
# flax param paths, e.g. "transformer/layers_0/attn/to_qkv/kernel".
#
# Conventions:
#   - Linear kernels are (in, out).
#   - QKV/out projections: shard the head-structured dim over tp.
#   - FF in/out: shard hidden dim over tp.
#   - Embeddings: shard vocab over tp (gives sharded logits matmul).
#   - fsdp shards the *other* large dim (ZeRO-style), composable with tp.
DEFAULT_RULES: Tuple[Tuple[str, P], ...] = (
    # attention projections
    (r".*attn.*(to_qkv|to_q|to_kv|query|key|value)/kernel$", P("fsdp", "tp")),
    (r".*attn.*(to_out|out_proj)/kernel$",                   P("tp", "fsdp")),
    # feed-forward
    (r".*(ff|mlp).*(w1|wi|fc1|dense_in)/kernel$",            P("fsdp", "tp")),
    (r".*(ff|mlp).*(w2|wo|fc2|dense_out)/kernel$",           P("tp", "fsdp")),
    # embeddings + output head. Vocab shards over BOTH axes with the feature
    # dim replicated: a gather from a vocab-sharded table emits a replicated
    # feature dim, so activations stay batch-sharded at remat-block boundaries
    # (feature-sharded tables force an involuntary full-remat reshard in the
    # SPMD partitioner: dim-over-fsdp gather output vs batch-over-(dp,fsdp)
    # block inputs).
    (r".*(tok_emb|text_emb|image_emb|embedding)/embedding$", P(("tp", "fsdp"),)),
    (r".*(to_logits|logits|head)/kernel$",                   P("fsdp", "tp")),
    # conv kernels (dVAE/VQGAN): shard output channels over fsdp only
    (r".*conv.*/kernel$",                                    P(None, None, None, "fsdp")),
    # biases / norms / scales: replicate ('g' only as a full component name)
    (r".*(bias|scale|embedding_pos)$|(^|.*/)g$",             P()),
)


def spec_for(path: str, shape: Tuple[int, ...],
             rules: Optional[Sequence[Tuple[str, P]]] = None,
             mesh: Optional[Mesh] = None) -> P:
    rules = DEFAULT_RULES if rules is None else rules
    for pat, spec in rules:
        if re.match(pat, path):
            spec = _fit_spec(spec, shape, mesh)
            return spec
    return P()


def _fit_spec(spec: P, shape: Tuple[int, ...], mesh: Optional[Mesh]) -> P:
    """Clip a spec to the array rank and drop axes that don't divide the dim
    (falls back to replication on that dim, like t5x's logical-axis fallback).

    Size-1 mesh axes are dropped from tuple entries — ``('tp', 'fsdp')`` on a
    tp=1 mesh becomes ``'fsdp'``. Placement is identical either way, but the
    spelling matters: GSPMD emits the normalized form on a jitted step's
    OUTPUTS, so a second same-config trainer built with the un-normalized
    input spelling would miss the executable cache and recompile the whole
    step (~seconds) for a byte-identical program."""
    parts = list(spec)
    parts = parts[: len(shape)] + [None] * (len(shape) - len(parts))
    if mesh is not None:
        for i, ax in enumerate(parts):
            if ax is None:
                continue
            axes = tuple(a for a in (ax if isinstance(ax, tuple) else (ax,))
                         if mesh.shape.get(a, 1) > 1)
            size = 1
            for a in axes:
                size *= mesh.shape[a]
            if size == 1 or shape[i] % size != 0:
                parts[i] = None
            else:
                parts[i] = axes[0] if len(axes) == 1 else axes
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def tree_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for kp, leaf in flat:
        path = "/".join(_key_str(k) for k in kp)
        out.append((path, leaf))
    return out


def _key_str(k):
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def make_param_shardings(mesh: Mesh, params,
                         rules: Optional[Sequence[Tuple[str, P]]] = None):
    """A pytree of NamedSharding matching ``params``' structure."""
    def per_path(kp, leaf):
        path = "/".join(_key_str(k) for k in kp)
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, spec_for(path, shape, rules, mesh))
    return jax.tree_util.tree_map_with_path(per_path, params)


def shard_params(mesh: Mesh, params, rules=None):
    """Place a (host or single-device) param tree onto the mesh per the rules."""
    shardings = make_param_shardings(mesh, params, rules)
    return jax.tree.map(jax.device_put, params, shardings)


def constrain(mesh: Mesh, x, *spec_axes):
    """Sharding constraint helper for activations inside jitted steps."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec_axes)))


def commit_to_mesh(mesh: Mesh, tree):
    """Replicate every leaf that is not already committed to a mesh sharding.

    ``TrainState.create`` builds the step counter and the optimizer's count
    scalars eagerly (``jnp.zeros``) — uncommitted single-device arrays. The
    params (and the mu/nu moments derived from them) are mesh-committed, so
    the FIRST train_step call carries a mixed signature, while its outputs
    come back fully mesh-committed: the second call then misses the
    executable cache and recompiles the whole program once (graftir caught
    this as a one-step retrace on every trainer). Committing the stray
    leaves up front makes the first call's signature the steady-state one —
    one compile for the life of the trainer."""
    repl = NamedSharding(mesh, P())

    def place(x):
        if isinstance(x, jax.Array) and not x.committed:
            return jax.device_put(x, repl)
        return x

    return jax.tree.map(place, tree)
