"""graftmend elastic pod runtime: membership epochs, heartbeats, liveness,
and the supervising agent that reshapes a pod around lost workers
(docs/RESILIENCE.md).

The reference's training loop assumes a fixed, immortal worker set; on a
real pod, preemption is routine. This module makes worker-set membership a
first-class, *versioned* fact:

  * **Membership epoch** (:class:`Epoch`, :class:`EpochFile`) — an atomic
    JSON record in the shared run directory: epoch number, the stable
    worker ids that are members, each member's ``process_id`` for
    ``jax.distributed.initialize``, the epoch's coordinator port. Every
    reconfiguration bumps the epoch; workers and agent agree on topology
    by reading one file instead of gossiping.
  * **Heartbeats** (:class:`Heartbeat`, :func:`read_heartbeats`,
    :func:`stale_workers`) — each worker atomically rewrites
    ``hb_<worker_id>.json`` (pid/step/epoch/wall-clock) from the training
    loop's ``on_step`` hook, write-through the retry layer and the chaos
    ``heartbeat`` injection site. Liveness = file age under a timeout.
  * **Worker side** (:class:`ElasticWorker`) — beats on every step and
    (optionally) watches PEER heartbeats from a daemon thread: a hung peer
    means the next collective never completes, and a worker blocked inside
    a gloo collective cannot be interrupted from Python — so the watcher
    exits the process with :data:`EXIT_RECONFIGURE`, handing recovery to
    the agent. That is the torchelastic teardown model, chosen on purpose:
    in-process ``jax.distributed.shutdown``/re-init cannot rescue a thread
    parked in a dead collective.
  * **Agent side** (:class:`ElasticAgent`) — the supervisor that owns the
    gang: spawns one process per member, watches child exits AND heartbeat
    staleness, and on any failure event tears the epoch down (SIGTERM so
    survivors take their graceful-preemption save, then SIGKILL
    stragglers), writes epoch N+1 — same membership (``policy="respawn"``,
    a replacement worker takes the dead worker's slot) or the survivors
    only (``policy="shrink"``, the pod reshapes to the smaller world) —
    and relaunches. Respawned workers re-run ``jax.distributed.initialize``
    at the new world size (retried — the whole gang dials in at once),
    orbax-restore the last durable step with resharding onto the new mesh
    (``partition.commit_to_mesh`` placement), and resume; the persistent
    XLA compile cache (``utils.misc.enable_compilation_cache``) makes the
    rejoin near-zero-compile.

Recovery invariant (asserted by ``scripts/chaos_smoke.py`` over the real
2-process gloo/DCN path): post-recovery state is bitwise-identical to an
uninterrupted run at the same step — determinism keys every batch and rng
draw off the host step, so re-executing [last-durable-step, crash-step]
reproduces the same bits.

Pure stdlib + retry/chaos/obs (no jax): the agent must import cheaply, and
workers use it before jax initializes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

from ..chaos import io_hook
from ..degrade import DegradeMonitor
from ..degrade.detector import frozen_progress
from ..obs import counter_add, record_event
from ..utils.retry import retry

# worker exit code meaning "membership changed under me — respawn me into
# the next epoch" (distinct from 0 = done and from crash codes)
EXIT_RECONFIGURE = 77

EPOCH_FILE = "epoch.json"

# env handoff: agent -> worker
DIR_ENV = "DALLE_ELASTIC_DIR"
WORKER_ENV = "DALLE_ELASTIC_WORKER"


# ---------------------------------------------------------------------------
# membership epochs
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Epoch:
    """One generation of pod membership. ``members`` are stable WORKER ids
    (a worker keeps its id across epochs; a shrink removes ids, a respawn
    reuses them); a member's ``process_id`` for jax.distributed is its
    index in the list."""

    epoch: int
    members: List[int]
    port: int
    coordinator: str = "127.0.0.1"

    @property
    def nproc(self) -> int:
        return len(self.members)

    @property
    def coordinator_address(self) -> str:
        return f"{self.coordinator}:{self.port}"

    def process_id(self, worker_id: int) -> Optional[int]:
        try:
            return self.members.index(worker_id)
        except ValueError:
            return None


class EpochFile:
    """Atomic read/write of the epoch record in the shared run dir."""

    def __init__(self, run_dir: str):
        self.path = os.path.join(run_dir, EPOCH_FILE)

    def read(self) -> Optional[Epoch]:
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        return Epoch(epoch=int(doc["epoch"]),
                     members=[int(m) for m in doc["members"]],
                     port=int(doc["port"]),
                     coordinator=doc.get("coordinator", "127.0.0.1"))

    @retry("epoch_write", attempts=4, base_delay_s=0.02)
    def write(self, ep: Epoch) -> Epoch:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(dataclasses.asdict(ep), fh)
        os.replace(tmp, self.path)
        return ep


# ---------------------------------------------------------------------------
# heartbeats + liveness
# ---------------------------------------------------------------------------

def _hb_path(run_dir: str, worker_id: int) -> str:
    return os.path.join(run_dir, f"hb_{worker_id}.json")


class Heartbeat:
    """Worker-side liveness beacon: atomic rewrite of one small JSON file,
    throttled to ``interval_s``, written through the retry layer (a full
    disk or NFS blip must not kill the step loop) and the chaos
    ``heartbeat`` injection site.

    Each beat carries PROGRESS, not just presence: ``step`` (last
    completed host step) and ``step_time`` (wall clock of the last time
    the step ADVANCED). Liveness readers distinguish three states: file
    fresh + step advancing (healthy), file fresh + step frozen past a
    progress timeout (hung main thread — the beater below keeps the file
    fresh through a hang), file present but old (frozen/killed process)."""

    def __init__(self, run_dir: str, worker_id: int, *,
                 interval_s: float = 0.5):
        self.path = _hb_path(run_dir, worker_id)
        self.worker_id = int(worker_id)
        self.interval_s = float(interval_s)
        self._last = 0.0
        self._step: Optional[int] = None
        self._step_time: Optional[float] = None
        # graftward straggler signal: the worker's self-measured device/
        # collective wait for its last step (grafttrace t_dispatch+t_sync).
        # In lockstep SPMD every worker's step WALL time is the same; the
        # one that never waits is the straggler (degrade/detector.py).
        self._blocked_s: Optional[float] = None
        # graftward health page: a sentry breach on THIS worker, carried
        # in every subsequent beat so the agent's DegradeMonitor sees a
        # fleet-visible page instead of a process-local log line
        self._page: Optional[str] = None
        # the beater thread and the fit thread's on_step both write; the
        # shared tmp path must never be truncated/renamed mid-write
        self._write_lock = threading.Lock()

    def page(self, reason: str, epoch: Optional[int] = None) -> None:
        """Latch a health page into the beacon and publish it NOW (the
        agent must not wait out the write throttle to learn a worker is
        sick). Sticky for the life of this process — the drain decision is
        the agent's; a page that cleared locally still warranted it."""
        self._page = str(reason)
        self._write(epoch, time.time())
        self._last = time.time()

    def beat(self, step: Optional[int] = None,
             epoch: Optional[int] = None, *,
             blocked_s: Optional[float] = None,
             force: bool = False) -> bool:
        now = time.time()
        if step is not None and step != self._step:
            self._step = step
            self._step_time = now
            if blocked_s is not None:
                self._blocked_s = float(blocked_s)
        if not force and now - self._last < self.interval_s:
            return False
        self._write(epoch, now)
        self._last = now
        return True

    @retry("heartbeat", attempts=3, base_delay_s=0.02, max_delay_s=0.2)
    def _write(self, epoch, now) -> None:
        io_hook("heartbeat")             # chaos injection point
        with self._write_lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"worker_id": self.worker_id, "pid": os.getpid(),
                           "time": now, "step": self._step,
                           "step_time": self._step_time,
                           "blocked_s": self._blocked_s, "epoch": epoch,
                           "page": self._page}, fh)
            os.replace(tmp, self.path)


def read_heartbeats(run_dir: str) -> Dict[int, dict]:
    """Every parseable heartbeat in the run dir (a torn write — impossible
    with the atomic replace, but cheap to tolerate — reads as absent)."""
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("hb_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(run_dir, name), encoding="utf-8") as fh:
                doc = json.load(fh)
            out[int(doc["worker_id"])] = doc
        except (OSError, ValueError, KeyError):
            continue
    return out


def stale_workers(run_dir: str, members: List[int], timeout_s: float,
                  now: Optional[float] = None) -> List[int]:
    """Members whose heartbeat is older than ``timeout_s`` (or missing).
    The caller supplies the membership — a departed worker's leftover file
    must not read as a zombie."""
    now = time.time() if now is None else now
    beats = read_heartbeats(run_dir)
    out = []
    for wid in members:
        doc = beats.get(wid)
        if doc is None or now - float(doc.get("time", 0.0)) > timeout_s:
            out.append(wid)
    return out


def hung_workers(run_dir: str, members: List[int], timeout_s: float,
                 now: Optional[float] = None) -> List[int]:
    """Members that are provably WEDGED — never a worker that simply
    hasn't come up yet (a missing heartbeat means "still starting"; the
    agent's child-exit detection and run deadline own that case). Two
    shapes count:

      * file present but older than ``timeout_s`` — the whole process is
        frozen or gone (the beater thread would otherwise keep it fresh);
      * file fresh but the STEP hasn't advanced for ``timeout_s`` after
        having completed at least one step — a hung main thread behind a
        live beater. The ≥1-step arm gate keeps the long first-step
        compile from reading as a hang."""
    now = time.time() if now is None else now
    beats = read_heartbeats(run_dir)
    out = []
    for wid in members:
        doc = beats.get(wid)
        if doc is None:
            continue
        if now - float(doc.get("time", 0.0)) > timeout_s:
            out.append(wid)
            continue
        # fresh file, frozen step: the shared graftward core — the same
        # predicate the fleet transport runs against a replica's engine
        # iteration counter (degrade/detector.py)
        if frozen_progress(doc.get("step"), doc.get("step_time"), now,
                           timeout_s):
            out.append(wid)
    return out


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

class ElasticWorker:
    """What a training process runs: beat from ``fit(on_step=...)``, watch
    peers, exit for respawn when the pod must reshape.

    ``peer_timeout_s > 0`` starts a daemon watcher: when any OTHER member's
    heartbeat goes stale past the timeout, the watcher records the event
    and calls ``on_peer_dead`` (default: ``os._exit(EXIT_RECONFIGURE)``).
    The hard exit is deliberate — see the module docstring: the main thread
    is typically parked inside a gloo collective that will never complete
    once the peer is gone, so only a process-level teardown can hand
    control back to the agent. The agent notices the exit (and the hung
    peer's stale heartbeat) and rebuilds the epoch."""

    def __init__(self, run_dir: str, worker_id: int, epoch: Epoch, *,
                 hb_interval_s: float = 0.5, peer_timeout_s: float = 0.0,
                 poll_s: float = 0.5,
                 on_peer_dead: Optional[Callable[[int], None]] = None,
                 log=print):
        self.run_dir = run_dir
        self.worker_id = int(worker_id)
        self.epoch = epoch
        self.heartbeat = Heartbeat(run_dir, worker_id,
                                   interval_s=hb_interval_s)
        self.peer_timeout_s = float(peer_timeout_s)
        self.poll_s = float(poll_s)
        self.on_peer_dead = on_peer_dead
        self.log = log
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._beater: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ElasticWorker":
        """Start the beater (and peer watcher) threads. Call EARLY — the
        beater keeps the heartbeat fresh through the long no-step phases
        (backend dial-in, restore, first-step compile) that the step hook
        cannot cover; progress-based liveness (``hung_workers``) is what
        distinguishes those from a real hang."""
        self.heartbeat.beat(step=None, epoch=self.epoch.epoch, force=True)
        self._beater = threading.Thread(
            target=self._beat_loop, name="elastic-heartbeat", daemon=True)
        self._beater.start()
        if self.peer_timeout_s > 0 and self.epoch.nproc > 1:
            self._watcher = threading.Thread(
                target=self._watch_peers, name="elastic-peer-watch",
                daemon=True)
            self._watcher.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def on_step(self, step: int,
                blocked_s: Optional[float] = None) -> None:
        """The ``BaseTrainer.fit(on_step=...)`` hook: records progress (the
        beater publishes it even while a later step wedges).
        ``blocked_s`` — the worker's device/collective wait for its last
        step — feeds the agent's straggler detector; callers with a
        grafttrace breakdown forward ``t_dispatch_s + t_sync_s`` (one step
        stale is fine, the detector smooths)."""
        try:
            self.heartbeat.beat(step=step, epoch=self.epoch.epoch,
                                blocked_s=blocked_s)
        except Exception as exc:  # noqa: BLE001 - a heartbeat outage past
            # the retry budget must not kill the training loop it reports
            # on; a quiet/stale file IS the failure signal
            self.log(f"[elastic] heartbeat beat failed: {exc!r}")

    def page(self, reason: str) -> None:
        """Publish a health page (graftward): latch ``reason`` into the
        heartbeat file so the agent's DegradeMonitor treats this worker
        like a straggler verdict — clean save, reshape around it,
        quarantine-respawn. Wire a graftpulse sentry to this via
        ``degrade.install_breach_pager(worker, sentry)``. Best-effort:
        a page lost to a heartbeat outage is re-published by every later
        beat (the marker is sticky)."""
        counter_add("degrade.pages_total", 1.0,
                    labels={"reason": "health_page"})
        record_event("worker_paged", worker_id=self.worker_id,
                     epoch=self.epoch.epoch, reason=reason)
        try:
            self.heartbeat.page(reason, epoch=self.epoch.epoch)
        except Exception as exc:  # noqa: BLE001 - same contract as
            # on_step: a beacon outage must not kill the loop it reports on
            self.log(f"[elastic] health page publish failed: {exc!r}")

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat.interval_s):
            try:
                self.heartbeat.beat(epoch=self.epoch.epoch, force=True)
            except Exception as exc:  # noqa: BLE001 - a dying beater must
                # not take the process with it; a quiet file IS the signal
                self.log(f"[elastic] heartbeat write failed: {exc!r}")

    # -- peer liveness -----------------------------------------------------
    def _watch_peers(self) -> None:
        peers = [m for m in self.epoch.members if m != self.worker_id]
        while not self._stop.wait(self.poll_s):
            dead = hung_workers(self.run_dir, peers, self.peer_timeout_s)
            if not dead:
                continue
            wid = dead[0]
            self.log(f"[elastic] worker {self.worker_id}: peer {wid} "
                     f"wedged (no progress/beat > {self.peer_timeout_s}s) "
                     "— requesting reconfiguration")
            counter_add("elastic.peer_dead_total", 1.0)
            record_event("elastic_peer_dead", worker_id=self.worker_id,
                         peer=wid, epoch=self.epoch.epoch)
            if self.on_peer_dead is not None:
                self.on_peer_dead(wid)
            else:
                os._exit(EXIT_RECONFIGURE)
            return

    # -- worker-side env plumbing -----------------------------------------
    @classmethod
    def from_env(cls, environ=os.environ, **kw) -> "ElasticWorker":
        """Build from the agent's env handoff: run dir + stable worker id
        from the env, topology from the epoch file."""
        run_dir = environ[DIR_ENV]
        worker_id = int(environ[WORKER_ENV])
        ep = EpochFile(run_dir).read()
        if ep is None:
            raise FileNotFoundError(f"no epoch file in {run_dir}")
        return cls(run_dir, worker_id, ep, **kw)


# ---------------------------------------------------------------------------
# agent side
# ---------------------------------------------------------------------------

def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def python_worker_env(devices_per_proc: int = 1, repo_root: str = "",
                      extra: Optional[dict] = None) -> dict:
    """Env for a spawned CPU-mesh worker process — the ``_run_dcn``
    machinery from tests/test_parallel.py, promoted into the harness so
    the chaos smoke, the elastic agent's callers, and the DCN tests build
    children the same way: force the CPU platform, pin the virtual device
    count (replacing any inherited ``xla_force_host_platform_device_count``
    — a parent's 8-device flag would silently change the child's world),
    and put the repo on PYTHONPATH."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={devices_per_proc}"
    ).strip()
    if repo_root:
        env["PYTHONPATH"] = repo_root
    env.update(extra or {})
    return env


class ElasticAgent:
    """The gang supervisor (torchelastic-style): spawn, watch, reshape.

    ``spawn(worker_id, epoch) -> subprocess.Popen`` is supplied by the
    caller (chaos_smoke builds python children; a launcher would exec the
    training CLI). The agent owns the epoch file: it writes epoch N before
    spawning its members, so a worker's view of topology is always a read
    of one atomic file.

    ``run()`` supervises until every member of the current epoch exits 0
    (returns the event log) or ``deadline_s`` passes (raises). Failure
    events — a child exiting nonzero (crash or EXIT_RECONFIGURE) or a
    running child whose heartbeat goes stale (hang; the agent SIGKILLs it)
    — trigger ``_reconfigure``: SIGTERM the survivors (their graceful-
    preemption handler saves + exits 0), escalate to SIGKILL after
    ``term_grace_s`` (a survivor blocked in a dead collective never
    reaches its step boundary), then write epoch N+1 per ``policy`` and
    respawn. ``max_reconfigures`` bounds crash loops."""

    def __init__(self, run_dir: str,
                 spawn: Callable[[int, Epoch], subprocess.Popen],
                 members: List[int], *, policy: str = "respawn",
                 hb_timeout_s: float = 0.0, poll_s: float = 0.2,
                 term_grace_s: float = 10.0, max_reconfigures: int = 4,
                 degrade: Optional[DegradeMonitor] = None,
                 log=print):
        assert policy in ("respawn", "shrink"), policy
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self.spawn = spawn
        self.all_members = list(members)
        self.policy = policy
        # graftward (docs/RESILIENCE.md "Degradation ladder"): when set,
        # every poll also feeds the fleet's heartbeats to the degradation
        # monitor — straggler verdicts page then drain (reshape WITHOUT
        # the slow worker), health-page markers drain straight away
        # (quarantine-respawn: fresh process, same slot). None = PR 10
        # behavior, dead/hung detection only.
        self.degrade = degrade
        self.hb_timeout_s = float(hb_timeout_s)
        self.poll_s = float(poll_s)
        self.term_grace_s = float(term_grace_s)
        self.max_reconfigures = int(max_reconfigures)
        self.log = log
        self.epoch_file = EpochFile(run_dir)
        self.epoch: Optional[Epoch] = None
        self.procs: Dict[int, subprocess.Popen] = {}
        self.done: Dict[int, int] = {}          # worker_id -> exit code 0
        self.events: List[dict] = []            # the smoke's verdict input
        self.reconfigures = 0

    # -- bookkeeping -------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        ev = {"kind": kind, "time": time.time(),
              "epoch": self.epoch.epoch if self.epoch else -1, **fields}
        self.events.append(ev)
        record_event(f"elastic_{kind}", **{k: v for k, v in ev.items()
                                           if k != "kind"})
        self.log(f"[elastic-agent] {kind}: "
                 + " ".join(f"{k}={v}" for k, v in fields.items()))

    # -- epoch lifecycle ---------------------------------------------------
    def start_epoch(self, members: Optional[List[int]] = None) -> Epoch:
        n = (self.epoch.epoch + 1) if self.epoch is not None else 0
        members = list(self.all_members if members is None
                       else members)
        self.epoch = self.epoch_file.write(
            Epoch(epoch=n, members=members, port=free_port()))
        # stale beats from the previous epoch must not mask a worker that
        # never comes up in this one
        for wid in members:
            try:
                os.remove(_hb_path(self.run_dir, wid))
            except OSError:
                pass
        self._event("epoch_start", members=members,
                    port=self.epoch.port, policy=self.policy)
        if self.degrade is not None:
            # verdict state must not outlive the membership it was
            # computed over (EWMAs, page markers, escalation rungs)
            self.degrade.reset()
        # completion is PER EPOCH: a reconfiguration respawns every member
        # (done ones included) so the gang resumes in lockstep from one
        # shared durable step — a "done" worker sitting out would leave the
        # others' collectives one participant short
        self.done = {}
        self.procs = {}
        for wid in members:
            self.procs[wid] = self.spawn(wid, self.epoch)
        counter_add("elastic.epochs_total", 1.0)
        return self.epoch

    def _kill_epoch(self) -> None:
        """Tear down every still-running member: SIGTERM (graceful save),
        grace wait, SIGKILL stragglers."""
        live = {w: p for w, p in self.procs.items() if p.poll() is None}
        for wid, p in live.items():
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + self.term_grace_s
        for wid, p in live.items():
            try:
                p.wait(timeout=max(deadline - time.time(), 0.1))
                self._event("survivor_drained", worker=wid,
                            returncode=p.returncode)
            except subprocess.TimeoutExpired:
                self._event("survivor_killed", worker=wid)
                p.kill()
                p.wait()

    def _reconfigure(self, *, lost: List[int], reason: str,
                     members: Optional[List[int]] = None) -> None:
        """Tear the epoch down and start the next one. ``members`` pins the
        new membership explicitly (the graftward drain rungs choose it —
        a straggler loses its slot regardless of policy, a health-paged
        worker keeps it for a fresh quarantine-respawn); None falls back
        to the death policy (respawn keeps every slot, shrink drops the
        lost)."""
        self.reconfigures += 1
        counter_add("elastic.reconfigures_total", 1.0)
        self._event("reconfigure", lost=lost, reason=reason,
                    n=self.reconfigures)
        if self.reconfigures > self.max_reconfigures:
            # tear the gang down BEFORE giving up: survivors are typically
            # wedged in dead collectives and would otherwise outlive the
            # agent as orphans
            self._kill_epoch()
            raise RuntimeError(
                f"elastic agent: {self.reconfigures} reconfigurations "
                f"(max {self.max_reconfigures}) — crash loop, giving up")
        self._kill_epoch()
        if members is None:
            if self.policy == "shrink":
                members = [m for m in self.epoch.members if m not in lost]
            else:
                members = list(self.epoch.members)
        if not members:
            raise RuntimeError("elastic agent: no survivors to shrink to")
        self.start_epoch(members)

    def _degrade_drain(self, action) -> None:
        """One ladder drain (graftward): SIGTERM the whole gang so every
        member — the sick one included — takes its graceful-preemption
        save at the next checkpoint boundary (``_kill_epoch``'s TERM →
        grace → KILL escalation is exactly the proactive-drain contract),
        then reshape: a STRAGGLER is excluded from the next epoch (a slow
        host is hardware-suspect — the PR 10 shrink path, bitwise-asserted
        by chaos_smoke's ``straggler_reshape``); a HEALTH-PAGED worker
        keeps its slot and is quarantine-respawned as a fresh process
        (sick software state, healthy host), with ``max_reconfigures``
        bounding the crash loop if the respawn pages again."""
        wid, reason = action.worker_id, action.reason
        counter_add("degrade.actions_total", 1.0, labels={"reason": reason})
        self._event("degrade_drain", worker=wid, reason=reason,
                    detail=action.detail)
        if reason == "straggler":
            members = [m for m in self.epoch.members if m != wid]
        else:
            members = list(self.epoch.members)
        self._reconfigure(lost=[wid], reason=f"degrade_{reason}",
                          members=members)

    # -- the supervision loop ----------------------------------------------
    def run(self, deadline_s: float = 600.0) -> List[dict]:
        if self.epoch is None:
            self.start_epoch()
        t0 = time.time()
        while True:
            if time.time() - t0 > deadline_s:
                self._kill_epoch()
                raise TimeoutError(
                    f"elastic agent: run exceeded {deadline_s}s "
                    f"(events: {[e['kind'] for e in self.events]})")
            time.sleep(self.poll_s)
            # 1. child exits
            exited = {w: p.returncode for w, p in self.procs.items()
                      if p.poll() is not None and w not in self.done}
            lost = []
            for wid, rc in exited.items():
                if rc == 0:
                    self.done[wid] = 0
                    self._event("worker_done", worker=wid)
                else:
                    lost.append(wid)
                    self._event("worker_lost", worker=wid, returncode=rc,
                                reconfigure_request=(rc == EXIT_RECONFIGURE))
            if lost:
                # a worker that ASKED for reconfiguration (exit 77) is not
                # dead — it rejoins the next epoch even under shrink; a
                # crashed/killed one is only respawned under "respawn".
                # Fold in concurrently-HUNG members (running but heartbeat
                # stale — the usual reason a peer exited 77) so a shrink
                # drops them too instead of respawning a zombie slot.
                crashed = [w for w in lost
                           if exited[w] != EXIT_RECONFIGURE]
                if self.hb_timeout_s > 0:
                    running = [w for w, p in self.procs.items()
                               if p.poll() is None]
                    crashed += [w for w in
                                hung_workers(self.run_dir, running,
                                             self.hb_timeout_s)
                                if w not in crashed]
                self._reconfigure(lost=crashed, reason="worker_exit")
                continue
            # 2. hangs: a RUNNING child that is provably wedged — beating
            # without step progress (hung main thread) or present-but-
            # silent (frozen process). A child that hasn't beaten at all
            # is still starting; the run deadline backstops it.
            if self.hb_timeout_s > 0:
                running = [w for w, p in self.procs.items()
                           if p.poll() is None]
                hung = hung_workers(self.run_dir, running, self.hb_timeout_s)
                if hung:
                    for wid in hung:
                        self._event("worker_hung", worker=wid)
                        self.procs[wid].kill()
                        self.procs[wid].wait()
                    self._reconfigure(lost=hung, reason="heartbeat_stale")
                    continue
            # 3. degradation ladder (graftward): stragglers and health
            # pages among RUNNING members — sick-but-alive is this rung's
            # whole domain; dead/hung workers were handled above
            if self.degrade is not None:
                running = [w for w, p in self.procs.items()
                           if p.poll() is None and w not in self.done]
                actions = self.degrade.observe(
                    read_heartbeats(self.run_dir), running)
                drained = False
                for act in actions:
                    if act.kind == "page":
                        counter_add("degrade.pages_total", 1.0,
                                    labels={"reason": act.reason})
                        self._event("worker_paged", worker=act.worker_id,
                                    reason=act.reason, detail=act.detail)
                    elif not drained:
                        # one drain per poll: the reshape replaces the
                        # whole epoch, so a second same-poll verdict is
                        # stale by construction
                        drained = True
                        self._degrade_drain(act)
                if drained:
                    continue
            # 4. done?
            if all(w in self.done for w in self.epoch.members):
                self._event("pod_done", members=self.epoch.members)
                return self.events
