import os as _os

import jax as _jax

# Shard-invariant rng: jax<0.5 defaults to the non-partitionable threefry,
# under which a random draw INSIDE a jitted program can produce different
# bits depending on the output sharding GSPMD picks — CFG text dropout then
# nulls different rows on a dp/fsdp/tp mesh than on one device, breaking the
# "sharding changes the schedule, never the math" equivalence this package
# guarantees (and that dryrun_multichip asserts to rtol 2e-4). The
# partitionable generator computes each element from its index, so values
# are identical under any sharding. A JAX_THREEFRY_PARTITIONABLE env
# setting wins; to opt out programmatically, flip the flag AFTER importing
# this package (a pre-import jax.config.update is indistinguishable from
# the jax default and gets overridden here).
if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ:
    _jax.config.update("jax_threefry_partitionable", True)

from .mesh import (build_mesh, single_device_mesh, shard_batch,
                   shard_stacked_batch, batch_spec, replicated,
                   local_batch_size, use_mesh)
from .backend import (DistributedBackend, JaxBackend, DummyBackend, BACKENDS,
                      wrap_arg_parser, set_backend_from_args, using_backend)
from .partition import (DEFAULT_RULES, commit_to_mesh, make_param_shardings,
                        shard_params, spec_for, constrain)
from .ring_attention import ring_attention, shard_seq
