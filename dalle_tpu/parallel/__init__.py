from .mesh import (build_mesh, single_device_mesh, shard_batch,
                   shard_stacked_batch, batch_spec, replicated,
                   local_batch_size, use_mesh)
from .backend import (DistributedBackend, JaxBackend, DummyBackend, BACKENDS,
                      wrap_arg_parser, set_backend_from_args, using_backend)
from .partition import (DEFAULT_RULES, make_param_shardings, shard_params,
                        spec_for, constrain)
from .ring_attention import ring_attention, shard_seq
