"""prng-key-reuse: the two PRNG hazards that corrupt sampling silently.

1. A hard-coded ``jax.random.PRNGKey(<literal>)`` in library code — the
   classic "fallback key" that makes every caller share one stream. Library
   code must require a key or route through the documented helper
   ``dalle_tpu.utils.misc.deterministic_key`` (which carries its own
   suppression and a docstring explaining when a fixed stream is correct).

2. The same key name consumed by two ``jax.random.*`` draws with no
   reassignment in between — both draws see identical bits, so e.g. two
   "independent" gumbel perturbations are perfectly correlated.
   ``split``/``fold_in``/``PRNGKey`` are derivations, not draws: they are
   exempt as consumers (``key, sub = split(key)`` rebinds the name, which
   the scan already honors).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import dotted_name

# derivations (not draws): handing these the same bits is the sanctioned
# key-plumbing pattern, not a correlated-sampling hazard
_CONSUMERS_EXEMPT = {"split", "fold_in", "PRNGKey", "key", "key_data",
                     "wrap_key_data", "clone"}


def jax_random_aliases(tree: ast.Module) -> set:
    """Names this module binds to the jax.random module. Bare ``random.``
    is stdlib unless imported from jax — ``from jax import random`` /
    ``import jax.random as jr`` make the alias a key-consuming prefix."""
    aliases = {"jax.random"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    aliases.add(a.asname)
    return aliases


def _is_jax_random_call(node: ast.Call, aliases: set) -> bool:
    name = dotted_name(node.func)
    return "." in name and name.rsplit(".", 1)[0] in aliases


def _random_fn_name(node: ast.Call) -> str:
    return dotted_name(node.func).rsplit(".", 1)[-1]


def _walk_local(root: ast.AST):
    """Scan ``root``'s own scope only (shared traversal from jit_scan):
    nested function/lambda bodies are scanned when the outer loop reaches
    them as roots — descending here would double-count and mix key scopes."""
    from .jit_scan import walk_scope
    return walk_scope(ast.iter_child_nodes(root))


class _FunctionKeyScan(ast.NodeVisitor):
    """Within one scope: order key-consuming uses and assignments by line,
    flag a second consumption with no intervening rebind. The scan is
    line-ordered, not control-flow-sensitive; the one disjointness it does
    understand is if/else — uses in opposite branches of the same If never
    execute together and are not a reuse pair."""

    def __init__(self, findings: List[Finding], rel_path: str, aliases: set):
        self.findings = findings
        self.rel_path = rel_path
        self.aliases = aliases

    def scan(self, func: ast.AST):
        uses = []      # (line, name)
        assigns = []   # (line, name)
        branches = []  # ((body_lo, body_hi), (else_lo, else_hi)) per If
        for node in _walk_local(func):
            if isinstance(node, ast.Call) and _is_jax_random_call(
                    node, self.aliases):
                fn = _random_fn_name(node)
                if fn in _CONSUMERS_EXEMPT:
                    continue
                if node.args and isinstance(node.args[0], ast.Name):
                    uses.append((node.lineno, node.args[0].id))
            elif isinstance(node, ast.If) and node.orelse:
                branches.append((self._span(node.body),
                                 self._span(node.orelse)))
            for tgt in self._assign_targets(node):
                assigns.append(tgt)
        uses.sort()
        reported = set()   # (name, line) — one report per reuse line
        for i, (ln, name) in enumerate(uses):
            for ln2, name2 in uses[i + 1:]:
                if name2 != name:
                    continue
                if self._disjoint_branches(ln, ln2, branches):
                    continue  # try the next same-name use instead
                rebound = any(a_name == name and ln < a_ln <= ln2
                              for a_ln, a_name in assigns)
                if not rebound and (name, ln2) not in reported:
                    reported.add((name, ln2))
                    self.findings.append(Finding(
                        "prng-key-reuse", self.rel_path, ln2,
                        f"key '{name}' already consumed by a jax.random call "
                        f"on line {ln}; split it first "
                        f"(identical bits → correlated draws)"))
                break  # one report per first reuse pair

    @staticmethod
    def _span(stmts):
        return (stmts[0].lineno, getattr(stmts[-1], "end_lineno",
                                         stmts[-1].lineno))

    @staticmethod
    def _disjoint_branches(ln, ln2, branches) -> bool:
        for (blo, bhi), (elo, ehi) in branches:
            if (blo <= ln <= bhi and elo <= ln2 <= ehi) or \
                    (elo <= ln <= ehi and blo <= ln2 <= bhi):
                return True
        return False

    @staticmethod
    def _assign_targets(node: ast.AST):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                yield from _names_in_target(t, node.lineno)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            yield from _names_in_target(node.target, node.lineno)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            yield from _names_in_target(node.target, node.lineno)
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            yield from _names_in_target(node.optional_vars,
                                        node.optional_vars.lineno)


def _names_in_target(t: ast.AST, line: int):
    if isinstance(t, ast.Name):
        yield (line, t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _names_in_target(e, line)


@register_rule
class PrngKeyReuse(Rule):
    name = "prng-key-reuse"
    description = ("hard-coded PRNGKey literal in library code, or the same "
                   "key consumed by two jax.random draws without a split")
    include = ("dalle_tpu/",)
    exclude = ("dalle_tpu/analysis/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        # hazard 1: literal PRNGKey anywhere in the file — matched by its
        # distinctive trailing name so aliased/from-imports are caught too
        for node in ast.walk(ctx.tree):
            name = dotted_name(node.func) if isinstance(node, ast.Call) else ""
            if (name.rsplit(".", 1)[-1] == "PRNGKey"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, int)):
                findings.append(Finding(
                    self.name, ctx.rel_path, node.lineno,
                    f"hard-coded jax.random.PRNGKey({node.args[0].value}) — "
                    "require a key from the caller or use "
                    "utils.misc.deterministic_key (documented fixed-stream "
                    "helper)"))
        # hazard 2: per-scope reuse scan — module top level plus each
        # function/lambda, nested scopes scanned independently
        aliases = jax_random_aliases(ctx.tree)
        scanner = _FunctionKeyScan(findings, ctx.rel_path, aliases)
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda))]
        for scope in scopes:
            scanner.scan(scope)
        return findings
