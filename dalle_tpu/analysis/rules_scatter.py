"""Scatter-shape rules for ops code — codifying the int8 KV-scale lesson.

The r5 speculative-decode ablation (ops/attention.KVCache.append_rows)
measured a vmapped dynamic-update-slice lowering to an unsorted/aliasing
scatter at 2.2x END-TO-END cost, and an int8 scale scatter along the
minormost (lane) axis as the second-largest term; the fix — explicit sorted
unique indices plus transposing the scale to sequence-major so the scatter
never touches the lane axis — removed the whole gap. Both halves of that
lesson are mechanical to drift back into, and only show up as wall clock on
hardware. These rules make the drift a lint finding instead:

  * ``scatter-minormost`` — an ``.at[...]`` scatter whose LAST index element
    is not a slice writes along the minormost axis (lane-axis scatter on
    TPU); restructure so the minormost axis stays fully sliced (transpose to
    sequence-major like the KV scale buffer).
  * ``scatter-missing-hints`` — an ``.at[...]`` scatter with array-valued
    indices and neither ``unique_indices`` nor ``indices_are_sorted``: XLA
    must assume aliasing, unsorted indices and serializes the scatter.
    Declare the hints where they hold; where they genuinely do not, say so
    with a suppression comment next to the call.

Scoped to ``dalle_tpu/ops/`` — the numerical core where these scatters sit
on decode hot paths. Syntactic by design (same trade as rules_jit): the
patterns are flagged as written, zero whole-program analysis.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .core import FileContext, Finding, Rule, register_rule

# jnp ``.at[]`` update methods that lower to scatter
_SCATTER_METHODS = ("set", "add", "subtract", "multiply", "divide", "power",
                    "min", "max", "apply")


def _index_elements(sub: ast.Subscript) -> List[ast.expr]:
    idx = sub.slice
    return list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]


def _is_full_slice_like(node: ast.expr) -> bool:
    """Index elements that do NOT scatter along their axis: slices, and
    Ellipsis/None (which only expand/insert axes)."""
    if isinstance(node, ast.Slice):
        return True
    return isinstance(node, ast.Constant) and node.value in (Ellipsis, None)


def _is_static_scalar(node: ast.expr) -> bool:
    """Statically-provable scalar int index (lowers to a single-position
    dynamic-update-slice, which cannot alias): int literals including
    negative ones (``-1`` parses as UnaryOp) and arithmetic over them.
    Names/attributes stay non-scalar — they may hold index arrays."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub,
                                                              ast.UAdd)):
        return _is_static_scalar(node.operand)
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv, ast.Mod)):
        return _is_static_scalar(node.left) and _is_static_scalar(node.right)
    return False


def _scatter_calls(tree: ast.Module) -> Iterable[Tuple[ast.Call,
                                                       ast.Subscript]]:
    """(call, subscript) pairs for every ``X.at[IDX].<method>(...)``."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SCATTER_METHODS):
            continue
        sub = node.func.value
        if (isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "at"):
            yield node, sub


@register_rule
class ScatterMinormost(Rule):
    name = "scatter-minormost"
    description = (".at[...] scatter whose index demonstrably reaches the "
                   "trailing axis (≥3 elements or a leading Ellipsis, "
                   "non-slice last) — writes along the minormost (lane) "
                   "axis, the layout TPU scatters serialize on; keep the "
                   "minormost axis fully sliced (transpose to "
                   "sequence-major)")
    include = ("dalle_tpu/ops/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for call, sub in _scatter_calls(ctx.tree):
            elts = _index_elements(sub)
            if _is_full_slice_like(elts[-1]):
                continue
            # rank is unknowable statically, so only flag indexes that
            # DEMONSTRABLY reach the trailing axis: a leading Ellipsis
            # aligns the last element with it outright, and ≥3 explicit
            # elements cover every array rank this codebase scatters
            # (rank-3 caches/scales). Two-element indexes on rank-3 arrays
            # leave the lane axis implicitly sliced (the blessed
            # append_rows shape) and are never flagged.
            reaches_minor = (len(elts) >= 3
                             or any(isinstance(e, ast.Constant)
                                    and e.value is Ellipsis
                                    for e in elts[:-1]))
            if not reaches_minor:
                continue
            yield Finding(
                self.name, ctx.rel_path, call.lineno,
                "scatter indexes the minormost axis (last index element is "
                "not a slice) — lane-axis scatters serialize on TPU; "
                "restructure so the trailing axis stays fully sliced, e.g. "
                "transpose to sequence-major as KVCache.append_rows does "
                "for the int8 scale buffer")


@register_rule
class ScatterMissingHints(Rule):
    name = "scatter-missing-hints"
    description = (".at[...] scatter with array-valued indices and neither "
                   "unique_indices nor indices_are_sorted — XLA assumes "
                   "aliasing/unsorted and serializes (the 2.2x append_rows "
                   "regression); declare the hints where they hold")
    include = ("dalle_tpu/ops/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for call, sub in _scatter_calls(ctx.tree):
            elts = _index_elements(sub)
            # "advanced" index: anything that is not a slice/Ellipsis/None
            # and not a statically-scalar int (single-position updates
            # don't alias). Names and gathered arrays count.
            advanced = [e for e in elts if not _is_full_slice_like(e)
                        and not _is_static_scalar(e)]
            if not advanced:
                continue
            kwargs = {kw.arg for kw in call.keywords}
            if kwargs & {"unique_indices", "indices_are_sorted"}:
                continue
            yield Finding(
                self.name, ctx.rel_path, call.lineno,
                "array-indexed scatter without unique_indices/"
                "indices_are_sorted — the compiler must assume aliasing and "
                "unsorted indices (measured 2.2x end-to-end on the b64 "
                "speculative loop); declare the hints that hold, or "
                "suppress here if they genuinely do not")
