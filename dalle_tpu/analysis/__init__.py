"""graftlint — a JAX/TPU-aware static-analysis pass for this codebase.

Off-the-shelf linters know nothing about the failure modes that actually
bite a JAX/Pallas repo: PRNG key reuse that silently correlates samples,
``static_argnums`` fed fresh unhashable objects (recompile storms),
host syncs inside jitted functions, and VMEM ceilings drifting away from
the kernel estimators they were calibrated against (see the b695782
scoped-vmem work). Each of those is a rule here.

Public surface:
  * :func:`run_lint` — lint a set of files (or the whole repo) and return
    :class:`Finding` objects.
  * :data:`RULES` — the rule registry (name → rule instance).
  * ``# graftlint: disable=<rule>[,<rule>]`` — per-line suppression, on the
    offending line or the line directly above it.

The runtime companion (jit-recompilation budgets for tests) lives in
:mod:`dalle_tpu.analysis.recompile_guard`.

Four sibling audit layers share this package but gate through their own
CLIs rather than the lint registry (each with a committed golden under
``contracts/`` and the same ``--check``/``--update`` exit-code split):
graftir (:mod:`ir_flow`, jaxpr/HLO contracts), graftnum
(:mod:`precision_flow`, quantization dataflow), graftsync
(:mod:`sync_flow`, locksets + lock-order graph) and graftwire
(:mod:`wire_flow`, the cross-process fleet protocol + lifecycle state
machines). See docs/ANALYSIS.md for the full layer table.
"""

from .core import (  # noqa: F401
    Finding,
    FileContext,
    Rule,
    ProjectRule,
    RULES,
    register_rule,
    iter_repo_files,
    run_lint,
)

# importing the rule modules populates the registry
from . import rules_rng  # noqa: F401,E402
from . import rules_except  # noqa: F401,E402
from . import rules_jit  # noqa: F401,E402
from . import rules_vmem  # noqa: F401,E402
from . import rules_scatter  # noqa: F401,E402
from . import rules_paged  # noqa: F401,E402
from . import rules_weaktype  # noqa: F401,E402
from . import rules_precision  # noqa: F401,E402
from . import rules_obs  # noqa: F401,E402
from . import rules_distributed  # noqa: F401,E402
from . import rules_coverage  # noqa: F401,E402
