"""graftir entry registry: the programs whose contracts CI pins.

Each entry builds a small-but-real instance of one production program — the
four trainer steps on a multi-axis mesh (so the collective inventory sees
dp/fsdp/tp), the autoregressive decode program, the serve engine's
refill/decode programs, and the Pallas attention kernels (traced in
interpret mode so the KERNEL body's primitives land in the histogram).

Shapes here are contract-calibration shapes, not benchmarks: tiny enough
that ``--check`` stays a CI-priced stage, structured enough that a refactor
changing the program (an extra collective, a dtype upcast, a lost donation)
changes the contract. Entry builders construct the REAL library objects
(trainers, engine) rather than re-deriving the jitted fns — the contract
must cover what production code actually runs.

Waivers: ``# graftir: allow=<rule> -- <reason>`` in an entry's ``source``
file applies to that entry (see analysis/ir_audit.py).
"""

from __future__ import annotations

import dataclasses
import functools
import tempfile
from typing import Callable, Dict, Optional

from .core import REPO_ROOT  # noqa: F401  (re-exported for the CLI)


@dataclasses.dataclass
class BuiltEntry:
    fn: Callable                 # jitted (or jittable) callable
    args: tuple
    donated: int = 0             # donated LEAF count (0 = no donation audit)
    mesh: object = None          # jax Mesh for collective axis naming
    compile: bool = False        # compile for collectives/donation?
    vmem: Optional[dict] = None  # kernel vmem estimator snapshot (PR 1)
    # precision-flow provenance roles, [(role, label)] per flattened arg
    # leaf; None = infer from pytree paths (precision_flow.infer_roles)
    roles: Optional[list] = None


@dataclasses.dataclass(frozen=True)
class EntrySpec:
    name: str
    source: str                  # repo-relative file whose waivers apply
    build: Callable[[], BuiltEntry]


ENTRIES: Dict[str, EntrySpec] = {}


def register_entry(name: str, source: str):
    def deco(fn):
        assert name not in ENTRIES, name
        ENTRIES[name] = EntrySpec(name, source, fn)
        return fn
    return deco


# --------------------------------------------------------------------------
# shared tiny configs (mirror the test-suite calibration configs)
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _mesh(dp=2, fsdp=2, tp=1):
    from ..config import MeshConfig
    from ..parallel.mesh import build_mesh
    return build_mesh(MeshConfig(dp=dp, fsdp=fsdp, tp=tp))


@functools.lru_cache(maxsize=None)
def _ckpt_dir() -> str:
    # one shared scratch dir per process (preflight_checkpoint=False and the
    # entries never save, so nothing is written; per-entry mkdtemp would
    # leak a /tmp dir on every audit run)
    return tempfile.mkdtemp(prefix="graftir_")


def _train_cfg(mesh_cfg, **kw):
    from ..config import ObsConfig, OptimConfig, PrecisionConfig, TrainConfig
    # health=True: the trainer goldens pin the graftpulse-tapped step
    # programs (obs/health.py) — the contract is that the taps add in-graph
    # reductions ONLY: no host-transfer primitives, no new collectives, and
    # donation stays fully aliased (obs_smoke re-asserts the transfer
    # invariant from the goldens; drift here fails the graftir CI stage).
    # The health=False default programs are NOT separately pinned —
    # duplicating all four compiled trainer entries would nearly double the
    # audit's wall time; instead obs_smoke live-builds the vae step BOTH
    # ways each CI run and diffs the two contracts (transfers, donation,
    # collective delta), guarding the off-variant structure through the
    # representative trainer.
    return TrainConfig(batch_size=8, preflight_checkpoint=False,
                       checkpoint_dir=_ckpt_dir(), mesh=mesh_cfg,
                       precision=PrecisionConfig(compute="float32"),
                       optim=OptimConfig(learning_rate=1e-2),
                       obs=ObsConfig(health=True), **kw)


@functools.lru_cache(maxsize=None)
def _dalle_model():
    import jax
    from ..config import DalleConfig
    from ..models.dalle import init_dalle
    cfg = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                      heads=2, dim_head=16, image_size=16,
                      image_vocab_size=32, image_fmap_size=4)
    return init_dalle(cfg, jax.random.PRNGKey(0))


def _tree_leaves(tree) -> int:
    import jax
    return len(jax.tree.leaves(tree))


# --------------------------------------------------------------------------
# trainer steps (compiled: donation + collectives)
# --------------------------------------------------------------------------

@register_entry("train_step_dalle", "dalle_tpu/train/trainer_dalle.py")
def _build_train_step_dalle() -> BuiltEntry:
    import jax
    import numpy as np
    from ..config import DalleConfig, MeshConfig
    from ..train.trainer_dalle import DalleTrainer
    mesh_cfg = MeshConfig(dp=2, fsdp=2, tp=2)
    cfg = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                      heads=2, dim_head=16, image_size=16,
                      image_vocab_size=32, image_fmap_size=4)
    tr = DalleTrainer(cfg, _train_cfg(mesh_cfg), mesh=_mesh(2, 2, 2))
    rng = np.random.RandomState(0)
    text, ids = tr._put_batch((rng.randint(1, 32, (8, 8)),
                               rng.randint(0, 32, (8, 16))))
    key = jax.random.fold_in(tr.base_key, 0)
    return BuiltEntry(fn=tr.step_fn, args=(tr.state, text, ids, key),
                      donated=_tree_leaves(tr.state), mesh=tr.mesh,
                      compile=True)


@register_entry("train_step_vae", "dalle_tpu/train/trainer_vae.py")
def _build_train_step_vae() -> BuiltEntry:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..config import DVAEConfig, MeshConfig
    from ..train.trainer_vae import VAETrainer
    mesh_cfg = MeshConfig(dp=4, fsdp=2)
    cfg = DVAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                     num_layers=2, num_resnet_blocks=0, hidden_dim=8)
    tr = VAETrainer(cfg, _train_cfg(mesh_cfg), mesh=_mesh(4, 2))
    images = tr._put(np.random.RandomState(0).rand(8, 16, 16, 3), np.float32)
    key = jax.random.fold_in(tr.base_key, 0)
    return BuiltEntry(fn=tr.step_fn,
                      args=(tr.state, images, key, jnp.float32(1.0)),
                      donated=_tree_leaves(tr.state), mesh=tr.mesh,
                      compile=True)


@register_entry("train_step_clip", "dalle_tpu/train/trainer_clip.py")
def _build_train_step_clip() -> BuiltEntry:
    import numpy as np
    from ..config import ClipConfig, MeshConfig
    from ..train.trainer_clip import CLIPTrainer
    mesh_cfg = MeshConfig(dp=2, fsdp=2, tp=2)
    cfg = ClipConfig(dim_text=32, dim_image=32, dim_latent=32,
                     num_text_tokens=64, text_enc_depth=1, text_seq_len=8,
                     text_heads=2, visual_enc_depth=1, visual_heads=2,
                     visual_image_size=16, visual_patch_size=8)
    tr = CLIPTrainer(cfg, _train_cfg(mesh_cfg), mesh=_mesh(2, 2, 2))
    rng = np.random.RandomState(0)
    text, images = tr._put_batch((rng.randint(1, 64, (8, 8)),
                                  rng.rand(8, 16, 16, 3)))
    return BuiltEntry(fn=tr.step_fn, args=(tr.state, text, images),
                      donated=_tree_leaves(tr.state), mesh=tr.mesh,
                      compile=True)


@register_entry("train_step_vqgan", "dalle_tpu/train/trainer_vqgan.py")
def _build_train_step_vqgan() -> BuiltEntry:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..config import MeshConfig, VQGANConfig
    from ..models.gan import GANLossConfig
    from ..train.trainer_vqgan import VQGANTrainer
    mesh_cfg = MeshConfig(dp=4, fsdp=2)
    cfg = VQGANConfig(embed_dim=16, n_embed=64, z_channels=16, resolution=32,
                      ch=16, ch_mult=(1, 2), num_res_blocks=1,
                      attn_resolutions=(16,))
    tr = VQGANTrainer(cfg, _train_cfg(mesh_cfg),
                      loss_cfg=GANLossConfig(disc_start=0,
                                             perceptual_weight=0.0),
                      mesh=_mesh(4, 2))
    images = tr._put(np.random.RandomState(0).rand(8, 32, 32, 3) * 2 - 1,
                     np.float32)
    key = jax.random.fold_in(tr.base_key, 0)
    return BuiltEntry(fn=tr.step_fn,
                      args=(tr.state, images, key, jnp.float32(1.0)),
                      donated=_tree_leaves(tr.state), mesh=tr.mesh,
                      compile=True)


# --------------------------------------------------------------------------
# decode programs (trace-only: dtype/primitive/memory discipline)
# --------------------------------------------------------------------------

@register_entry("generate_images_tokens", "dalle_tpu/models/dalle.py")
def _build_generate() -> BuiltEntry:
    import jax
    import jax.numpy as jnp
    from ..models.dalle import DALLE
    model, params = _dalle_model()

    def gen(p, text, key):
        return model.apply(p, text, key, method=DALLE.generate_images_tokens)

    text = jnp.zeros((2, 8), jnp.int32)
    return BuiltEntry(fn=gen, args=(params, text, jax.random.PRNGKey(0)))


@register_entry("generate_images_tokens_int8w", "dalle_tpu/models/dalle.py")
def _build_generate_int8w() -> BuiltEntry:
    # the quantized decode fast path (wrapper precision="int8w"): int8
    # matmul kernels + bf16 everything else + int8 KV. Its contract pins
    # the quantization boundary map — every dequant site and scale axis —
    # alongside the f32 entry above
    import jax
    import jax.numpy as jnp
    from ..models.dalle import DALLE
    from ..ops.quantize_weights import quantize_params_int8
    model, params = _dalle_model()
    qv = quantize_params_int8(params)

    def gen(p, text, key):
        return model.apply(p, text, key, cache_dtype=jnp.int8,
                           method=DALLE.generate_images_tokens)

    text = jnp.zeros((2, 8), jnp.int32)
    return BuiltEntry(fn=gen, args=(qv, text, jax.random.PRNGKey(0)))


@functools.lru_cache(maxsize=None)
def _engine():
    # the PRODUCTION serve configuration: int8 weights (per-channel scales
    # in the mirrored ``quant`` collection) + int8 KV — the serve-engine
    # default since DalleWithVae.serve_engine flipped to precision="int8w".
    # The contract (and the precision boundary map in it) pins the
    # quantized program; the precision_audit CI stage certifies its
    # quantization safety rules hold.
    import jax.numpy as jnp
    from ..ops.quantize_weights import quantize_params_int8
    from ..serve.engine import DecodeEngine
    model, params = _dalle_model()
    return DecodeEngine(model, quantize_params_int8(params), slots=4,
                        cache_dtype=jnp.int8)


@register_entry("serve_decode", "dalle_tpu/serve/engine.py")
def _build_serve_decode() -> BuiltEntry:
    eng = _engine()
    state = eng._init_state()
    return BuiltEntry(fn=eng._step_fn, args=(eng.params, state),
                      donated=_tree_leaves(state), compile=True)


@register_entry("serve_decode_aot", "dalle_tpu/gateway/aot.py")
def _build_serve_decode_aot() -> BuiltEntry:
    # the program gateway/aot.py EXPORTS for replica cold-start: the
    # production gateway configuration (int8w like _engine, but
    # steps_per_sync=4 — the K-step scan the serve_gateway CLI ships).
    # Pinning it through the aot module's own aval builder means a change
    # to what the export lowers (not just to the engine) drifts this
    # contract before stale AOT bundles can ship.
    import jax.numpy as jnp
    from ..gateway.aot import _program_args
    from ..ops.quantize_weights import quantize_params_int8
    from ..serve.engine import DecodeEngine
    model, params = _dalle_model()
    eng = DecodeEngine(model, quantize_params_int8(params), slots=4,
                       cache_dtype=jnp.int8, steps_per_sync=4)
    args = _program_args(eng)["step"]
    return BuiltEntry(fn=eng._step_fn, args=args,
                      donated=_tree_leaves(args[1]), compile=True)


@register_entry("serve_decode_health", "dalle_tpu/serve/engine.py")
def _build_serve_decode_health() -> BuiltEntry:
    # the graftpulse-instrumented decode step (decode_health=True): the
    # per-row entropy/top-k taps computed from the logits already on
    # device. The golden pins that the taps are free of host transfers and
    # change nothing about the collectives — and, vs ``serve_decode``, that
    # the sampling path itself is untouched (the bit-exactness contract's
    # static half).
    import jax.numpy as jnp
    from ..ops.quantize_weights import quantize_params_int8
    from ..serve.engine import DecodeEngine
    model, params = _dalle_model()
    eng = DecodeEngine(model, quantize_params_int8(params), slots=4,
                       cache_dtype=jnp.int8, decode_health=True)
    state = eng._init_state()
    return BuiltEntry(fn=eng._step_fn, args=(eng.params, state),
                      donated=_tree_leaves(state), compile=True)


@register_entry("serve_refill", "dalle_tpu/serve/engine.py")
def _build_serve_refill() -> BuiltEntry:
    import jax.numpy as jnp
    eng = _engine()
    state = eng._init_state()
    texts = jnp.zeros((4, eng.text_seq_len), jnp.int32)
    seeds = jnp.zeros((4,), jnp.int32)
    n_rows = jnp.full((4,), eng.n_steps, jnp.int32)
    mask = jnp.ones((4,), bool)
    return BuiltEntry(fn=eng._refill_fn,
                      args=(eng.params, state, texts, seeds, n_rows, mask),
                      donated=_tree_leaves(state), compile=True)


@register_entry("serve_refill_shared", "dalle_tpu/serve/engine.py")
def _build_serve_refill_shared() -> BuiltEntry:
    # graftloom shared-prefix admission: ONE b=1 text prefill broadcast
    # into every masked slot of the live cache, per-candidate RNG lanes
    # seeded independently (DALLE.serve_refill_shared → engine
    # _refill_shared). The golden pins the amortization claim's static
    # half: one prefill's worth of matmul/attend primitives — not N — plus
    # the masked broadcast, with the quantization boundary identical to the
    # per-row trickle prefill the bits must match.
    import jax.numpy as jnp
    eng = _engine()
    state = eng._init_state()
    text1 = jnp.zeros((1, eng.text_seq_len), jnp.int32)
    seeds = jnp.zeros((4,), jnp.int32)
    n_rows = jnp.full((4,), eng.n_steps, jnp.int32)
    mask = jnp.ones((4,), bool)
    return BuiltEntry(fn=eng._refill_shared_fn,
                      args=(eng.params, state, text1, seeds, n_rows, mask),
                      donated=_tree_leaves(state), compile=True)


@functools.lru_cache(maxsize=None)
def _paged_engine():
    # the graftpage serve configuration: the production int8 engine of
    # _engine() with the dense slab swapped for the paged pool (block
    # size 4 on the tiny calibration shapes → multiple blocks per row, so
    # the gather really walks the page table). Host-side radix/COW control
    # flow is data-only by design; these entries pin the static half of
    # that claim — the paged programs' primitive sets, dtype boundaries
    # and donation maps, which admission must never change.
    import jax.numpy as jnp
    from ..ops.quantize_weights import quantize_params_int8
    from ..serve.engine import DecodeEngine
    model, params = _dalle_model()
    return DecodeEngine(model, quantize_params_int8(params), slots=4,
                        cache_dtype=jnp.int8, kv_block_tokens=4)


@register_entry("serve_decode_paged", "dalle_tpu/serve/engine.py")
def _build_serve_decode_paged() -> BuiltEntry:
    # the paged decode step: page-table gather → dense attend math → paged
    # scatter. vs ``serve_decode`` the contract adds the gather/scatter
    # primitives and the CFG merge, and must NOT add host transfers — the
    # page table is a donated device leaf, not a host round-trip.
    eng = _paged_engine()
    state = eng._init_state()
    return BuiltEntry(fn=eng._step_fn, args=(eng.params, state),
                      donated=_tree_leaves(state), compile=True)


@register_entry("serve_refill_paged", "dalle_tpu/serve/engine.py")
def _build_serve_refill_paged() -> BuiltEntry:
    # the paged bulk prefill (radix-miss admission): same window math as
    # ``serve_refill``, writes routed through the page table
    import jax.numpy as jnp
    eng = _paged_engine()
    state = eng._init_state()
    texts = jnp.zeros((4, eng.text_seq_len), jnp.int32)
    seeds = jnp.zeros((4,), jnp.int32)
    n_rows = jnp.full((4,), eng.n_steps, jnp.int32)
    mask = jnp.ones((4,), bool)
    return BuiltEntry(fn=eng._refill_fn,
                      args=(eng.params, state, texts, seeds, n_rows, mask),
                      donated=_tree_leaves(state), compile=True)


@register_entry("serve_refill_chunk_paged", "dalle_tpu/serve/engine.py")
def _build_serve_refill_chunk_paged() -> BuiltEntry:
    # the fixed-width suffix window of a radix PARTIAL hit (and the w=1
    # full-hit logits recompute shares the same program at width 1): one
    # block_tokens-wide masked prefill window through the page table. The
    # width set is static (chunk_widths), which is what keeps partial-hit
    # admission AOT-exportable and recompile-free.
    import jax.numpy as jnp
    eng = _paged_engine()
    state = eng._init_state()
    w = eng.kv_block_tokens
    ids = jnp.zeros((4, w), jnp.int32)
    seeds = jnp.zeros((4,), jnp.int32)
    n_rows = jnp.full((4,), eng.n_steps, jnp.int32)
    mask = jnp.ones((4,), bool)
    return BuiltEntry(fn=eng._refill_chunk_fn,
                      args=(eng.params, state, ids, jnp.int32(0), seeds,
                            n_rows, mask, jnp.bool_(True)),
                      donated=_tree_leaves(state), compile=True)


@register_entry("serve_cow_copy", "dalle_tpu/serve/engine.py")
def _build_serve_cow_copy() -> BuiltEntry:
    # the copy-on-write fork: per-layer pool block copies (int8 scale
    # planes ride along), fixed lane count, OOB-dst drop for inactive
    # lanes. The contract pins that a fork is pure device block moves —
    # no host transfer, no reshape of the pool, donation fully aliased.
    import jax.numpy as jnp
    eng = _paged_engine()
    state = eng._init_state()
    src = jnp.zeros((4,), jnp.int32)
    dst = jnp.full((4,), eng.kv_pool_blocks, jnp.int32)
    return BuiltEntry(fn=eng._cow_copy_fn, args=(state, src, dst),
                      donated=_tree_leaves(state), compile=True)


@register_entry("clip_rerank", "dalle_tpu/serve/pipeline.py")
def _build_clip_rerank() -> BuiltEntry:
    # the /v1/images rerank stage: the jitted batched CLIP scorer the
    # pipeline dispatches per finished candidate group (CLIP.score_images —
    # text tower once, N image towers, one matvec). Traced through the
    # pipeline's own builder so a change to what the product loop actually
    # runs (e.g. the fused resize) drifts this contract.
    import jax
    import jax.numpy as jnp
    from ..config import ClipConfig
    from ..models.clip import init_clip
    from ..serve.pipeline import ImagePipeline
    cfg = ClipConfig(dim_text=32, dim_image=32, dim_latent=32,
                     num_text_tokens=64, text_enc_depth=1, text_seq_len=8,
                     text_heads=2, visual_enc_depth=1, visual_heads=2,
                     visual_image_size=16, visual_patch_size=8)
    clip, params = init_clip(cfg, jax.random.PRNGKey(0))

    class _StubVae:     # satisfies the clip-needs-pixels invariant; only
        def decode(self, ids):  # the scorer program is traced here
            raise NotImplementedError

    pipe = ImagePipeline(vae=_StubVae(), clip=clip, clip_params=params)
    text = jnp.zeros((1, 8), jnp.int32)
    images = jnp.zeros((4, 16, 16, 3), jnp.float32)
    return BuiltEntry(fn=pipe._scorer, args=(params, text, images))


# --------------------------------------------------------------------------
# attention kernels (trace-only, interpret=True so the pallas kernel body's
# primitives land in the histogram; vmem snapshot from the PR 1 estimator)
# --------------------------------------------------------------------------

def _fused_vmem(n: int, hd: int) -> dict:
    from ..ops import fused_attention as fa
    est = fa._bwd_bytes(n, hd)
    cp = fa._compiler_params(est)
    return {"bwd_bytes_est": int(est),
            "vmem_limit_bytes": int(getattr(cp, "vmem_limit_bytes", 0) or 0)
            if cp is not None else 0,
            "calibration": f"n={n}, hd={hd}"}


@register_entry("fused_qkv_attention", "dalle_tpu/ops/fused_attention.py")
def _build_fused_attention() -> BuiltEntry:
    import jax
    import jax.numpy as jnp
    from ..ops.fused_attention import fused_qkv_attention
    n, heads, d = 128, 2, 32
    hd = heads * d

    def fwd_bwd(qkv):
        # value-and-grad captures BOTH pallas kernels (fwd + custom-vjp bwd)
        return jax.grad(lambda x: fused_qkv_attention(
            x, heads=heads, interpret=True).sum())(qkv)

    qkv = jnp.zeros((2, n, 3 * hd), jnp.float32)
    return BuiltEntry(fn=fwd_bwd, args=(qkv,), vmem=_fused_vmem(n, hd))


@register_entry("decode_attend_window", "dalle_tpu/ops/decode_attention.py")
def _build_decode_window() -> BuiltEntry:
    import jax.numpy as jnp
    from ..ops.attention import KVCache
    from ..ops.decode_attention import decode_attend_window_kernel
    b, h, S, d, w = 4, 2, 64, 32, 4
    cache = KVCache.init(b, h, S, d, jnp.float32)

    def attend(q, kv, starts):
        return decode_attend_window_kernel(q, cache.replace(kv=kv), starts,
                                           interpret=True)

    q = jnp.zeros((b, h, w, d), jnp.float32)
    starts = jnp.zeros((b,), jnp.int32)
    return BuiltEntry(fn=attend, args=(q, cache.kv, starts))
