"""jit-shaped rules: recompile storms, host syncs, tracer branches, donation.

All four rules share :mod:`dalle_tpu.analysis.jit_scan`'s view of where
``jax.jit`` is applied in a module. They are syntactic: a jitted function is
scanned as written; helpers it calls are each scanned at their own jit site
(if any). That trades whole-program soundness for zero-false-positive
signal on the patterns that actually recur in this codebase.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import (JitInfo, body_nodes, dotted_name, find_jit_functions,
                       func_param_names)

# --------------------------------------------------------------------------
# jit-static-hazard
# --------------------------------------------------------------------------

_FRESH_CTORS = {"dict", "list", "set", "frozenset"}


def _unhashable_or_fresh(node: ast.expr) -> Optional[str]:
    """Why this call-site argument will miss (or break) the jit cache."""
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp, ast.GeneratorExp)):
        return "an unhashable literal (list/dict/set) — TypeError at call time"
    if isinstance(node, ast.Lambda):
        return ("a fresh lambda — every call site builds a new object, so "
                "every call recompiles")
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in _FRESH_CTORS:
            return f"a fresh {name}() — unhashable, TypeError at call time"
        if name in ("functools.partial", "partial"):
            return ("a fresh functools.partial — new object per call, so "
                    "every call recompiles")
    return None


@register_rule
class JitStaticHazard(Rule):
    name = "jit-static-hazard"
    description = ("static_argnums/static_argnames argument receives an "
                   "unhashable or freshly-constructed value at a call site "
                   "(recompile storm or TypeError)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        jits = [j for j in find_jit_functions(ctx.tree)
                if (j.static_argnums or j.static_argnames) and j.name]
        if not jits:
            return findings
        by_name = {j.name: j for j in jits}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            info = by_name.get(node.func.id)
            if info is None:
                continue
            params = func_param_names(info.func_node)
            for pos in info.static_argnums:
                if pos < len(node.args):
                    why = _unhashable_or_fresh(node.args[pos])
                    if why:
                        findings.append(Finding(
                            self.name, ctx.rel_path, node.lineno,
                            f"static arg {pos} of '{info.name}' is {why}"))
            static_names = set(info.static_argnames)
            static_names.update(params[p] for p in info.static_argnums
                                if p < len(params))
            for kw in node.keywords:
                if kw.arg in static_names:
                    why = _unhashable_or_fresh(kw.value)
                    if why:
                        findings.append(Finding(
                            self.name, ctx.rel_path, node.lineno,
                            f"static arg '{kw.arg}' of '{info.name}' is {why}"))
        return findings


# --------------------------------------------------------------------------
# host-sync-in-jit
# --------------------------------------------------------------------------

_NUMPY_SYNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "onp.asarray", "onp.array"}


@register_rule
class HostSyncInJit(Rule):
    name = "host-sync-in-jit"
    description = (".item()/float()/int()/np.asarray on traced values inside "
                   "a jitted function — blocks the device and breaks tracing")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for info in find_jit_functions(ctx.tree):
            params = set(func_param_names(info.func_node))
            # static args are concrete Python values under trace — float()/
            # int() on them is legal, so they are not "traced params"
            all_params = func_param_names(info.func_node)
            params -= set(info.static_argnames)
            params -= {all_params[i] for i in info.static_argnums
                       if i < len(all_params)}
            for node in body_nodes(info.func_node):
                if not isinstance(node, ast.Call):
                    continue
                # x.item() on anything
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    findings.append(Finding(
                        self.name, ctx.rel_path, node.lineno,
                        ".item() inside a jitted function forces a host "
                        "sync (ConcretizationTypeError under trace)"))
                    continue
                name = dotted_name(node.func)
                if name in _NUMPY_SYNCS:
                    findings.append(Finding(
                        self.name, ctx.rel_path, node.lineno,
                        f"{name}() inside a jitted function materializes a "
                        "host array — use jnp, or hoist out of jit"))
                    continue
                # float(x)/int(x)/bool(x) where x mentions a traced param
                if (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and node.args):
                    mentioned = {n.id for n in ast.walk(node.args[0])
                                 if isinstance(n, ast.Name)}
                    if mentioned & params:
                        findings.append(Finding(
                            self.name, ctx.rel_path, node.lineno,
                            f"{node.func.id}() on a traced argument inside a "
                            "jitted function — ConcretizationTypeError (use "
                            "jnp casts, or mark the arg static)"))
        return findings


# --------------------------------------------------------------------------
# python-branch-on-tracer
# --------------------------------------------------------------------------

_TRACED_ROOTS = re.compile(r"^(jnp|jax\.numpy|jax\.lax|lax)\.")


def _test_mentions_traced_call(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _TRACED_ROOTS.match(
                dotted_name(node.func)):
            return True
    return False


@register_rule
class PythonBranchOnTracer(Rule):
    name = "python-branch-on-tracer"
    description = ("Python if/while on a value computed by jnp/jax.lax inside "
                   "a jitted function — TracerBoolConversionError (use "
                   "jnp.where / lax.cond / lax.while_loop)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for info in find_jit_functions(ctx.tree):
            for node in body_nodes(info.func_node):
                if isinstance(node, (ast.If, ast.While)) and \
                        _test_mentions_traced_call(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(Finding(
                        self.name, ctx.rel_path, node.lineno,
                        f"Python '{kind}' on a jnp/lax expression inside a "
                        "jitted function — the tracer has no concrete bool; "
                        "use jnp.where / lax.cond / lax.while_loop"))
        return findings


# --------------------------------------------------------------------------
# donate-missing
# --------------------------------------------------------------------------

_STEP_NAME = re.compile(r"(^|_)step$")


@register_rule
class DonateMissing(Rule):
    name = "donate-missing"
    description = ("train-step jit without donate_argnums — the old state "
                   "buffer stays live across the update, doubling peak HBM")
    # trainers + training entry points. bench scripts are excluded on
    # purpose: they re-feed the same state across timed iterations, which
    # donation would invalidate.
    include = ("dalle_tpu/train/", "scripts/train_")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for info in find_jit_functions(ctx.tree):
            step_name = next((n for n in (info.name, info.wrapped_name)
                              if n and _STEP_NAME.search(n)), None)
            if step_name is None or info.has_donate:
                continue
            findings.append(Finding(
                self.name, ctx.rel_path, info.line,
                f"jitted step function '{step_name}' does not donate its "
                "state — pass donate_argnums so XLA reuses the old buffers "
                "in place"))
        return findings
