"""untested-public-op: every public symbol in dalle_tpu/ops/ must appear in
tests/.

The ops layer is the repo's numerical core — a public op nobody references
from tests/ is an op whose behavior can silently change. "Referenced" is a
word-boundary text match across tests/*.py: cheap, and exactly the bar a
reviewer applies ("where is this exercised?"). Symbols that are genuinely
internal should be renamed with a leading underscore instead of suppressed.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, Iterable, List, Tuple

from .core import REPO_ROOT, FileContext, Finding, ProjectRule, register_rule


def public_symbols(tree: ast.Module) -> List[Tuple[str, int]]:
    """(name, line) of top-level public defs/classes."""
    out = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and not node.name.startswith("_"):
            out.append((node.name, node.lineno))
    return out


def untested_ops(ops_ctxs: Dict[str, ast.Module],
                 tests_source: str) -> Iterable[Tuple[str, str, int]]:
    """(rel_path, symbol, line) for public ops symbols absent from tests.
    Split out (inputs injected) so tests can run it on fixtures."""
    for rel_path, tree in sorted(ops_ctxs.items()):
        for name, line in public_symbols(tree):
            if not re.search(rf"\b{re.escape(name)}\b", tests_source):
                yield rel_path, name, line


@register_rule
class UntestedPublicOp(ProjectRule):
    name = "untested-public-op"
    description = ("public symbol in dalle_tpu/ops/ with no reference "
                   "anywhere in tests/")
    triggers = ("dalle_tpu/ops/", "tests/", "dalle_tpu/analysis/")

    def check_project(self, ctxs, repo_root=REPO_ROOT) -> Iterable[Finding]:
        ops = {c.rel_path: c.tree for c in ctxs
               if c.rel_path.startswith("dalle_tpu/ops/")
               and not c.rel_path.endswith("__init__.py")}
        tests_source = ""
        for p in sorted(glob.glob(os.path.join(repo_root, "tests", "*.py"))):
            with open(p, encoding="utf-8") as fh:
                tests_source += fh.read()
        for rel_path, name, line in untested_ops(ops, tests_source):
            yield Finding(
                self.name, rel_path, line,
                f"public op '{name}' has no reference in tests/ — add a "
                "test or rename it _private")
