"""graftsync — whole-module static concurrency model for the threaded
control plane.

graftlint's ``unbounded-blocking-call`` rule found a real hang in
``serve/pipeline.py`` on its first run, but it reads one call site at a
time. The hazards that remain are *relational*: a field written under
``self._lock`` in one method and read bare from the worker thread, two
locks acquired in opposite orders from two call paths, a blocking wait
issued while a lock is held, a non-daemon thread nobody joins. This module
builds the repo-wide model those checks need:

  * **lock inventory** — every ``threading.Lock``/``RLock``/``Condition``
    created in the sync roots, identified by owner (``path::Class.attr`` or
    ``path::name`` for module-level locks) and by its creation site
    ``(path, line)`` — the same key the runtime tracker
    (:mod:`dalle_tpu.obs.lockorder`) records, so the static graph and an
    observed run are directly comparable. ``Condition(self._lock)`` aliases
    the wrapped lock: acquiring the condition IS acquiring the lock.
  * **guarded-field map** — per class, the attributes written while one of
    its locks is held (``with self._lock:`` scopes, including helper-method
    summaries one call deep: a helper's bare writes count as guarded by the
    caller's held lock).
  * **lock-acquisition graph** — an edge ``A -> B`` wherever code acquires
    B while holding A, with the acquiring ``file::function`` site. Edges
    follow one-call-deep summaries: a locked body calling ``self.m()`` or a
    typed attribute's method inherits that callee's direct acquisitions.
  * **thread entries** — ``run`` methods, callables passed to
    ``threading.Thread(target=...)``/``Timer``/executor ``submit``, with
    nested ``def``s attributed to their enclosing class (a closure's
    ``self`` is the enclosing method's).
  * **access log** — every ``self.field`` read/write per function with the
    lock set held at that point, plus blocking calls under a held lock,
    ``Condition.wait`` predicate-loop context, and thread-lifecycle facts.

The model is pure AST — no imports of the analyzed code — so it runs on
any tree state. Rules that consume it live in
:mod:`dalle_tpu.analysis.rules_sync`; the CLI is ``scripts/sync_audit.py``
(golden lock graph in ``contracts/sync.json``). Waivers are source
comments on the finding's line or the line above::

    # graftsync: allow=blocking-under-lock -- <reason>

A waiver without a reason, or naming an unknown rule, is itself a finding.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

from .core import REPO_ROOT, iter_repo_files
from .jit_scan import dotted_name

# the threaded control plane: every package that owns threading state
SYNC_ROOTS = ("dalle_tpu/serve", "dalle_tpu/gateway", "dalle_tpu/fleet",
              "dalle_tpu/degrade", "dalle_tpu/obs", "dalle_tpu/parallel",
              "dalle_tpu/chaos")

_WAIVER_RE = re.compile(r"#\s*graftsync:\s*allow=([\w\-]+)(?:\s*--\s*(.*))?")

_LOCK_CTORS = {"threading.Lock": "Lock", "threading.RLock": "RLock",
               "threading.Condition": "Condition"}

# container methods that mutate shared state (a write for lockset purposes)
_MUTATORS = {"append", "appendleft", "pop", "popleft", "add", "remove",
             "discard", "clear", "update", "extend", "insert", "setdefault",
             "__setitem__"}


@dataclasses.dataclass(frozen=True)
class LockDef:
    """One lock object, keyed by owner and by creation site."""
    lock_id: str            # "path::Class.attr" or "path::name"
    path: str
    line: int               # line of the threading.Lock() call
    kind: str               # Lock | RLock | Condition


@dataclasses.dataclass(frozen=True)
class Edge:
    """B acquired while A held, at ``site`` (file::function)."""
    src: str
    dst: str
    site: str
    line: int


@dataclasses.dataclass(frozen=True)
class Access:
    field: str
    line: int
    kind: str               # "r" | "w"
    held: FrozenSet[str]    # lock ids held at the access


@dataclasses.dataclass(frozen=True)
class BlockingCall:
    lock_id: str
    desc: str               # human-readable call description
    line: int


@dataclasses.dataclass(frozen=True)
class CondWait:
    lock_id: str
    line: int
    in_loop: bool           # lexically inside a while (predicate re-check)


@dataclasses.dataclass(frozen=True)
class ThreadDef:
    path: str
    line: int
    site: str               # creating file::function
    daemon: bool
    joined: bool            # a .join( on the thread's binding is in scope
    target: Optional[str]   # resolved entry func key, when resolvable
    name: Optional[str]


@dataclasses.dataclass
class FuncInfo:
    """Per-function concurrency summary."""
    key: str                            # "path::qualname"
    path: str
    qualname: str
    cls: Optional[str]                  # enclosing class name, if any
    line: int
    accesses: List[Access] = dataclasses.field(default_factory=list)
    acquires: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    edges: List[Edge] = dataclasses.field(default_factory=list)
    blocking: List[BlockingCall] = dataclasses.field(default_factory=list)
    cond_waits: List[CondWait] = dataclasses.field(default_factory=list)
    # callee key -> (line, held lock ids at the call)
    calls: List[Tuple[str, int, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SyncModel:
    """The whole-project concurrency model."""
    locks: Dict[str, LockDef]
    functions: Dict[str, FuncInfo]
    # "path::Class" -> field -> lock ids it is written under
    guarded: Dict[str, Dict[str, FrozenSet[str]]]
    edges: List[Edge]                   # deduped, one-call-deep resolved
    thread_entries: Dict[str, ThreadDef]  # entry func key -> creating thread
    threads: List[ThreadDef]
    # class name -> "path::Class" (ambiguous names dropped)
    class_keys: Dict[str, str]

    def lock_by_site(self) -> Dict[Tuple[str, int], str]:
        """(path, line) of the Lock() call -> lock_id — the join key with
        the runtime tracker's creation-site identities."""
        return {(d.path, d.line): d.lock_id for d in self.locks.values()}


# --------------------------------------------------------------------------
# per-file scan
# --------------------------------------------------------------------------

class _ClassScan:
    """First pass over one class: lock attrs, condition aliases, attribute
    types (``self.x = SomeClass(...)`` / annotated ctor params)."""

    def __init__(self, path: str, name: str):
        self.path = path
        self.name = name
        self.bases: List[str] = []                # base class names
        self.locks: Dict[str, LockDef] = {}       # attr -> def
        self.aliases: Dict[str, str] = {}         # cond attr -> lock attr
        self.attr_types: Dict[str, str] = {}      # attr -> class name
        self.methods: Dict[str, ast.AST] = {}
        self.inherited: Dict[str, str] = {}       # method -> base func key

    @property
    def key(self) -> str:
        return f"{self.path}::{self.name}"


def _lock_ctor_kind(call: ast.AST) -> Optional[str]:
    if isinstance(call, ast.Call):
        return _LOCK_CTORS.get(dotted_name(call.func))
    return None


def _ann_name(node: Optional[ast.AST]) -> str:
    """Class name from an annotation node; string annotations
    (``x: "Table"``) are Constants, not Names."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return dotted_name(node) if node is not None else ""


def _type_from_ann(node: Optional[ast.AST]) -> Optional[str]:
    """Capitalized class name from an annotation, looking through
    ``Optional[...]``/subscripts and string annotations."""
    if node is None:
        return None
    if isinstance(node, ast.Subscript):
        return _type_from_ann(node.slice)
    name = _ann_name(node).rsplit(".", 1)[-1].strip("\"'")
    return name if name and name[0].isupper() else None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'f' for ``self.f``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _FileScan:
    """Parse one file into class scans + module-level locks/functions."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.tree = ast.parse(source, filename=path)
        self.classes: Dict[str, _ClassScan] = {}
        self.module_locks: Dict[str, LockDef] = {}   # name -> def
        self.module_funcs: Dict[str, ast.AST] = {}
        self.module_var_types: Dict[str, str] = {}   # global -> class name
        self.imported_names: set = set()
        self._scan()

    def _scan(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    self.imported_names.add(a.asname
                                            or a.name.split(".")[0])
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                # "_tracer: Optional[Tracer] = None" — the module
                # singleton pattern; functions resolve "tr._lock" via it
                t = _type_from_ann(node.annotation)
                if t:
                    self.module_var_types[node.target.id] = t
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                name = node.targets[0].id
                if kind:
                    self.module_locks[name] = LockDef(
                        f"{self.path}::{name}", self.path,
                        node.value.lineno, kind)
                elif isinstance(node.value, ast.Call):
                    ctor = dotted_name(node.value.func).rsplit(".", 1)[-1]
                    if ctor and ctor[0].isupper():
                        self.module_var_types[name] = ctor

    def _scan_class(self, cls: ast.ClassDef) -> None:
        scan = _ClassScan(self.path, cls.name)
        scan.bases = [dotted_name(b).rsplit(".", 1)[-1]
                      for b in cls.bases if dotted_name(b)]
        self.classes[cls.name] = scan
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan.methods[item.name] = item
                ann = {a.arg: _ann_name(a.annotation)
                       for a in item.args.args
                       if a.annotation is not None}
                for sub in ast.walk(item):
                    self._scan_stmt(scan, sub, ann)
            elif isinstance(item, ast.Assign) and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name):
                kind = _lock_ctor_kind(item.value)
                if kind:     # class-body lock (shared across instances)
                    attr = item.targets[0].id
                    scan.locks[attr] = LockDef(
                        f"{self.path}::{cls.name}.{attr}", self.path,
                        item.value.lineno, kind)

    def _scan_stmt(self, scan: _ClassScan, node: ast.AST,
                   annotations: Dict[str, str]) -> None:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            return
        attr = _self_attr(node.targets[0])
        if attr is None:
            return
        kind = _lock_ctor_kind(node.value)
        if kind == "Condition" and isinstance(node.value, ast.Call) \
                and node.value.args:
            wrapped = _self_attr(node.value.args[0])
            if wrapped is not None:
                # Condition(self._lock): acquiring the condition IS
                # acquiring the wrapped lock — alias, not a new node
                scan.aliases[attr] = wrapped
                return
        if kind:
            scan.locks[attr] = LockDef(
                f"{self.path}::{scan.name}.{attr}", self.path,
                node.value.lineno, kind)
            return
        # attribute types: self.x = SomeClass(...) and self.x = param
        # where the ctor annotates param's class — the one-call-deep
        # resolver uses these to find the callee's locks across files
        if isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func).rsplit(".", 1)[-1]
            if callee and callee[0].isupper():
                scan.attr_types[attr] = callee
        elif isinstance(node.value, ast.Name):
            ann = annotations.get(node.value.id, "")
            ann = ann.rsplit(".", 1)[-1]
            if ann and ann[0].isupper():
                scan.attr_types[attr] = ann


# --------------------------------------------------------------------------
# per-function walk (held-lock tracking)
# --------------------------------------------------------------------------

def _call_blocking_desc(call: ast.Call) -> Optional[str]:
    """Description when ``call`` is a blocking primitive, else None."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        name = dotted_name(func)
        if name == "time.sleep" or name.endswith("create_connection"):
            return f"{name}(...)"
        return None
    attr = func.attr
    recv = dotted_name(func.value) or "<expr>"
    kwargs = {k.arg for k in call.keywords}
    has_timeout = "timeout" in kwargs or (
        attr in ("get", "wait", "join") and call.args)
    if attr == "get" and not call.args and not kwargs:
        return f"{recv}.get() with no timeout"
    if attr == "put" and not has_timeout \
            and ("q" == recv.rsplit(".", 1)[-1]
                 or recv.rsplit(".", 1)[-1].endswith(("queue", "_q"))):
        return f"{recv}.put(...) with no timeout"
    if attr in ("wait", "join") and not has_timeout:
        return f"{recv}.{attr}() with no timeout"
    if attr in ("recv", "recv_into", "accept", "connect"):
        return f"{recv}.{attr}(...) socket I/O"
    if attr == "create_connection":
        return f"{recv}.create_connection(...) socket dial"
    if attr == "block_until_ready":
        return f"{recv}.block_until_ready()"
    if attr == "sleep" and recv == "time":
        return "time.sleep(...)"
    return None


class _FuncWalker:
    """Walk one function body tracking the held-lock stack."""

    def __init__(self, file_scan: _FileScan, scan: Optional[_ClassScan],
                 qualname: str, node: ast.AST, collect,
                 global_classes: Optional[Dict[str, _ClassScan]] = None):
        self.fs = file_scan
        self.cls = scan
        self.path = file_scan.path
        self.qualname = qualname
        self.global_classes = global_classes or {}
        self.info = FuncInfo(
            key=f"{file_scan.path}::{qualname}", path=file_scan.path,
            qualname=qualname, cls=scan.name if scan else None,
            line=node.lineno)
        self.collect = collect      # (qualname, node) for nested defs
        self.held: List[str] = []
        self.loop_depth = 0
        # local var -> class name: annotated params + "x = Class(...)" +
        # "x = <typed module global>" (the "tr = _tracer" singleton grab)
        self.local_types: Dict[str, str] = {}
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for a in node.args.args:
                t = _type_from_ann(a.annotation)
                if t:
                    self.local_types[a.arg] = t
        for stmt in node.body:
            self._walk(stmt)

    # -- lock-expression resolution ---------------------------------------

    def _local_class(self, var: str) -> Optional[_ClassScan]:
        """The _ClassScan a local/global variable is known to hold."""
        tname = self.local_types.get(var) \
            or self.fs.module_var_types.get(var)
        if tname is None:
            return None
        return self.fs.classes.get(tname) or self.global_classes.get(tname)

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            attr = self.cls.aliases.get(attr, attr)
            d = self.cls.locks.get(attr)
            return d.lock_id if d else None
        if isinstance(expr, ast.Name):
            d = self.fs.module_locks.get(expr.id)
            return d.lock_id if d else None
        # "tr._lock" where tr's class is known (annotated param, local
        # "x = Class(...)", or a typed module singleton like obs.trace's
        # "_tracer: Optional[Tracer]")
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            cscan = self._local_class(expr.value.id)
            if cscan is not None:
                attr = cscan.aliases.get(expr.attr, expr.attr)
                d = cscan.locks.get(attr)
                return d.lock_id if d else None
        return None

    def _callee_key(self, func: ast.AST) -> Optional[str]:
        """One-call-deep resolution: self.m(), typed-attr .m(), module f(),
        imported f() (resolved against the global registry later)."""
        if isinstance(func, ast.Name):
            if func.id in self.fs.module_funcs:
                return f"{self.path}::{func.id}"
            if func.id in self.fs.imported_names:
                return f"@@{func.id}"      # cross-module, resolved later
            return None
        if not isinstance(func, ast.Attribute):
            return None
        owner = _self_attr(func.value)
        if isinstance(func.value, ast.Name) and func.value.id == "self" \
                and self.cls is not None:
            if func.attr in self.cls.methods:
                return f"{self.path}::{self.cls.name}.{func.attr}"
            return self.cls.inherited.get(func.attr)
        if owner is not None and self.cls is not None:
            tname = self.cls.attr_types.get(owner)
            if tname:
                return f"@{tname}.{func.attr}"   # resolved globally later
        if isinstance(func.value, ast.Name):
            cscan = self._local_class(func.value.id)
            if cscan is not None:
                if func.attr in cscan.methods:
                    return f"{cscan.path}::{cscan.name}.{func.attr}"
                return cscan.inherited.get(func.attr)
        return None

    # -- the walk ---------------------------------------------------------

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: its own summary, attributed to the enclosing
            # class (a closure's ``self`` is the enclosing method's)
            self.collect(f"{self.qualname}.{node.name}", node, self.cls)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.With):
            self._walk_with(node)
            return
        if isinstance(node, (ast.While, ast.For)):
            self.loop_depth += 1
            for child in ast.iter_child_nodes(node):
                self._walk(child)
            self.loop_depth -= 1
            return
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v, tname = node.value, None
            if isinstance(v, ast.Call):
                ctor = dotted_name(v.func).rsplit(".", 1)[-1]
                if ctor and ctor[0].isupper():
                    tname = ctor
            elif isinstance(v, ast.Name):
                tname = self.fs.module_var_types.get(v.id)
            if tname:
                self.local_types[node.targets[0].id] = tname
        if isinstance(node, ast.Call):
            self._visit_call(node)
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            field = _self_attr(node.value)
            if field is not None:      # self.f[k] = v writes f
                self.info.accesses.append(Access(
                    field, node.lineno, "w", frozenset(self.held)))
        elif isinstance(node, ast.Attribute):
            self._visit_attribute(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _walk_with(self, node: ast.With) -> None:
        pushed = []
        for item in node.items:
            lock = self._resolve_lock(item.context_expr)
            if lock is None:
                self._walk(item.context_expr)
                continue
            line = item.context_expr.lineno
            self.info.acquires.append((lock, line))
            for held in self.held:
                if held != lock:
                    self.info.edges.append(Edge(
                        held, lock, f"{self.path}::{self.qualname}", line))
            self.held.append(lock)
            pushed.append(lock)
        for stmt in node.body:
            self._walk(stmt)
        for _ in pushed:
            self.held.pop()

    def _visit_call(self, node: ast.Call) -> None:
        func = node.func
        held = frozenset(self.held)
        callee = self._callee_key(func)
        if callee is not None:
            self.info.calls.append((callee, node.lineno, held))
        # Condition.wait predicate-loop check (wait_for builds its own)
        if isinstance(func, ast.Attribute) and func.attr == "wait":
            lock = self._resolve_lock(func.value)
            if lock is not None:
                self.info.cond_waits.append(CondWait(
                    lock, node.lineno, self.loop_depth > 0))
        if self.held:
            # Condition.wait/wait_for RELEASES the condition's own lock
            # while parked — only OTHER held locks make it a blocking
            # hazard, and they are the ones attributed
            recv_lock = None
            if isinstance(func, ast.Attribute):
                recv_lock = self._resolve_lock(func.value)
            effective = [h for h in self.held if h != recv_lock]
            desc = _call_blocking_desc(node)
            if desc is not None and callee is None and effective:
                self.info.blocking.append(BlockingCall(
                    effective[-1], desc, node.lineno))
        # container mutation on a self field is a WRITE to that field
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            field = _self_attr(func.value)
            if field is not None:
                self.info.accesses.append(Access(
                    field, node.lineno, "w", held))

    def _visit_attribute(self, node: ast.Attribute) -> None:
        field = _self_attr(node)
        if field is None:
            return
        if self.cls is not None and (
                field in self.cls.locks or field in self.cls.aliases):
            return                       # the lock itself is not data
        kind = "w" if isinstance(node.ctx, (ast.Store, ast.Del)) else "r"
        self.info.accesses.append(Access(
            field, node.lineno, kind, frozenset(self.held)))


# --------------------------------------------------------------------------
# thread-entry + lifecycle extraction
# --------------------------------------------------------------------------

def _scope_has_join(nodes: Iterable[ast.AST]) -> bool:
    """Any ``<x>.join(...)`` call in the given bodies (str.join excluded by
    requiring a non-string-literal receiver heuristically: a call with
    positional args whose receiver is a Constant is a str.join)."""
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and not isinstance(node.func.value, ast.Constant):
                # os.path.join / "sep".join are not thread joins
                recv = dotted_name(node.func.value)
                if recv.startswith(("os.", "posixpath", "ntpath")):
                    continue
                return True
    return False


def _thread_facts(file_scan: _FileScan, scan: Optional[_ClassScan],
                  qualname: str, fn: ast.AST,
                  scope_has_join: bool) -> List[ThreadDef]:
    """Thread creations in one function: daemon-ness, join-ness, target.
    ``scope_has_join`` is class-wide for methods (threads stored on self
    are joined from the shutdown path, a different method), function-local
    for module functions."""
    out = []
    src_dump = ast.dump(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        ctor = dotted_name(node.func)
        is_submit = (isinstance(node.func, ast.Attribute)
                     and node.func.attr == "submit"
                     and ("executor" in dotted_name(node.func.value).lower()
                          or "pool" in dotted_name(node.func.value).lower()))
        if ctor not in ("threading.Thread", "Thread", "threading.Timer",
                        "Timer") and not is_submit:
            continue
        kwargs = {k.arg: k.value for k in node.keywords if k.arg}
        daemon = isinstance(kwargs.get("daemon"), ast.Constant) \
            and bool(kwargs["daemon"].value)
        target = None
        tval = kwargs.get("target")
        if "Timer" in ctor and len(node.args) >= 2:
            tval = node.args[1]
        if is_submit and node.args:
            tval = node.args[0]
        if tval is not None:
            tattr = _self_attr(tval)
            if tattr is not None and scan is not None \
                    and tattr in scan.methods:
                target = f"{file_scan.path}::{scan.name}.{tattr}"
            elif isinstance(tval, ast.Name):
                # local closure defined in this function, or module func
                if tval.id in file_scan.module_funcs:
                    target = f"{file_scan.path}::{tval.id}"
                else:
                    target = f"{file_scan.path}::{qualname}.{tval.id}"
        name = None
        nval = kwargs.get("name")
        if isinstance(nval, ast.Constant):
            name = str(nval.value)
        if not daemon:
            # daemon set post-construction (t.daemon = True) in this fn
            daemon = bool(re.search(r"attr='daemon'", src_dump)
                          and "Constant(value=True" in src_dump)
        if is_submit:
            daemon = True              # the executor owns the lifecycle
        out.append(ThreadDef(file_scan.path, node.lineno,
                             f"{file_scan.path}::{qualname}",
                             daemon, scope_has_join, target, name))
    return out


# --------------------------------------------------------------------------
# model build
# --------------------------------------------------------------------------

def sync_files(repo_root: str = REPO_ROOT) -> List[str]:
    """Repo-relative .py files in the sync roots."""
    return iter_repo_files(SYNC_ROOTS, repo_root)


def build_model(files: Sequence[Tuple[str, str]]) -> SyncModel:
    """Build the concurrency model from (rel_path, source) pairs."""
    file_scans: List[_FileScan] = []
    for path, source in files:
        try:
            file_scans.append(_FileScan(path, source))
        except SyntaxError:
            continue

    # global class registry: name -> key (ambiguous names are dropped —
    # a wrong cross-file resolution is worse than a missing one)
    class_keys: Dict[str, Optional[str]] = {}
    scans_by_key: Dict[str, _ClassScan] = {}
    for fs in file_scans:
        for cname, scan in fs.classes.items():
            key = f"{fs.path}::{cname}"
            scans_by_key[key] = scan
            class_keys[cname] = None if cname in class_keys else key

    # inheritance: a subclass shares its base's locks/aliases/attr types
    # and can call inherited methods on self — propagate base facts down
    # (bases first; the subclass's own definitions win; lock identity is
    # the BASE's lock_id: one object at runtime, one graph node here)
    propagated: set = set()

    def _propagate(scan: _ClassScan) -> None:
        if scan.key in propagated:
            return
        propagated.add(scan.key)
        for bname in scan.bases:
            bkey = class_keys.get(bname)
            if bkey is None:
                continue
            base = scans_by_key[bkey]
            _propagate(base)
            for attr, d in base.locks.items():
                scan.locks.setdefault(attr, d)
            for attr, tgt in base.aliases.items():
                scan.aliases.setdefault(attr, tgt)
            for attr, tname in base.attr_types.items():
                scan.attr_types.setdefault(attr, tname)
            for mname in base.methods:
                if mname not in scan.methods:
                    scan.inherited.setdefault(
                        mname, f"{base.path}::{base.name}.{mname}")
            for mname, fkey in base.inherited.items():
                if mname not in scan.methods:
                    scan.inherited.setdefault(mname, fkey)

    for scan in scans_by_key.values():
        _propagate(scan)

    # unambiguous class/function name registries for cross-file resolution
    global_classes = {n: scans_by_key[k]
                      for n, k in class_keys.items() if k is not None}
    func_keys: Dict[str, Optional[str]] = {}
    for fs in file_scans:
        for fname in fs.module_funcs:
            key = f"{fs.path}::{fname}"
            func_keys[fname] = None if fname in func_keys else key

    locks: Dict[str, LockDef] = {}
    functions: Dict[str, FuncInfo] = {}
    threads: List[ThreadDef] = []

    for fs in file_scans:
        for d in fs.module_locks.values():
            locks[d.lock_id] = d
        for scan in fs.classes.values():
            for d in scan.locks.values():
                locks[d.lock_id] = d

        class_joins = {cname: _scope_has_join(scan.methods.values())
                       for cname, scan in fs.classes.items()}
        pending: List[Tuple[str, ast.AST, Optional[_ClassScan]]] = []
        for cname, scan in fs.classes.items():
            for mname, mnode in scan.methods.items():
                pending.append((f"{cname}.{mname}", mnode, scan))
        for fname, fnode in fs.module_funcs.items():
            pending.append((fname, fnode, None))
        while pending:
            qualname, node, scan = pending.pop(0)

            def _collect(q, n, s):
                pending.append((q, n, s))
            walker = _FuncWalker(fs, scan, qualname, node, _collect,
                                 global_classes)
            functions[walker.info.key] = walker.info
            has_join = (class_joins[scan.name] if scan is not None
                        else _scope_has_join([node]))
            threads.extend(_thread_facts(fs, scan, qualname, node,
                                         has_join))

    # resolve "@Class.method" / "@@func" callee keys against the registries
    def resolve(callee: str) -> Optional[str]:
        if callee.startswith("@@"):
            fkey = func_keys.get(callee[2:])
            return fkey if fkey in functions else None
        if not callee.startswith("@"):
            return callee if callee in functions else None
        cname, mname = callee[1:].rsplit(".", 1)
        key = class_keys.get(cname)
        if key is None:
            return None
        scan = scans_by_key[key]
        fkey = f"{scan.path}::{cname}.{mname}"
        if fkey in functions:
            return fkey
        fkey = scan.inherited.get(mname)       # method defined on a base
        return fkey if fkey in functions else None

    # rewrite call targets to resolved function keys (unresolvable calls
    # drop out — a wrong cross-file resolution is worse than a missing one)
    for info in functions.values():
        info.calls = [(resolve(c), line, held) for c, line, held in
                      info.calls if resolve(c) is not None]

    # transitive may-acquire summaries: the locks a call into f can end up
    # taking, any depth down the resolved call graph. Deadlock edges need
    # the closure — "record_event -> recorder.event -> with self._lock" is
    # two frames deep and very much a real runtime edge (the fleet smoke's
    # tracker observed exactly that before this was transitive).
    may_acquire: Dict[str, set] = {
        k: {lock for lock, _ in f.acquires} for k, f in functions.items()}
    changed = True
    while changed:
        changed = False
        for key, info in functions.items():
            acc = may_acquire[key]
            for callee, _, _ in info.calls:
                extra = may_acquire[callee] - acc
                if extra:
                    acc |= extra
                    changed = True

    # edge propagation: caller holds L at a call whose closure may
    # acquire M -> edge L -> M at the call site
    edges: Dict[Tuple[str, str, str], Edge] = {}
    for info in functions.values():
        for e in info.edges:
            edges.setdefault((e.src, e.dst, e.site), e)
        for callee, line, held in info.calls:
            if not held:
                continue
            for lock in may_acquire[callee]:
                for h in held:
                    if h != lock:
                        e = Edge(h, lock, f"{info.path}::{info.qualname}",
                                 line)
                        edges.setdefault((e.src, e.dst, e.site), e)

    # guarded-field map: direct locked writes + one-call-deep (a helper's
    # bare writes guarded by the caller's held lock)
    guarded: Dict[str, Dict[str, set]] = {}

    def class_key_of(info: FuncInfo) -> Optional[str]:
        return f"{info.path}::{info.cls}" if info.cls else None

    for info in functions.values():
        ckey = class_key_of(info)
        if ckey is None:
            continue
        for acc in info.accesses:
            if acc.kind == "w" and acc.held:
                fields = guarded.setdefault(ckey, {})
                fields.setdefault(acc.field, set()).update(acc.held)
        for callee, _, held in info.calls:
            if not held:
                continue
            tinfo = functions[callee]
            tckey = class_key_of(tinfo)
            if tckey is None:
                continue
            for acc in tinfo.accesses:
                if acc.kind == "w" and not acc.held:
                    fields = guarded.setdefault(tckey, {})
                    fields.setdefault(acc.field, set()).update(held)

    # guarded fields flow down the hierarchy too: a subclass method reading
    # a base-guarded field bare is the same race, so the subclass's map is
    # the union of its own and every (resolvable) ancestor's
    def _ancestor_keys(scan: _ClassScan, out: List[str]) -> None:
        for bname in scan.bases:
            bkey = class_keys.get(bname)
            if bkey is not None and bkey not in out:
                out.append(bkey)
                _ancestor_keys(scans_by_key[bkey], out)

    for key, scan in scans_by_key.items():
        ancestors: List[str] = []
        _ancestor_keys(scan, ancestors)
        for akey in ancestors:
            for field, lks in guarded.get(akey, {}).items():
                guarded.setdefault(key, {}).setdefault(field, set()).update(lks)

    # thread entries: explicit targets + every method literally named run
    thread_entries: Dict[str, ThreadDef] = {}
    for t in threads:
        if t.target is not None and t.target in functions:
            thread_entries.setdefault(t.target, t)
    for key, info in functions.items():
        if info.cls and info.qualname.endswith(".run") \
                and info.qualname.count(".") == 1:
            thread_entries.setdefault(key, ThreadDef(
                info.path, info.line, key, True, True, key, None))

    return SyncModel(
        locks=locks,
        functions=functions,
        guarded={k: {f: frozenset(v) for f, v in fields.items()}
                 for k, fields in guarded.items()},
        edges=sorted(edges.values(),
                     key=lambda e: (e.src, e.dst, e.site, e.line)),
        thread_entries=thread_entries,
        threads=threads,
        class_keys={n: k for n, k in class_keys.items() if k is not None},
    )


def build_repo_model(repo_root: str = REPO_ROOT,
                     paths: Optional[Sequence[str]] = None) -> SyncModel:
    import os
    files = []
    for rel in (paths if paths is not None else sync_files(repo_root)):
        with open(os.path.join(repo_root, rel), encoding="utf-8") as fh:
            files.append((rel, fh.read()))
    return build_model(files)


# --------------------------------------------------------------------------
# lock-graph utilities
# --------------------------------------------------------------------------

def find_cycles(edges: Iterable[Edge]) -> List[List[Edge]]:
    """Elementary cycles in the acquisition graph, each as its edge list
    (both/all acquisition sites named). Deduped by node set."""
    adj: Dict[str, List[Edge]] = {}
    for e in edges:
        adj.setdefault(e.src, []).append(e)
    cycles: List[List[Edge]] = []
    seen_sets = set()

    def dfs(start: str, node: str, path: List[Edge], on_path: set) -> None:
        for e in adj.get(node, []):
            if e.dst == start:
                key = frozenset(x.src for x in path + [e])
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(path + [e])
            elif e.dst not in on_path and e.dst > start:
                # only expand nodes ordered after start: each cycle is
                # discovered exactly once, from its smallest node
                on_path.add(e.dst)
                dfs(start, e.dst, path + [e], on_path)
                on_path.discard(e.dst)

    for start in sorted(adj):
        dfs(start, start, [], {start})
    return cycles


# --------------------------------------------------------------------------
# waivers
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SyncWaiver:
    rule: str
    reason: str
    line: int


def collect_waivers(source: str, rel_path: str, known_rules: Sequence[str]
                    ) -> Tuple[List[SyncWaiver], List[str]]:
    """(waivers, problems) from real comment tokens of one file. A waiver
    applies to findings of its rule on its own line or the line below
    (comment-above placement, graftlint-style)."""
    waivers: List[SyncWaiver] = []
    problems: List[str] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return waivers, problems
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _WAIVER_RE.search(tok.string)
        if not m:
            continue
        rule, reason = m.group(1), (m.group(2) or "").strip()
        if rule not in known_rules:
            problems.append(
                f"{rel_path}:{tok.start[0]}: unknown graftsync rule "
                f"'{rule}' in waiver (known: {', '.join(known_rules)})")
            continue
        if not reason:
            problems.append(
                f"{rel_path}:{tok.start[0]}: graftsync waiver for "
                f"'{rule}' has no reason — write "
                f"'# graftsync: allow={rule} -- <why>'")
            continue
        waivers.append(SyncWaiver(rule, reason, tok.start[0]))
    return waivers, problems
