"""graftnum — precision-flow audit: forward dataflow over closed jaxprs.

graftir (ir_audit.py) pins every ``convert_element_type`` site, but it only
*diffs* precision — nothing reasons about it. Mixed-precision failures are
exactly the silent kind static analysis catches best: a bf16 accumulation
inside a softmax/norm/loss reduction, an int8 matmul accumulating at low
width, a dequant scale riding the wrong axis, a value quantized twice
(double rounding), an upcast that quietly erases the HBM win (cf. FP8
training, Micikevicius et al. 2022; LLM.int8, Dettmers et al. 2022).

This module runs a forward dataflow analysis over a ClosedJaxpr with

  * a **precision lattice** per value — f32 / bf16 / f16 / int8 / int /
    bool, plus JAX's weak-typed flag (counted in the boundary map);
  * **provenance** per value — where it was seeded from: ``param``, ``kv``
    (cache storage), ``scale`` (quantization scales), ``activation``,
    ``const`` — inferred from the entry's argument pytree paths
    (:func:`infer_roles`) and propagated through every primitive;
  * a **quantization state machine** per value: int8 storage (``q``) →
    dequantized-but-unscaled (``dq``, the int8→float convert) →
    dequantized-and-scaled (``dqs``, the multiply by a scale). Movement
    ops (reshape/transpose/broadcast/slice/gather/...) carry the state;
    real arithmetic produces fresh activations.

The quantization-safety rules enforced on the flow (each finding carries
``file::function`` provenance via graftir's source-info walker):

  ``low-precision-reduction``  reductions (softmax denominators, norm
      statistics, loss accumulation — ``reduce_sum``/``cumsum``/...) must
      accumulate at ≥ f32; a bf16/f16 operand is a finding.
  ``int8-dot-accum``  every ``dot_general`` consuming an int8 operand must
      declare a ≥ 32-bit ``preferred_element_type`` accumulator.
  ``unscaled-dequant``  a dequantized int8 value must be multiplied by its
      scale before any matmul consumes it (the ``assert_float_params``
      garbage-output hazard, caught statically).
  ``dequant-scale-axis``  the dequant scale must be constant along every
      axis the consuming matmul contracts over — per-channel scales ride
      the output (minormost-safe) axis, never the contraction axis.
  ``double-rounding``  re-quantizing an already-dequantized value.
  ``quant-upcast``  widening a dequantized value to a wider float — the
      upcast defeats the quantization's HBM/MXU win.
  ``orphaned-scale``  a scale input that never reaches a dequantizing
      multiply (its quantized partner is being consumed scale-less
      somewhere, or the scale is dead weight shipped to the device).

Findings are waivable per entry source file with the existing graftir
mechanism: ``# graftir: allow=precision -- <reason>``. The per-entry
**boundary map** (which matmuls consume int8, accumulator dtypes, dequant
sites and scale axes, value-class counts) is also serialized as the
``precision`` section of the graftir contract goldens under ``contracts/``,
so a quantization-boundary change is reviewable drift like any other
program change. CI runs both: ``scripts/precision_audit.py`` (rules +
boundary-map artifact) and ``scripts/ir_audit.py --check`` (drift).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

PRECISION_RULES = (
    "low-precision-reduction", "int8-dot-accum", "unscaled-dequant",
    "dequant-scale-axis", "double-rounding", "quant-upcast", "orphaned-scale",
)

# reductions that ACCUMULATE (error compounds with width) — max/min/argmax
# compare and are precision-safe at any width
_ACCUM_REDUCES = {"reduce_sum", "reduce_prod", "cumsum", "cumprod",
                  "cumlogsumexp", "reduce_window_sum"}

# ops that move data without computing on it: quantization state and axis
# tracking ride through these (gather/pad/dus lose axis tracking but keep
# the state — see _map_axes)
_MOVEMENT = {"reshape", "transpose", "broadcast_in_dim", "slice",
             "dynamic_slice", "squeeze", "rev", "copy", "stop_gradient",
             "gather", "pad", "expand_dims"}

# join ops: output state is the operands' agreement (a cache buffer updated
# with fresh rows stays quantized storage only if both halves are)
_JOIN = {"concatenate", "select_n", "dynamic_update_slice"}

_HIGHER_SPECIAL = {"scan", "while", "cond", "pallas_call"}


def _jnp():
    import jax.numpy as jnp
    return jnp


def _is_float(dtype) -> bool:
    return _jnp().issubdtype(dtype, _jnp().floating)


def _itemsize(dtype) -> int:
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return int(getattr(dtype, "itemsize", 0))


def _is_int8(dtype) -> bool:
    try:
        return np.dtype(dtype) == np.dtype(np.int8)
    except TypeError:
        return False   # extended dtypes (PRNG key<fry> etc.)


def classify_dtype(dtype) -> str:
    """Lattice class name of a dtype (the boundary-map vocabulary)."""
    try:
        name = np.dtype(dtype).name
    except TypeError:
        return "other"
    table = {"float64": "f32", "float32": "f32", "bfloat16": "bf16",
             "float16": "f16", "int8": "int8", "uint8": "int8",
             "bool": "bool"}
    if name in table:
        return table[name]
    if name.startswith(("int", "uint")):
        return "int"
    return "other"


# --------------------------------------------------------------------------
# value info + role inference
# --------------------------------------------------------------------------

@dataclasses.dataclass
class VInfo:
    """Per-value dataflow fact: provenance roles, the set of axes the value
    is known to VARY along (None = unknown → axis rules stay silent, the
    zero-false-positive contract), quantization state, and — for ``dqs``
    values — the scale's varying axes in the value's current coordinates."""
    prov: frozenset = frozenset()
    varies: Optional[frozenset] = None
    quant: str = ""                      # "" | "q" | "dq" | "dqs"
    scale_varies: Optional[frozenset] = None
    scale_src: frozenset = frozenset()   # input-leaf ids of scales carried
    # (site, line) of a float-widening convert applied to this dequantized
    # value — only a FINDING if a matmul later consumes the widened value
    # (a norm's internal f32 stats upcast is required, not a hazard)
    upcast: Optional[Tuple[str, int]] = None


def _shape_varies(aval) -> Optional[frozenset]:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return None
    return frozenset(i for i, s in enumerate(shape) if s != 1)


def _key_str(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _role_of_path(keys: Sequence[str]) -> str:
    last = keys[-1] if keys else ""
    in_cache = any(k == "cache" or k.startswith("kv_") for k in keys)
    if "quant" in keys or last in ("kernel_scale", "shared_emb_scale"):
        return "scale"
    if last == "scale" and in_cache:
        return "scale"
    if "params" in keys:
        return "param"
    if last == "kv" or in_cache:
        return "kv"
    return "activation"


def infer_roles(args: tuple) -> List[Tuple[str, str]]:
    """[(role, label)] aligned with ``jax.tree_util.tree_leaves(args)`` —
    the flattening order ``jax.make_jaxpr`` gives the jaxpr invars.
    Roles come from pytree path names: the ``quant`` collection and cache
    ``scale`` leaves are scales, ``params`` subtrees are params, cache
    ``kv`` buffers are KV storage, everything else is activation-shaped.
    (Optimizer-state mirrors of params deliberately do NOT match the scale
    patterns — a ``mu`` leaf named ``scale`` is a param moment, not a
    quantization scale.)"""
    import jax
    leaves = jax.tree_util.tree_flatten_with_path(args)[0]
    out = []
    for keypath, _leaf in leaves:
        keys = [_key_str(k) for k in keypath]
        out.append((_role_of_path(keys), "/".join(keys) or "arg"))
    return out


# --------------------------------------------------------------------------
# findings / boundary-map accumulation
# --------------------------------------------------------------------------

class _Ctx:
    def __init__(self):
        self.findings: Dict[Tuple[str, str], dict] = {}
        self.int8_dots: Dict[Tuple[str, str], dict] = {}
        self.dequants: Dict[Tuple[str, str, str], dict] = {}
        self.used_scales: set = set()
        self.seeded_scales: Dict[int, str] = {}

    def finding(self, rule: str, eqn, detail: str):
        from .ir_audit import _site_of
        self.finding_at(rule, _site_of(eqn), detail)

    def finding_at(self, rule: str, site_line: Tuple[str, int], detail: str):
        site, line = site_line
        key = (rule, site)
        f = self.findings.setdefault(key, {
            "rule": rule, "site": site, "line": line, "detail": detail,
            "count": 0})
        f["count"] += 1

    def int8_dot(self, eqn, accum: str):
        from .ir_audit import _site_of
        site, _ = _site_of(eqn)
        ev = self.int8_dots.setdefault((site, accum), {
            "site": site, "accum": accum, "count": 0})
        ev["count"] += 1

    def dequant(self, eqn, dst: str, scale_axes: str):
        from .ir_audit import _site_of
        site, _ = _site_of(eqn)
        ev = self.dequants.setdefault((site, dst, scale_axes), {
            "site": site, "dst": dst, "scale_axes": scale_axes, "count": 0})
        ev["count"] += 1


# --------------------------------------------------------------------------
# axis mapping through movement ops
# --------------------------------------------------------------------------

def _map_axes(eqn, axes: Optional[frozenset]) -> Optional[frozenset]:
    """Transform a set of varying axes of eqn's FIRST operand into output
    coordinates. None in → None out; unmappable ops (gather, pad, dynamic
    windows) also degrade to None — unknown silences the axis rules rather
    than mis-firing them."""
    if axes is None:
        return None
    name = eqn.primitive.name
    in_aval = eqn.invars[0].aval
    out_aval = eqn.outvars[0].aval
    if name in ("copy", "stop_gradient", "convert_element_type", "rev"):
        return axes
    if name == "transpose":
        perm = eqn.params["permutation"]
        return frozenset(j for j, p in enumerate(perm) if p in axes)
    if name == "broadcast_in_dim":
        bd = eqn.params["broadcast_dimensions"]
        return frozenset(bd[i] for i in axes if in_aval.shape[i] != 1)
    if name == "squeeze":
        dims = set(eqn.params["dimensions"])
        remap = {}
        j = 0
        for i in range(len(in_aval.shape)):
            if i in dims:
                continue
            remap[i] = j
            j += 1
        return frozenset(remap[i] for i in axes if i in remap)
    if name in ("slice", "dynamic_slice"):
        return frozenset(i for i in axes if out_aval.shape[i] != 1)
    if name == "reshape":
        old = [(i, s) for i, s in enumerate(in_aval.shape) if s != 1]
        new = [(i, s) for i, s in enumerate(out_aval.shape) if s != 1]
        if [s for _, s in old] != [s for _, s in new]:
            return None
        remap = {oi: ni for (oi, _), (ni, _) in zip(old, new)}
        return frozenset(remap[i] for i in axes if i in remap)
    return None


def _is_scale_like(info: VInfo) -> bool:
    """Evidence that a value IS a quantization scale: seeded 'scale'
    provenance (the ``quant`` collection, cache scale buffers — carried by
    ``scale_src`` too) or an amax-derived chain ('scale' is added to the
    provenance of ``reduce_max(abs(...))`` results, the shape of every
    in-program quantizer — ops/attention._quantize_int8)."""
    return bool(info.scale_src) or "scale" in info.prov


def _join(infos: List[VInfo], varies=None) -> VInfo:
    prov = frozenset().union(*(i.prov for i in infos)) if infos else frozenset()
    src = frozenset().union(*(i.scale_src for i in infos)) if infos \
        else frozenset()
    quants = {i.quant for i in infos}
    quant = quants.pop() if len(quants) == 1 else ""
    return VInfo(prov, varies, quant, None, src)


# --------------------------------------------------------------------------
# the flow
# --------------------------------------------------------------------------

def _info_of(env, v) -> VInfo:
    import jax.core as core
    if isinstance(v, core.Literal) or not hasattr(v, "count"):
        quant = ""
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None and _is_int8(dt):
            quant = "q"
        return VInfo(prov=frozenset({"const"}),
                     varies=_shape_varies(getattr(v, "aval", None)),
                     quant=quant)
    return env.get(v, VInfo(prov=frozenset({"const"})))


def _main_sub(eqn):
    from .ir_audit import _sub_jaxprs
    for sub in _sub_jaxprs(eqn.params):
        if len(sub.invars) == len(eqn.invars):
            return sub
    return None


def _flow(jaxpr, in_infos: List[VInfo], ctx: _Ctx) -> List[VInfo]:
    import jax.core as core
    jnp = _jnp()
    env: Dict = {}
    for v, info in zip(jaxpr.invars, in_infos):
        env[v] = info
    for v in jaxpr.constvars:
        env[v] = VInfo(prov=frozenset({"const"}),
                       varies=_shape_varies(v.aval))

    def setout(eqn, info: VInfo):
        for ov in eqn.outvars:
            if isinstance(ov, core.DropVar):
                continue
            dt = getattr(ov.aval, "dtype", None)
            if dt is not None and _is_int8(dt) and info.quant != "q":
                # int8 IS quantized storage in these programs (ids are
                # int32, masks bool) — values quantized in-program (the KV
                # cache append path) enter the state machine here
                env[ov] = dataclasses.replace(info, quant="q",
                                              scale_varies=None)
            else:
                env[ov] = info

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        infos = [_info_of(env, v) for v in eqn.invars]

        if name in _HIGHER_SPECIAL or _main_sub(eqn) is not None:
            _flow_higher(eqn, infos, env, ctx)
            continue

        if name == "convert_element_type":
            src_dt = eqn.invars[0].aval.dtype
            dst_dt = eqn.outvars[0].aval.dtype
            a = infos[0]
            quant, sv, upcast = a.quant, a.scale_varies, a.upcast
            if a.quant == "q" and _is_float(dst_dt):
                quant, sv = "dq", None
            elif a.quant in ("dq", "dqs"):
                if _is_float(dst_dt) and \
                        _itemsize(dst_dt) > _itemsize(src_dt):
                    from .ir_audit import _site_of
                    upcast = _site_of(eqn)
                elif jnp.issubdtype(dst_dt, jnp.integer):
                    ctx.finding(
                        "double-rounding", eqn,
                        f"re-quantization {np.dtype(src_dt).name}->"
                        f"{np.dtype(dst_dt).name} of an already-dequantized "
                        "int8 value — double rounding compounds the "
                        "quantization error")
                    quant, sv, upcast = "q", None, None
            setout(eqn, VInfo(a.prov, a.varies, quant, sv, a.scale_src,
                              upcast))
            continue

        if name in _MOVEMENT:
            a = infos[0]
            varies = _map_axes(eqn, a.varies)
            sv = _map_axes(eqn, a.scale_varies)
            prov = frozenset().union(*(i.prov for i in infos))
            src = frozenset().union(*(i.scale_src for i in infos))
            setout(eqn, VInfo(prov, varies, a.quant, sv, src, a.upcast))
            continue

        if name in _JOIN:
            if name == "dynamic_update_slice":
                data = infos[:2]            # (operand, update); rest: indices
            elif name == "select_n":
                data = infos[1:]            # first operand is the predicate
            else:
                data = infos
            setout(eqn, _join(data, varies=_shape_varies(
                eqn.outvars[0].aval)))
            continue

        if name == "mul":
            a, b = infos[0], infos[1]
            out_varies = None
            if a.varies is not None and b.varies is not None:
                out_varies = a.varies | b.varies
            # a multiply only COMPLETES a dequant when the partner carries
            # scale EVIDENCE — seeded 'scale' provenance (quant collection,
            # cache scale buffers) or an amax-derived chain (the in-program
            # _quantize_int8 path). An arbitrary float multiply (a dropout
            # or attention mask) must NOT silence unscaled-dequant: the
            # value stays 'dq' and a later true scale-mul can still
            # complete it.
            pending = None
            if a.quant == "dq" and b.quant == "" and _is_scale_like(b):
                pending = (a, b)
            elif b.quant == "dq" and a.quant == "" and _is_scale_like(a):
                pending = (b, a)
            if pending is not None:
                dq, sc = pending
                dst = np.dtype(eqn.outvars[0].aval.dtype).name
                axes = ("?" if sc.varies is None
                        else ",".join(str(i) for i in sorted(sc.varies))
                        or "-")
                ctx.dequant(eqn, dst, axes)
                ctx.used_scales.update(sc.scale_src)
                setout(eqn, VInfo(dq.prov | sc.prov, out_varies, "dqs",
                                  sc.varies, frozenset(), dq.upcast))
                continue
            if "dqs" in (a.quant, b.quant) and "" in (a.quant, b.quant):
                d = a if a.quant == "dqs" else b
                setout(eqn, VInfo(a.prov | b.prov, out_varies, "dqs",
                                  d.scale_varies,
                                  a.scale_src | b.scale_src, d.upcast))
                continue
            if "dq" in (a.quant, b.quant) and "" in (a.quant, b.quant):
                d = a if a.quant == "dq" else b
                setout(eqn, VInfo(a.prov | b.prov, out_varies, "dq",
                                  None, a.scale_src | b.scale_src,
                                  d.upcast))
                continue
            setout(eqn, VInfo(a.prov | b.prov, out_varies, "",
                              None, a.scale_src | b.scale_src))
            continue

        if name == "dot_general":
            (lc, rc), _batch = eqn.params["dimension_numbers"]
            pet = eqn.params.get("preferred_element_type")
            contr = (frozenset(lc), frozenset(rc))
            has_int8 = False
            for idx, (v, info) in enumerate(zip(eqn.invars[:2], infos[:2])):
                dt = v.aval.dtype
                if _is_int8(dt):
                    has_int8 = True
                if info.quant == "dq":
                    ctx.finding(
                        "unscaled-dequant", eqn,
                        "dequantized int8 operand reaches a matmul without "
                        "its per-channel scale — the output is garbage "
                        "(the assert_float_params hazard, statically)")
                if info.quant == "dqs" and info.scale_varies is not None:
                    bad = info.scale_varies & contr[idx]
                    if bad:
                        ctx.finding(
                            "dequant-scale-axis", eqn,
                            f"dequant scale varies along contracted axis "
                            f"{sorted(bad)} of the matmul operand — "
                            "per-channel scales must ride the output "
                            "(minormost-safe) axis, not the contraction")
                if info.quant in ("dq", "dqs") and info.upcast is not None:
                    ctx.finding_at(
                        "quant-upcast", info.upcast,
                        "dequantized int8 value widened to a wider float "
                        "before a matmul consumes it — the upcast defeats "
                        "the quantization's HBM/MXU win")
            if has_int8:
                accum = ("none" if pet is None
                         else np.dtype(pet).name)
                ctx.int8_dot(eqn, accum)
                if pet is None or _itemsize(pet) < 4:
                    ctx.finding(
                        "int8-dot-accum", eqn,
                        f"int8 dot_general accumulates at "
                        f"'{accum}' — declare preferred_element_type="
                        "float32 (or int32) so the MXU accumulator "
                        "keeps full width")
            prov = frozenset().union(*(i.prov for i in infos)) if infos \
                else frozenset()
            setout(eqn, VInfo(prov, _shape_varies(eqn.outvars[0].aval)))
            continue

        if name in _ACCUM_REDUCES:
            dt = eqn.invars[0].aval.dtype
            if _is_float(dt) and _itemsize(dt) < 4:
                ctx.finding(
                    "low-precision-reduction", eqn,
                    f"{name} accumulates at {np.dtype(dt).name} — "
                    "reductions (softmax/normalization/loss accumulation) "
                    "must run at ≥ float32")

        # default: fresh value; provenance and scale taint flow through,
        # quantization state does not survive arithmetic
        prov = frozenset().union(*(i.prov for i in infos)) if infos \
            else frozenset()
        # amax-chain tagging: |x| → max reduce is how every in-program
        # quantizer derives its scales — mark the result 'scale' so the
        # dequant-completion check (see mul) has evidence for scales that
        # were never input leaves (the KV cache's _quantize_int8 path)
        if name == "abs":
            prov |= {"_abs"}
        elif name == "reduce_max" and infos and "_abs" in infos[0].prov:
            prov |= {"scale"}
        src = frozenset().union(*(i.scale_src for i in infos)) if infos \
            else frozenset()
        out_aval = getattr(eqn.outvars[0], "aval", None) if eqn.outvars \
            else None
        same_shape = infos and all(
            getattr(v.aval, "shape", None) == getattr(out_aval, "shape", ())
            for v in eqn.invars if hasattr(v, "aval"))
        varies = None
        if same_shape and all(i.varies is not None for i in infos):
            varies = frozenset().union(*(i.varies for i in infos))
        setout(eqn, VInfo(prov, varies, "", None, src))

    return [_info_of(env, v) for v in jaxpr.outvars]


def _flow_higher(eqn, infos: List[VInfo], env, ctx: _Ctx) -> None:
    """Recurse into nested jaxprs, mapping operand infos positionally."""
    import jax.core as core
    from .ir_audit import _sub_jaxprs
    name = eqn.primitive.name

    def setout(out_infos):
        outs = [v for v in eqn.outvars]
        for ov, info in zip(outs, out_infos or []):
            if not isinstance(ov, core.DropVar):
                env[ov] = info
        for ov in outs[len(out_infos or []):]:
            if not isinstance(ov, core.DropVar):
                env[ov] = VInfo(prov=frozenset({"const"}))

    if name == "pallas_call":
        # kernel bodies compute on Refs — opaque to value dataflow (their
        # primitive mix still lands in the contract histogram/class counts)
        setout([])
        return
    if name == "scan":
        body = next(iter(_sub_jaxprs(eqn.params)), None)
        if body is None or len(body.invars) != len(eqn.invars):
            setout([])
            return
        # consts and carry pass through whole; only the xs arrive sliced
        # along the scan axis (and only the ys come back stacked), so axis
        # tracking degrades just for those
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        mapped = list(infos[:nc + ncar]) + [
            dataclasses.replace(i, varies=None, scale_varies=None)
            for i in infos[nc + ncar:]]
        outs = _flow(body, mapped, ctx)
        setout(list(outs[:ncar]) + [
            dataclasses.replace(o, varies=None, scale_varies=None)
            for o in outs[ncar:]])
        return
    if name == "while":
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        cond_j = eqn.params["cond_jaxpr"].jaxpr
        body_j = eqn.params["body_jaxpr"].jaxpr
        carry = infos[cn + bn:]
        _flow(cond_j, infos[:cn] + carry, ctx)
        outs = _flow(body_j, infos[cn:cn + bn] + carry, ctx)
        setout(outs)
        return
    if name == "cond":
        branch_outs = []
        for br in eqn.params["branches"]:
            branch_outs.append(_flow(br.jaxpr, infos[1:], ctx))
        if not branch_outs:
            setout([])
            return
        joined = [_join(list(col)) for col in zip(*branch_outs)]
        setout(joined)
        return
    sub = _main_sub(eqn)
    if sub is None:
        setout([])
        return
    outs = _flow(sub, infos, ctx)
    if len(outs) == len(eqn.outvars):
        setout(outs)
    else:
        setout([])


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PrecisionReport:
    findings: List[dict]      # rule/site/line/detail/count, sorted
    boundary: dict            # the contract "precision" section


def class_counts(closed) -> Dict[str, int]:
    """Lattice-class histogram of every eqn output (recursively, pallas
    kernel bodies included) plus the weak-typed count."""
    import jax.core as core
    from .ir_audit import iter_eqns
    counts: Dict[str, int] = {}
    weak = 0
    for eqn in iter_eqns(closed.jaxpr):
        for ov in eqn.outvars:
            if isinstance(ov, core.DropVar):
                continue
            aval = ov.aval
            cls = classify_dtype(getattr(aval, "dtype", None))
            counts[cls] = counts.get(cls, 0) + 1
            if getattr(aval, "weak_type", False):
                weak += 1
    if weak:
        counts["weak"] = weak
    return dict(sorted(counts.items()))


def analyze(closed, roles: Optional[List[Tuple[str, str]]] = None
            ) -> PrecisionReport:
    """Run the precision flow over ``closed`` (a ClosedJaxpr). ``roles``:
    [(role, label)] aligned with the jaxpr invars (see :func:`infer_roles`);
    unlabeled invars default to activations."""
    jaxpr = closed.jaxpr
    ctx = _Ctx()
    in_infos: List[VInfo] = []
    for i, v in enumerate(jaxpr.invars):
        role, label = (roles[i] if roles is not None and i < len(roles)
                       else ("activation", f"arg{i}"))
        dtype = getattr(v.aval, "dtype", None)
        quant = "q" if (dtype is not None and _is_int8(dtype)) else ""
        scale_src = frozenset()
        if role == "scale":
            scale_src = frozenset({i})
            ctx.seeded_scales[i] = label
        in_infos.append(VInfo(frozenset({role}), _shape_varies(v.aval),
                              quant, None, scale_src))
    _flow(jaxpr, in_infos, ctx)

    findings = sorted(ctx.findings.values(),
                      key=lambda f: (f["rule"], f["site"]))
    for i, label in sorted(ctx.seeded_scales.items()):
        if i not in ctx.used_scales:
            findings.append({
                "rule": "orphaned-scale", "site": "<inputs>", "line": 0,
                "detail": f"scale input '{label}' never reaches a "
                          "dequantizing multiply — its quantized partner "
                          "is consumed scale-less or the scale is dead "
                          "weight", "count": 1})
    boundary = {
        "class_counts": class_counts(closed),
        "int8_dots": sorted(ctx.int8_dots.values(),
                            key=lambda e: (e["site"], e["accum"])),
        "dequants": sorted(ctx.dequants.values(),
                           key=lambda e: (e["site"], e["dst"],
                                          e["scale_axes"])),
    }
    return PrecisionReport(findings=findings, boundary=boundary)


def analyze_fn(fn, args, roles: Optional[List[Tuple[str, str]]] = None
               ) -> PrecisionReport:
    """Trace ``fn(*args)`` and analyze; roles default to the argument
    pytree's inferred provenance."""
    import jax
    closed = jax.make_jaxpr(fn)(*args)
    if roles is None:
        roles = infer_roles(args)
    return analyze(closed, roles)


def render_findings(entry: str, findings: List[dict]) -> List[str]:
    """Human-readable finding lines (the precision_audit report format)."""
    out = []
    for f in findings:
        n = f" (x{f['count']})" if f.get("count", 1) > 1 else ""
        out.append(f"{entry}: [{f['rule']}] {f['site']}: {f['detail']}{n}")
    return out
