"""Runtime recompilation guard — the dynamic half of graftlint.

The static rules catch the *causes* of recompile storms
(``jit-static-hazard``); this module catches the *symptom* wherever it
slips through: it counts actual XLA backend compiles via
``jax.monitoring``'s duration events and lets tests declare a compile
budget. A test that quietly starts recompiling per step still passes its
assertions — only wall-clock shows it, and only on hardware where compiles
are expensive. The budget turns that drift into a red test on CPU.

Usage (wired in tests/conftest.py):

    pytestmark = pytest.mark.recompile_budget(40)   # per-test ceiling

Budgets count EVERY backend compile the test triggers — including tiny
constant computations like ``jnp.ones`` — so they are ceilings locked to
measured values, not tight equalities.

Setting a sound ceiling: measure the module's COLD full-run total
(``GRAFTLINT_RECOMPILE_REPORT=1``, sum the per-test counts) and use that as
the per-test ceiling. Any single test run standalone compiles a subset of
what the full module run compiles, so the module total bounds every
ordering, ``-k`` subset, and xdist shard; a per-test cap measured mid-module
does NOT (later tests ride the first test's warm cache, then blow the cap
when run alone). The ceiling is loose for warm in-order runs — fine, the
guard exists to catch recompile DRIFT, which adds compiles per step, not
per single digit.

Set ``GRAFTLINT_RECOMPILE_REPORT=1`` to print per-test counts (how the
declared budgets were measured).
"""

from __future__ import annotations

from typing import Optional

import jax

try:
    from jax._src.dispatch import BACKEND_COMPILE_EVENT
except ImportError:  # event key is stable across recent jax; private import is not
    BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileCounter:
    """Monotonic count of XLA backend compiles in this process."""

    def __init__(self):
        self.count = 0

    def _on_event(self, event: str, duration: float, **kwargs):
        if event == BACKEND_COMPILE_EVENT:
            self.count += 1


_counter: Optional[CompileCounter] = None


def _self_test(counter: CompileCounter) -> None:
    """A guard that fails open is worse than no guard: if jax renames the
    monitoring event, the count would stay 0 and every budget would pass
    forever. One tiny throwaway jit at install time proves the listener
    actually fires (a fresh lambda is never cache-hit)."""
    import jax.numpy as jnp
    before = counter.count
    jax.jit(lambda x: x + 1)(jnp.zeros((3,), jnp.float32))
    if counter.count == before:
        raise RuntimeError(
            "recompile guard self-test failed: no backend-compile event "
            "observed for a fresh jit — jax likely renamed "
            f"{BACKEND_COMPILE_EVENT!r}; update recompile_guard.py")


def install_compile_counter() -> CompileCounter:
    """Idempotent: jax.monitoring has no unregister, so one listener is
    installed for the life of the process and shared by every caller."""
    global _counter
    if _counter is None:
        _counter = CompileCounter()
        jax.monitoring.register_event_duration_secs_listener(_counter._on_event)
        _self_test(_counter)
    return _counter
