"""Runtime recompilation guard — the dynamic half of graftlint.

The static rules catch the *causes* of recompile storms
(``jit-static-hazard``); this module catches the *symptom* wherever it
slips through: it counts actual XLA backend compiles via
``jax.monitoring``'s duration events and lets tests declare a compile
budget. A test that quietly starts recompiling per step still passes its
assertions — only wall-clock shows it, and only on hardware where compiles
are expensive. The budget turns that drift into a red test on CPU.

The counter itself now lives in ``dalle_tpu/obs/device.py`` so the same
event stream also feeds runtime telemetry (recompiles-per-100-steps as a
training metric — see docs/OBSERVABILITY.md); this module re-exports it for
the test harness, which is the guard's home turf.

Usage (wired in tests/conftest.py):

    pytestmark = pytest.mark.recompile_budget(40)   # per-test ceiling

Budgets count EVERY backend compile the test triggers — including tiny
constant computations like ``jnp.ones`` — so they are ceilings locked to
measured values, not tight equalities.

Setting a sound ceiling: measure the module's COLD full-run total
(``GRAFTLINT_RECOMPILE_REPORT=1``, sum the per-test counts) and use that as
the per-test ceiling. Any single test run standalone compiles a subset of
what the full module run compiles, so the module total bounds every
ordering, ``-k`` subset, and xdist shard; a per-test cap measured mid-module
does NOT (later tests ride the first test's warm cache, then blow the cap
when run alone). The ceiling is loose for warm in-order runs — fine, the
guard exists to catch recompile DRIFT, which adds compiles per step, not
per single digit.

Set ``GRAFTLINT_RECOMPILE_REPORT=1`` to print per-test counts (how the
declared budgets were measured).
"""

from __future__ import annotations

from ..obs.device import (BACKEND_COMPILE_EVENT, CompileCounter,  # noqa: F401
                          install_compile_counter)
