"""vmem-ceiling: keep Pallas scoped-VMEM ceilings and estimators in lockstep.

The b695782 lesson: Mosaic's 16M scoped-vmem ceiling is a compiler default,
and ops/fused_attention.py raises it per kernel from a byte ESTIMATOR that
is known to underestimate the compiler's real demand (21.55M estimated vs
25.68M reported at the medium calibration point). The ≥25% headroom rule is
what keeps an admitted shape from busting its requested ceiling with no
dense fallback. Nothing at runtime checks that rule — a PR that edits the
estimator, the tier table, or the admission gate independently compiles
fine and fails on hardware. This rule re-derives the contract at lint time:

  * every (gate, ceiling) tier is internally ordered (gate < ceiling);
  * the admission budget equals the first tier's gate;
  * the MEDIUM calibration shape (n=513, h·d=1024) routes to the 32M tier
    and its estimate carries ≥25% headroom under that ceiling;
  * that headroom still covers the compiler's measured 25.68M demand —
    i.e. the estimator has not drifted below the one real data point;
  * the largest admitted estimate still fits the top tier with headroom;
  * no ops file hard-codes a ``vmem_limit_bytes=`` literal outside the
    tier table (rogue ceilings bypass the whole contract).
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Sequence

from .core import REPO_ROOT, FileContext, Finding, ProjectRule, register_rule

_FUSED_PATH = "dalle_tpu/ops/fused_attention.py"

# the one measured calibration point (docs/PERF_SMALL.md r5, commit b695782):
# medium config n=513, h·d=1024; compiler reported 25.68M scoped-vmem demand;
# the tier that admits it is 32M.
_CAL_N, _CAL_HD = 513, 1024
_CAL_COMPILER_BYTES = int(25.68 * 1024 * 1024)
_CAL_EXPECTED_LIMIT = 32 * 1024 * 1024
_HEADROOM_NUM, _HEADROOM_DEN = 1, 4   # ≥25% over the estimate


def check_estimator_contract(mod) -> List[str]:
    """Invariant messages for a module shaped like ops.fused_attention.
    Split out (module injected) so tests can feed a broken fake."""
    msgs: List[str] = []
    limits: Sequence = getattr(mod, "_VMEM_RAISED_LIMITS", ())
    budget = getattr(mod, "_VMEM_RAISED_BUDGET", None)
    bwd_bytes = getattr(mod, "_bwd_bytes", None)
    compiler_params = getattr(mod, "_compiler_params", None)
    if not limits or budget is None or bwd_bytes is None or compiler_params is None:
        return ["fused_attention no longer exposes _VMEM_RAISED_LIMITS/"
                "_VMEM_RAISED_BUDGET/_bwd_bytes/_compiler_params — the "
                "vmem-ceiling rule cannot verify the contract; update "
                "analysis/rules_vmem.py with it"]

    for gate, limit in limits:
        if gate >= limit:
            msgs.append(f"tier ({gate}, {limit}): gate must be below its "
                        "ceiling")
    if budget != limits[0][0]:
        msgs.append(f"_VMEM_RAISED_BUDGET ({budget}) != first tier gate "
                    f"({limits[0][0]}) — the admission gate and the tier "
                    "table have drifted apart")

    est = bwd_bytes(_CAL_N, _CAL_HD)
    need = est + est * _HEADROOM_NUM // _HEADROOM_DEN
    cp = compiler_params(est)
    got = getattr(cp, "vmem_limit_bytes", None) if cp is not None else None
    if got != _CAL_EXPECTED_LIMIT:
        msgs.append(
            f"medium calibration (n={_CAL_N}, hd={_CAL_HD}): estimator gives "
            f"{est} bytes, which routes to ceiling {got} — expected the "
            f"{_CAL_EXPECTED_LIMIT} (32M) tier. Estimator and tier table "
            "were edited inconsistently")
    elif need > got:
        msgs.append(
            f"medium calibration: estimate {est} + 25% headroom = {need} "
            f"exceeds its own ceiling {got}")
    if need < _CAL_COMPILER_BYTES:
        msgs.append(
            f"medium calibration: estimate {est} + 25% headroom = {need} no "
            f"longer covers the compiler's measured {_CAL_COMPILER_BYTES} "
            "demand — the estimator drifted below the known data point; "
            "recalibrate before trusting the admission gate")

    # the largest estimate the gate admits must fit the top tier with headroom
    top = limits[-1][1]
    worst = budget + budget * _HEADROOM_NUM // _HEADROOM_DEN
    if worst > top:
        msgs.append(
            f"admission budget {budget} + 25% headroom = {worst} exceeds the "
            f"top ceiling {top} — a gate-admitted shape could bust scoped "
            "VMEM with no dense fallback")
    return msgs


def _known_limits(mod) -> set:
    """CEILING values only — a tier's admission gate (e.g. 30M) is not a
    valid ceiling to request; hard-coding it would admit the calibration
    shape with <25% headroom, the exact bust this rule exists to prevent."""
    return {limit for _, limit in getattr(mod, "_VMEM_RAISED_LIMITS", ())}


@register_rule
class VmemCeiling(ProjectRule):
    name = "vmem-ceiling"
    description = ("pltpu.CompilerParams vmem ceilings must stay consistent "
                   "with the kernel VMEM estimator (≥25% headroom rule)")
    triggers = ("dalle_tpu/ops/", "dalle_tpu/analysis/")

    def check_project(self, ctxs, repo_root=REPO_ROOT) -> Iterable[Finding]:
        findings: List[Finding] = []
        if os.path.realpath(repo_root) != os.path.realpath(REPO_ROOT):
            # the contract check executes the IMPORTED dalle_tpu, which is
            # this checkout's — silently validating it against a foreign
            # checkout's sources would lint green on a broken tree
            return [Finding(
                self.name, _FUSED_PATH, 1,
                "vmem-ceiling verifies the imported dalle_tpu package and "
                f"cannot vouch for a foreign checkout at {repo_root}; run "
                "that checkout's own scripts/lint.py")]
        try:
            from dalle_tpu.ops import fused_attention as mod
        except Exception as e:  # noqa: BLE001 - import failure IS the finding
            return [Finding(self.name, _FUSED_PATH, 1,
                            f"cannot import ops.fused_attention: {e!r}")]
        anchor = self._anchor_line(ctxs)
        try:
            msgs = check_estimator_contract(mod)
        except Exception as e:  # noqa: BLE001 - a raising contract IS the finding
            msgs = [f"estimator contract check raised {e!r} — the ceiling "
                    "machinery is broken, not just drifted"]
        for msg in msgs:
            findings.append(Finding(self.name, _FUSED_PATH, anchor, msg))

        known = _known_limits(mod)
        for ctx in ctxs:
            if not ctx.rel_path.startswith("dalle_tpu/ops/"):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if (kw.arg == "vmem_limit_bytes"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, int)
                            and kw.value.value not in known):
                        findings.append(Finding(
                            self.name, ctx.rel_path, node.lineno,
                            f"hard-coded vmem_limit_bytes={kw.value.value} "
                            "is not in fused_attention._VMEM_RAISED_LIMITS — "
                            "route ceilings through the tier table so the "
                            "headroom contract covers them"))
        return findings

    @staticmethod
    def _anchor_line(ctxs) -> int:
        """Line of the tier table assignment, for a clickable finding."""
        for ctx in ctxs:
            if ctx.rel_path != _FUSED_PATH:
                continue
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "_VMEM_RAISED_LIMITS"
                        for t in node.targets):
                    return node.lineno
        return 1
