"""Distributed-I/O hygiene — codifying the graftmend retry-layer lesson.

A production pod fails at the edges: the coordinator isn't listening yet
when a rejoining worker dials in, a checkpoint write races a filesystem
blip. ``utils/retry.py`` exists so those single-attempt edges absorb
transient failures with jittered backoff and obs counters — but only at
call sites that actually route through it. This rule makes a bare edge a
lint finding instead of a 3 a.m. page:

  * ``unguarded-distributed-io`` — a ``jax.distributed.initialize(...)``
    call, a ``save``/``restore`` call on an orbax manager handle (the
    ``_mgr`` naming convention set by ``train/checkpoints.py``), or a raw
    ``socket.create_connection(...)`` RPC dial (the graftfleet transport
    edge — ``fleet/transport.py`` sets the guarded-dial convention), that
    is not executed under the retry layer. "Under the retry layer" is
    recognized syntactically (the rules_jit trade): the call sits inside a
    function decorated with ``@retry(...)``, or inside a function whose
    name is passed to ``with_retry(...)``/``retry(...)(...)`` in the same
    module. A deliberate single-attempt call takes a one-line suppression
    next to the code with the why.

The runtime half of the story lives in ``dalle_tpu/utils/retry.py``
(policy, counters) and ``scripts/chaos_smoke.py`` (the CI stage that
injects coordinator/checkpoint faults and asserts they are absorbed, not
fatal — docs/RESILIENCE.md).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import dotted_name

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
# the RAW orbax handle naming convention (train/checkpoints.py). The
# public CheckpointManager.save/restore wrappers are themselves the
# retry layer, so calls on a `mgr`-named wrapper instance are not flagged.
_MGR_NAMES = ("_mgr",)
_MGR_METHODS = ("save", "restore")


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _retry_guarded_names(tree: ast.AST) -> Set[str]:
    """Function names executed under the retry layer: arguments of
    ``with_retry(op, fn, ...)`` calls and targets of ``retry(...)(fn)``
    immediate application."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "with_retry":
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
        # retry("op", ...)(fn): the decorator factory applied inline
        if (isinstance(node.func, ast.Call)
                and _call_name(node.func) == "retry"):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
    return out


def _has_retry_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = (target.attr if isinstance(target, ast.Attribute)
                else target.id if isinstance(target, ast.Name) else "")
        if name in ("retry", "with_retry"):
            return True
    return False


def _is_distributed_init(node: ast.Call) -> bool:
    name = dotted_name(node.func) or ""
    return name.endswith("distributed.initialize")


def _is_socket_dial(node: ast.Call) -> bool:
    """``socket.create_connection(...)`` (or the bare name after a
    ``from socket import create_connection``) — the raw TCP dial every
    fleet RPC edge starts from. A single-attempt dial turns a replica
    mid-restart or a briefly full accept queue into a failed request; the
    graftfleet transport wraps its one raw dial in ``retry(...)`` and
    everything else goes through that wrapper."""
    name = dotted_name(node.func) or ""
    # exactly the stdlib spellings: ``socket.create_connection(...)`` or
    # the bare name after a from-import. Other APIs that happen to carry
    # the method name (asyncio's loop.create_connection, a pool's) manage
    # their own retries and are not this rule's business.
    return name in ("create_connection", "socket.create_connection")


def _is_mgr_io(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _MGR_METHODS):
        return False
    recv = fn.value
    # self._mgr.save(...) / mgr.restore(...): the receiver chain must name
    # an orbax manager handle — plain .save()/.restore() on anything else
    # (a model, a figure) is not this rule's business
    for sub in ast.walk(recv):
        if isinstance(sub, ast.Attribute) and sub.attr in _MGR_NAMES:
            return True
        if isinstance(sub, ast.Name) and sub.id in _MGR_NAMES:
            return True
    return False


@register_rule
class UnguardedDistributedIO(Rule):
    name = "unguarded-distributed-io"
    description = (
        "jax.distributed.initialize, an orbax manager save/restore call, "
        "or a raw socket.create_connection RPC dial outside the retry "
        "layer (utils/retry.py) — a transient coordinator/filesystem/"
        "connect blip becomes a dead worker or failed request instead of "
        "a few ms of jittered backoff; wrap the call in @retry/with_retry "
        "or suppress with the why")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        guarded = _retry_guarded_names(ctx.tree)

        def walk(node: ast.AST, stack: List[ast.AST]):
            if isinstance(node, _FUNC_NODES):
                stack = stack + [node]
            if isinstance(node, ast.Call):
                kind = ("jax.distributed.initialize"
                        if _is_distributed_init(node)
                        else "socket.create_connection"
                        if _is_socket_dial(node)
                        else f"orbax manager .{node.func.attr}()"
                        if _is_mgr_io(node) else None)
                if kind is not None and not any(
                        fn.name in guarded or _has_retry_decorator(fn)
                        for fn in stack):
                    yield Finding(
                        self.name, ctx.rel_path, node.lineno,
                        f"{kind} runs single-attempt — route it through "
                        "the retry layer (utils/retry.py: @retry or "
                        "with_retry) so transient failures back off "
                        "instead of killing the run")
            for child in ast.iter_child_nodes(node):
                yield from walk(child, stack)

        yield from walk(ctx.tree, [])
