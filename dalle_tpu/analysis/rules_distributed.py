"""Distributed-I/O hygiene — codifying the graftmend retry-layer lesson.

A production pod fails at the edges: the coordinator isn't listening yet
when a rejoining worker dials in, a checkpoint write races a filesystem
blip. ``utils/retry.py`` exists so those single-attempt edges absorb
transient failures with jittered backoff and obs counters — but only at
call sites that actually route through it. This rule makes a bare edge a
lint finding instead of a 3 a.m. page:

  * ``unguarded-distributed-io`` — a ``jax.distributed.initialize(...)``
    call, a ``save``/``restore`` call on an orbax manager handle (the
    ``_mgr`` naming convention set by ``train/checkpoints.py``), or a raw
    ``socket.create_connection(...)`` RPC dial (the graftfleet transport
    edge — ``fleet/transport.py`` sets the guarded-dial convention), that
    is not executed under the retry layer. "Under the retry layer" is
    recognized syntactically (the rules_jit trade): the call sits inside a
    function decorated with ``@retry(...)``, or inside a function whose
    name is passed to ``with_retry(...)``/``retry(...)(...)`` in the same
    module. A deliberate single-attempt call takes a one-line suppression
    next to the code with the why.

The runtime half of the story lives in ``dalle_tpu/utils/retry.py``
(policy, counters) and ``scripts/chaos_smoke.py`` (the CI stage that
injects coordinator/checkpoint faults and asserts they are absorbed, not
fatal — docs/RESILIENCE.md).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import dotted_name

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
# the RAW orbax handle naming convention (train/checkpoints.py). The
# public CheckpointManager.save/restore wrappers are themselves the
# retry layer, so calls on a `mgr`-named wrapper instance are not flagged.
_MGR_NAMES = ("_mgr",)
_MGR_METHODS = ("save", "restore")


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _retry_guarded_names(tree: ast.AST) -> Set[str]:
    """Function names executed under the retry layer: arguments of
    ``with_retry(op, fn, ...)`` calls and targets of ``retry(...)(fn)``
    immediate application."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "with_retry":
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
        # retry("op", ...)(fn): the decorator factory applied inline
        if (isinstance(node.func, ast.Call)
                and _call_name(node.func) == "retry"):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
    return out


def _has_retry_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = (target.attr if isinstance(target, ast.Attribute)
                else target.id if isinstance(target, ast.Name) else "")
        if name in ("retry", "with_retry"):
            return True
    return False


def _is_distributed_init(node: ast.Call) -> bool:
    name = dotted_name(node.func) or ""
    return name.endswith("distributed.initialize")


def _is_socket_dial(node: ast.Call) -> bool:
    """``socket.create_connection(...)`` (or the bare name after a
    ``from socket import create_connection``) — the raw TCP dial every
    fleet RPC edge starts from. A single-attempt dial turns a replica
    mid-restart or a briefly full accept queue into a failed request; the
    graftfleet transport wraps its one raw dial in ``retry(...)`` and
    everything else goes through that wrapper."""
    name = dotted_name(node.func) or ""
    # exactly the stdlib spellings: ``socket.create_connection(...)`` or
    # the bare name after a from-import. Other APIs that happen to carry
    # the method name (asyncio's loop.create_connection, a pool's) manage
    # their own retries and are not this rule's business.
    return name in ("create_connection", "socket.create_connection")


def _is_mgr_io(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _MGR_METHODS):
        return False
    recv = fn.value
    # self._mgr.save(...) / mgr.restore(...): the receiver chain must name
    # an orbax manager handle — plain .save()/.restore() on anything else
    # (a model, a figure) is not this rule's business
    for sub in ast.walk(recv):
        if isinstance(sub, ast.Attribute) and sub.attr in _MGR_NAMES:
            return True
        if isinstance(sub, ast.Name) and sub.id in _MGR_NAMES:
            return True
    return False


@register_rule
class UnboundedBlockingCall(Rule):
    """``unbounded-blocking-call`` — codifying the graftward
    wedge-detection lesson (docs/RESILIENCE.md "Degradation ladder"): the
    serving control plane is a web of threads joined by queues, events and
    sockets, and ONE timeout-less blocking call turns a sick peer into a
    parked thread nobody can observe — the connection handler waiting on a
    queue a wedged engine will never feed, the worker waiting on an event
    a dead thread will never set. Every cross-thread/cross-process wait in
    the fleet/gateway/serve paths must be BOUNDED so the waiter gets a
    chance to notice the world changed (drain flags, closed replicas,
    frozen progress).

    Flagged, scoped to ``dalle_tpu/{fleet,gateway,serve}/``:

      * ``q.get()`` / ``ev.wait()`` / ``t.join()`` with NO arguments and
        no ``timeout=`` — the zero-arg forms are exactly the
        block-forever spellings (``d.get(key)`` has a positional arg and
        never matches, so dict lookups stay out of scope).
      * ``sock.recv(...)`` in a module that never calls ``settimeout`` —
        a best-effort whole-module check: one ``settimeout`` anywhere
        means the module manages socket deadlines (the
        ``fleet/transport.py`` convention, where every reader sets the
        socket timeout before pulling frames).

    A deliberate forever-wait (a main thread parked on a shutdown event)
    takes a one-line suppression with the why."""

    name = "unbounded-blocking-call"
    description = (
        "a Queue.get()/Event.wait()/Thread.join() with no timeout, or a "
        "socket recv in a module that never sets a socket timeout, in the "
        "fleet/gateway/serve control plane — a wedged or dead peer then "
        "parks this thread forever with no way to notice drain flags or "
        "frozen progress; pass a timeout and re-check, or suppress with "
        "the why")
    include = ("dalle_tpu/fleet/", "dalle_tpu/gateway/",
               "dalle_tpu/serve/")

    _BLOCKING_ATTRS = ("get", "wait", "join")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        has_settimeout = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "settimeout"
            for n in ast.walk(ctx.tree))
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            has_timeout_kw = any(kw.arg == "timeout"
                                 for kw in node.keywords)
            if (attr in self._BLOCKING_ATTRS and not node.args
                    and not node.keywords):
                yield Finding(
                    self.name, ctx.rel_path, node.lineno,
                    f".{attr}() with no timeout blocks this thread until "
                    "the other side acts — a wedged engine or dead peer "
                    "parks it forever; pass timeout= and re-check the "
                    "drain/closed state each wakeup")
            elif (attr == "recv" and not has_settimeout
                    and not has_timeout_kw):
                yield Finding(
                    self.name, ctx.rel_path, node.lineno,
                    ".recv() in a module that never calls settimeout — "
                    "a quiet peer blocks this reader forever; set a "
                    "socket timeout (the fleet/transport.py convention) "
                    "so liveness checks get to run")


@register_rule
class UnguardedDistributedIO(Rule):
    name = "unguarded-distributed-io"
    description = (
        "jax.distributed.initialize, an orbax manager save/restore call, "
        "or a raw socket.create_connection RPC dial outside the retry "
        "layer (utils/retry.py) — a transient coordinator/filesystem/"
        "connect blip becomes a dead worker or failed request instead of "
        "a few ms of jittered backoff; wrap the call in @retry/with_retry "
        "or suppress with the why")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        guarded = _retry_guarded_names(ctx.tree)

        def walk(node: ast.AST, stack: List[ast.AST]):
            if isinstance(node, _FUNC_NODES):
                stack = stack + [node]
            if isinstance(node, ast.Call):
                kind = ("jax.distributed.initialize"
                        if _is_distributed_init(node)
                        else "socket.create_connection"
                        if _is_socket_dial(node)
                        else f"orbax manager .{node.func.attr}()"
                        if _is_mgr_io(node) else None)
                if kind is not None and not any(
                        fn.name in guarded or _has_retry_decorator(fn)
                        for fn in stack):
                    yield Finding(
                        self.name, ctx.rel_path, node.lineno,
                        f"{kind} runs single-attempt — route it through "
                        "the retry layer (utils/retry.py: @retry or "
                        "with_retry) so transient failures back off "
                        "instead of killing the run")
            for child in ast.iter_child_nodes(node):
                yield from walk(child, stack)

        yield from walk(ctx.tree, [])
