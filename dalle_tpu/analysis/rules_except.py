"""broad-except: ``except Exception`` must carry a written justification.

The required idiom (set by data/webdataset.py, which catches broadly on
purpose at shard/sample level):

    except Exception as e:   # noqa: BLE001 - shard-level skip

i.e. a ``# noqa: BLE001`` on the except line followed by ``- <reason>``.
A bare ``except:`` is flagged unconditionally — it swallows
KeyboardInterrupt/SystemExit invisibly; spell it ``except BaseException``
with a justification if crossing a thread boundary really requires it.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import dotted_name

_JUSTIFIED = re.compile(r"#\s*noqa:\s*BLE001\s*-\s*\S")


@register_rule
class BroadExcept(Rule):
    name = "broad-except"
    description = ("except Exception without a '# noqa: BLE001 - <reason>' "
                   "justification on the except line")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(self.name, ctx.rel_path, node.lineno,
                              "bare 'except:' swallows KeyboardInterrupt/"
                              "SystemExit — catch a concrete exception type")
                continue
            caught = {dotted_name(t) for t in (
                node.type.elts if isinstance(node.type, ast.Tuple)
                else [node.type])}
            broad = caught & {"Exception", "BaseException"}
            if not broad:
                continue
            line = ctx.lines[node.lineno - 1] if node.lineno <= len(ctx.lines) else ""
            if _JUSTIFIED.search(line):
                continue
            yield Finding(
                self.name, ctx.rel_path, node.lineno,
                f"'except {sorted(broad)[0]}' without justification — narrow "
                "the type or annotate why broad is correct: "
                "'except Exception as e:  # noqa: BLE001 - <reason>'")
