"""graftir — jaxpr/HLO-level program contracts for the registered entry points.

graftlint (PR 1) reads source text; the hazards that actually burn TPU time
live in the traced program: a silent bf16→f32 ``convert_element_type`` in the
step, a refactor that doubles the collective count under fsdp, a
``donate_argnums`` XLA quietly declines to alias, a host callback hiding
behind a library call. This module extracts a **program contract** from the
ClosedJaxpr (and, for compiled entries, the optimized HLO) of an entry point:

  * primitive histogram — every primitive, counted recursively through
    nested jaxprs (scan/cond/while/pjit/custom_vjp/pallas_call kernels);
  * dtype-promotion events — each ``convert_element_type`` that WIDENS a
    value to a floating dtype, with source provenance (file::function);
  * host-transfer sites — callback/infeed/outfeed primitives in the program;
  * collective inventory — kind × per-device operand bytes × mesh axes,
    parsed from the compiled HLO (GSPMD inserts collectives at compile time,
    so the jaxpr alone cannot see them);
  * donation effectiveness — donated inputs actually aliased to outputs in
    the compiled executable (``input_output_alias``);
  * an analytic peak-memory estimate — linear liveness scan over the jaxpr
    (deterministic, version-stable; compared with tolerance).

Contracts serialize to golden JSON under ``contracts/`` and are enforced by
``scripts/ir_audit.py --check`` (CI). Waivers are source comments next to
the code they excuse, graftlint-style::

    # graftir: allow=donation -- <reason>

and apply to the entry whose ``source`` file carries them. A waiver without
a reason is itself a finding. The entry registry lives in
:mod:`dalle_tpu.analysis.contracts`.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import re
import tokenize
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .core import REPO_ROOT

SCHEMA = 2

# drift checks a source waiver can silence, and the invariant checks.
# "precision" covers both the contract's quantization-boundary-map drift
# (below) and the precision-flow rule findings scripts/precision_audit.py
# enforces (analysis/precision_flow.py).
RULES = ("primitives", "promotions", "transfers", "collectives", "memory",
         "donation", "precision")

# memory estimate is analytic; small jaxpr-preserving refactors can move it
# a little without a real regression — compare with tolerance
MEMORY_RTOL = 0.05

_WAIVER_RE = re.compile(r"#\s*graftir:\s*allow=([\w\-]+)(?:\s*--\s*(.*))?")

_TRANSFER_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "infeed", "outfeed"}

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------

def _jax():
    import jax
    return jax


def _aval_bytes(aval) -> int:
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG key<fry> etc.) aren't numpy dtypes but do
        # carry their storage itemsize
        itemsize = getattr(dtype, "itemsize", 0)
    return int(size) * int(itemsize)


def _sub_jaxprs(params: dict):
    """Nested (Closed)Jaxprs hiding in an eqn's params, recursively."""
    import jax.core as core

    def walk(val):
        if isinstance(val, core.ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, core.Jaxpr):
            yield val
        elif isinstance(val, (list, tuple)):
            for v in val:
                yield from walk(v)

    for val in params.values():
        yield from walk(val)


def iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and its nested jaxprs (static occurrence count:
    an eqn inside a scan body is counted once, not ``length`` times)."""
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield eqn
            stack.extend(_sub_jaxprs(eqn.params))


def unwrap_jaxpr(closed):
    """The traced body of a jitted fn is one pjit eqn — descend to it so the
    top-level liveness scan sees the real program."""
    j = closed.jaxpr
    while len(j.eqns) == 1 and j.eqns[0].primitive.name in ("pjit", "jit",
                                                            "closed_call"):
        inner = list(_sub_jaxprs(j.eqns[0].params))
        if not inner:
            break
        j = inner[0]
    return j


def primitive_histogram(closed) -> Dict[str, int]:
    counts = Counter(eqn.primitive.name for eqn in iter_eqns(closed.jaxpr))
    return dict(sorted(counts.items()))


def _site_of(eqn) -> Tuple[str, int]:
    """("relpath::function", line) of the user frame that emitted ``eqn`` —
    the contract keys on file::function only, so unrelated edits that shift
    line numbers don't read as drift."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return "<unknown>", 0
        path = frame.file_name
        try:
            rel = os.path.relpath(path, REPO_ROOT)
            if not rel.startswith(".."):
                path = rel.replace(os.sep, "/")
            else:
                path = os.path.basename(path)
        except ValueError:
            path = os.path.basename(path)
        line = getattr(frame, "start_line", 0) or 0
        return f"{path}::{frame.function_name}", int(line)
    except Exception:  # noqa: BLE001 - provenance is best-effort (private API)
        return "<unknown>", 0


def promotion_events(closed) -> List[dict]:
    """convert_element_type eqns that WIDEN to a floating dtype (bf16→f32,
    int8→bf16 dequant, f32→f64...), aggregated by (src, dst, site)."""
    agg: Dict[Tuple[str, str, str], dict] = {}
    for eqn in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src_aval, dst_aval = eqn.invars[0].aval, eqn.outvars[0].aval
        src = np.dtype(src_aval.dtype)
        dst = np.dtype(dst_aval.dtype)
        if not (np.issubdtype(dst, np.floating)
                and dst.itemsize > src.itemsize):
            continue
        site, line = _site_of(eqn)
        key = (src.name, dst.name, site)
        ev = agg.setdefault(key, {"src": src.name, "dst": dst.name,
                                  "site": site, "count": 0, "bytes": 0})
        ev["count"] += 1
        ev["bytes"] += _aval_bytes(dst_aval)
    return sorted(agg.values(), key=lambda e: (e["site"], e["src"], e["dst"]))


def transfer_sites(closed) -> List[dict]:
    """Host round-trip primitives in the program (callbacks, infeed/outfeed).
    ``device_get``-style syncs cannot appear inside a traced program — those
    are source-level and covered by graftlint's host-sync-in-jit rule."""
    agg: Dict[Tuple[str, str], dict] = {}
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name not in _TRANSFER_PRIMS:
            continue
        site, _ = _site_of(eqn)
        ev = agg.setdefault((name, site),
                            {"primitive": name, "site": site, "count": 0})
        ev["count"] += 1
    return sorted(agg.values(), key=lambda e: (e["primitive"], e["site"]))


def peak_memory_estimate(closed) -> dict:
    """Analytic liveness scan over the (unwrapped) jaxpr: walk eqns in
    program order, track live value bytes (a var dies after its last use),
    charge each eqn its outputs plus the transient peak of its nested
    jaxprs. An ESTIMATE — XLA fuses and rematerializes — but deterministic
    for a given program, which is what a drift check needs."""
    import jax.core as core

    def scan(jaxpr) -> Tuple[int, int]:
        """(peak_bytes, resident_in_out_bytes) for one jaxpr."""
        last_use: Dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if isinstance(v, core.Var):
                    last_use[v] = i
        n = len(jaxpr.eqns)
        for v in jaxpr.outvars:
            if isinstance(v, core.Var):
                last_use[v] = n
        live: Dict = {}
        for v in list(jaxpr.invars) + list(jaxpr.constvars):
            live[v] = _aval_bytes(v.aval)
        live_bytes = sum(live.values())
        peak = live_bytes
        for i, eqn in enumerate(jaxpr.eqns):
            inner = 0
            for sub in _sub_jaxprs(eqn.params):
                inner = max(inner, scan(sub)[0])
            out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars
                            if not isinstance(v, core.DropVar))
            peak = max(peak, live_bytes + out_bytes + inner)
            for v in eqn.outvars:
                if isinstance(v, core.DropVar):
                    continue
                if v not in live:
                    live[v] = _aval_bytes(v.aval)
                    live_bytes += live[v]
            dead = [v for v, at in last_use.items() if at == i and v in live]
            for v in dead:
                live_bytes -= live.pop(v)
                del last_use[v]
        return peak, live_bytes

    j = unwrap_jaxpr(closed)
    arg_bytes = sum(_aval_bytes(v.aval) for v in j.invars)
    out_bytes = sum(_aval_bytes(getattr(v, "aval", None)) for v in j.outvars
                    if hasattr(v, "aval"))
    peak, _ = scan(j)
    return {"peak_bytes_est": int(peak), "arg_bytes": int(arg_bytes),
            "out_bytes": int(out_bytes)}


# --------------------------------------------------------------------------
# compiled-HLO parsing: collectives + donation aliasing
# --------------------------------------------------------------------------

def _parse_hlo_shapes(arglist: str) -> int:
    """Total bytes of the HLO operand list ``f32[8,16]{1,0} %a, bf16[4] %b``."""
    total = 0
    for dtype, dims in re.findall(r"\b(\w+)\[([\d,]*)\]", arglist):
        if dtype not in _HLO_DTYPE_BYTES:
            continue
        numel = 1
        for d in dims.split(","):
            if d:
                numel *= int(d)
        total += numel * _HLO_DTYPE_BYTES[dtype]
    return total


def parse_replica_groups(text: str) -> List[frozenset]:
    """HLO ``replica_groups`` in either the explicit ``{{0,1},{2,3}}`` form or
    the iota form ``[4,2]<=[8]`` / ``[4,2]<=[2,2,2]T(2,1,0)``."""
    text = text.strip()
    if text.startswith("{"):
        return [frozenset(int(x) for x in g.split(","))
                for g in re.findall(r"\{([\d,]+)\}", text)]
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", text)
    if not m:
        return []
    gshape = [int(x) for x in m.group(1).split(",")]
    dims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    ids = ids.reshape(gshape)
    return [frozenset(int(x) for x in row) for row in ids]


def mesh_axis_groups(mesh, axes: Sequence[str]) -> List[frozenset]:
    """Device-id groups a collective over ``axes`` of ``mesh`` would form."""
    names = list(mesh.axis_names)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    order = [i for i, n in enumerate(names) if n not in axes] + \
            [i for i, n in enumerate(names) if n in axes]
    moved = np.transpose(ids, order)
    group = int(np.prod([mesh.shape[a] for a in axes]))
    return [frozenset(int(x) for x in row)
            for row in moved.reshape(-1, group)]


def axes_for_groups(mesh, groups: List[frozenset]) -> str:
    """Mesh axis names matching a set of replica groups; smallest matching
    subset of the >1-sized axes wins (a size-1 axis never changes groups)."""
    import itertools
    if not groups or all(len(g) <= 1 for g in groups):
        return "none"
    real = [a for a in mesh.axis_names if mesh.shape[a] > 1]
    want = set(groups)
    for r in range(1, len(real) + 1):
        for combo in itertools.combinations(real, r):
            if set(mesh_axis_groups(mesh, combo)) == want:
                return ",".join(combo)
    return "unmatched"


def axes_for_pairs(mesh, pairs: List[Tuple[int, int]]) -> str:
    """Mesh axes a ``source_target_pairs`` permutation moves data across:
    the union, over pairs, of axes whose device coordinates differ between
    source and target. A ring shift along one axis names that axis; a GSPMD
    resharding permute names every axis it crosses."""
    coords: Dict[int, dict] = {}
    it = np.nditer(np.vectorize(lambda d: d.id)(mesh.devices),
                   flags=["multi_index"])
    for did in it:
        coords[int(did)] = dict(zip(mesh.axis_names, it.multi_index))
    moved = set()
    for a, b in pairs:
        ca, cb = coords.get(a), coords.get(b)
        if ca is None or cb is None:
            return "unknown"
        moved.update(ax for ax in mesh.axis_names if ca[ax] != cb[ax])
    if not moved:
        return "none"
    return ",".join(ax for ax in mesh.axis_names if ax in moved)


def collective_inventory(hlo_text: str, mesh=None) -> List[dict]:
    """Collective instructions in optimized HLO: kind × per-device operand
    bytes × mesh axes, aggregated with counts. ``-done`` halves of async
    pairs are skipped (the ``-start`` carries the operands). Axis
    attribution reads ``replica_groups`` where present; a
    ``collective-permute`` instead carries ``source_target_pairs``, from
    which :func:`axes_for_pairs` recovers the crossed mesh axes."""
    agg: Dict[Tuple[str, int, str], dict] = {}
    op_re = re.compile(
        r"=\s*(?:\([^)]*\)|\S+)\s+(" + "|".join(_COLLECTIVE_OPS) +
        r")(-start)?\((.*?)\)(?:,|\s)")
    rg_re = re.compile(r"replica_groups=(\{\{[\d,{} ]*\}\}|\[[\d,]+\]<=\["
                       r"[\d,]+\](?:T\([\d,]+\))?)")
    stp_re = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
    for line in hlo_text.splitlines():
        m = op_re.search(line)
        if not m or f"{m.group(1)}-done" in line:
            continue
        kind = m.group(1)
        nbytes = _parse_hlo_shapes(m.group(3))
        axes = "unknown"
        rg = rg_re.search(line)
        stp = stp_re.search(line)
        if rg and mesh is not None:
            axes = axes_for_groups(mesh, parse_replica_groups(rg.group(1)))
        elif stp and mesh is not None:
            pairs = [(int(a), int(b)) for a, b in
                     re.findall(r"\{(\d+),(\d+)\}", stp.group(1))]
            axes = axes_for_pairs(mesh, pairs)
        key = (kind, nbytes, axes)
        ev = agg.setdefault(key, {"kind": kind, "bytes": nbytes,
                                  "axes": axes, "count": 0})
        ev["count"] += 1
    return sorted(agg.values(),
                  key=lambda e: (e["kind"], e["axes"], -e["bytes"]))


def donation_report(hlo_text: str, donated_leaves: int) -> dict:
    """input_output_alias pairs in the compiled module header vs the number
    of donated argument leaves. ``aliased < donated`` means XLA declined to
    reuse some donated buffer — the donation is silently not saving the
    memory the code claims it does."""
    marker = "input_output_alias={"
    aliased = 0
    start = hlo_text.find(marker)
    if start != -1:
        # the annotation nests braces ({ {0}: (0, {}, may-alias), ... }) —
        # scan to the BALANCED close; a regex alternation stops at the
        # first inner '}'
        i = j = start + len(marker)
        depth = 1
        while j < len(hlo_text) and depth:
            depth += {"{": 1, "}": -1}.get(hlo_text[j], 0)
            j += 1
        aliased = len(re.findall(r"\(\s*\d+\s*,\s*\{[^}]*\}\s*,\s*"
                                 r"(?:may|must)-alias\)", hlo_text[i:j]))
    return {"donated": int(donated_leaves), "aliased": int(aliased)}


# --------------------------------------------------------------------------
# contract build / serialize / diff
# --------------------------------------------------------------------------

def build_contract(name: str, built) -> dict:
    """Extract the full contract dict for a BuiltEntry (see contracts.py)."""
    from . import precision_flow
    jax = _jax()
    closed = jax.make_jaxpr(built.fn)(*built.args)
    roles = getattr(built, "roles", None)
    if roles is None:
        roles = precision_flow.infer_roles(built.args)
    contract = {
        "schema": SCHEMA,
        "entry": name,
        "primitives": primitive_histogram(closed),
        "promotions": promotion_events(closed),
        "transfers": transfer_sites(closed),
        "collectives": [],
        "donation": None,
        "memory": peak_memory_estimate(closed),
        "precision": precision_flow.analyze(closed, roles).boundary,
        "vmem": built.vmem,
    }
    if built.compile:
        jitted = built.fn if hasattr(built.fn, "lower") else jax.jit(built.fn)
        hlo = jitted.lower(*built.args).compile().as_text()
        contract["collectives"] = collective_inventory(hlo, built.mesh)
        if built.donated:
            contract["donation"] = donation_report(hlo, built.donated)
    return contract


def save_contract(contract: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(contract, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_contract(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f} MB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KB"
    return f"{n} B"


def _keyed(events: Iterable[dict], keys: Sequence[str]) -> Dict[tuple, dict]:
    return {tuple(e[k] for k in keys): e for e in events}


def _diff_events(old, new, keys, render) -> List[str]:
    o, n = _keyed(old, keys), _keyed(new, keys)
    lines = []
    for k in sorted(set(o) | set(n), key=str):
        oe, ne = o.get(k), n.get(k)
        oc = (oe or {}).get("count", 0)
        nc = (ne or {}).get("count", 0)
        if oc == nc:
            # count-stable but byte-volume drift (an upcast moved from a
            # small tensor to a big one at the same site keeps count==1) —
            # only for event kinds whose bytes are NOT part of the key
            ob = (oe or {}).get("bytes")
            nb = (ne or {}).get("bytes")
            if oe and ne and "bytes" not in keys and ob is not None \
                    and ob != nb:
                lines.append(f"~ {render(ne)} [bytes {_fmt_bytes(ob)} -> "
                             f"{_fmt_bytes(nb)}]")
            continue
        ev = ne or oe
        sign = nc - oc
        lines.append(f"{'+' if sign > 0 else ''}{sign} {render(ev)}"
                     f" [{oc} -> {nc}]")
    return lines


def diff_contracts(old: dict, new: dict) -> Dict[str, List[str]]:
    """Per-rule human-readable drift lines; empty dict == no drift."""
    out: Dict[str, List[str]] = {}

    prim = []
    po, pn = old.get("primitives", {}), new.get("primitives", {})
    for name in sorted(set(po) | set(pn)):
        a, b = po.get(name, 0), pn.get(name, 0)
        if a != b:
            prim.append(f"{name}: {a} -> {b} ({b - a:+d})")
    if prim:
        out["primitives"] = prim

    coll = _diff_events(
        old.get("collectives", []), new.get("collectives", []),
        ("kind", "bytes", "axes"),
        lambda e: f"{e['kind']} {_fmt_bytes(e['bytes'])} on axis "
                  f"'{e['axes']}'")
    if coll:
        out["collectives"] = coll

    prom = _diff_events(
        old.get("promotions", []), new.get("promotions", []),
        ("src", "dst", "site"),
        lambda e: f"promotion {e['src']}->{e['dst']} "
                  f"({_fmt_bytes(e['bytes'])}) at {e['site']}")
    if prom:
        out["promotions"] = prom

    tr = _diff_events(
        old.get("transfers", []), new.get("transfers", []),
        ("primitive", "site"),
        lambda e: f"host transfer {e['primitive']} at {e['site']}")
    if tr:
        out["transfers"] = tr

    om = old.get("memory", {}).get("peak_bytes_est", 0)
    nm = new.get("memory", {}).get("peak_bytes_est", 0)
    if om and abs(nm - om) > om * MEMORY_RTOL:
        out["memory"] = [
            f"peak est {_fmt_bytes(om)} -> {_fmt_bytes(nm)} "
            f"({(nm - om) / om:+.1%}, tol {MEMORY_RTOL:.0%})"]

    # precision: the quantization boundary map (graftnum,
    # analysis/precision_flow.py) — which matmuls consume int8 and at what
    # accumulator width, where dequants happen and which axes their
    # per-channel scales ride, plus the value-class histogram
    po, pn = old.get("precision") or {}, new.get("precision") or {}
    prec: List[str] = []
    co, cn = po.get("class_counts", {}), pn.get("class_counts", {})
    for cls in sorted(set(co) | set(cn)):
        a, b = co.get(cls, 0), cn.get(cls, 0)
        if a != b:
            prec.append(f"value class {cls}: {a} -> {b} ({b - a:+d})")
    prec += _diff_events(
        po.get("int8_dots", []), pn.get("int8_dots", []),
        ("site", "accum"),
        lambda e: f"int8 dot (accum '{e['accum']}') at {e['site']}")
    prec += _diff_events(
        po.get("dequants", []), pn.get("dequants", []),
        ("site", "dst", "scale_axes"),
        lambda e: f"dequant ->{e['dst']} (scale axes {e['scale_axes']}) "
                  f"at {e['site']}")
    if prec:
        out["precision"] = prec
    return out


# --------------------------------------------------------------------------
# waivers
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Waiver:
    rule: str
    reason: str
    line: int


def collect_waivers(source_rel: str,
                    repo_root: Optional[str] = None
                    ) -> Tuple[Dict[str, Waiver], List[str]]:
    """(waivers by rule, problems) from REAL comment tokens of ``source_rel``.
    A waiver must carry a reason (``-- why``); a bare allow is a problem, as
    is an unknown rule name — both would otherwise silently waive nothing or
    the wrong thing. ``repo_root`` resolves lazily so tests can monkeypatch
    the module's ``REPO_ROOT``."""
    path = os.path.join(repo_root or REPO_ROOT, source_rel)
    waivers: Dict[str, Waiver] = {}
    problems: List[str] = []
    if not os.path.exists(path):
        return waivers, problems
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError):
        return waivers, problems
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _WAIVER_RE.search(tok.string)
        if not m:
            continue
        rule, reason = m.group(1), (m.group(2) or "").strip()
        if rule not in RULES:
            problems.append(f"{source_rel}:{tok.start[0]}: unknown graftir "
                            f"rule '{rule}' in waiver (known: "
                            f"{', '.join(RULES)})")
            continue
        if not reason:
            problems.append(f"{source_rel}:{tok.start[0]}: graftir waiver "
                            f"for '{rule}' has no reason — write "
                            f"'# graftir: allow={rule} -- <why>'")
            continue
        waivers[rule] = Waiver(rule, reason, tok.start[0])
    return waivers, problems


# --------------------------------------------------------------------------
# audit orchestration (used by the CLI and the tests)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class EntryReport:
    name: str
    drift: Dict[str, List[str]]          # rule -> lines (unwaived)
    waived: Dict[str, List[str]]         # rule -> lines (suppressed)
    problems: List[str]                  # waiver syntax issues etc.
    updated: bool = False

    @property
    def failed(self) -> bool:
        return bool(self.drift or self.problems)


def contract_path(contracts_dir: str, name: str) -> str:
    return os.path.join(contracts_dir, f"{name}.json")


def audit_entry(name: str, spec, contracts_dir: str, *, update: bool = False,
                repo_root: Optional[str] = None) -> Tuple[EntryReport, dict]:
    """Build the live contract for one registry entry, compare (or rewrite)
    its golden, apply waivers. Returns (report, live contract)."""
    built = spec.build()
    live = build_contract(name, built)
    waivers, problems = collect_waivers(spec.source, repo_root)

    drift: Dict[str, List[str]] = {}
    waived: Dict[str, List[str]] = {}

    # donation is an invariant, not a golden: every donated leaf aliased
    don = live.get("donation")
    if don is not None and don["aliased"] < don["donated"]:
        line = (f"only {don['aliased']} of {don['donated']} donated buffers "
                "are aliased in the compiled executable — XLA is silently "
                "keeping the old state live")
        if "donation" in waivers:
            waived.setdefault("donation", []).append(
                f"{line} (waived: {waivers['donation'].reason})")
        else:
            drift["donation"] = [line]

    path = contract_path(contracts_dir, name)
    if update:
        save_contract(live, path)
        return EntryReport(name, drift, waived, problems, updated=True), live

    golden = load_contract(path)
    if golden is None:
        drift["missing"] = [f"no golden contract at {path} — run "
                            "scripts/ir_audit.py --update"]
        return EntryReport(name, drift, waived, problems), live

    for rule, lines in diff_contracts(golden, live).items():
        if rule in waivers:
            waived.setdefault(rule, []).extend(
                f"{ln} (waived: {waivers[rule].reason})" for ln in lines)
        else:
            drift[rule] = lines
    return EntryReport(name, drift, waived, problems), live


def render_report(reports: Sequence[EntryReport], sources: Dict[str, str],
                  scope: str) -> str:
    lines = []
    failed = [r for r in reports if r.failed]
    for r in reports:
        if not (r.drift or r.waived or r.problems):
            continue
        lines.append(f"{r.name} ({sources.get(r.name, '?')}):")
        for rule, ls in sorted(r.drift.items()):
            for ln in ls:
                lines.append(f"  {rule}: {ln}")
        for rule, ls in sorted(r.waived.items()):
            for ln in ls:
                lines.append(f"  {rule} [waived]: {ln}")
        for p in r.problems:
            lines.append(f"  waiver-problem: {p}")
    n = len(failed)
    if n:
        lines.append(f"graftir: contract drift in {n} "
                     f"entr{'y' if n == 1 else 'ies'} ({scope})")
        lines.append("intentional change? regenerate with "
                     "scripts/ir_audit.py --update and commit the diff")
    else:
        lines.append(f"graftir: contracts clean ({scope})")
    return "\n".join(lines)


def explain(contract: dict) -> str:
    """Pretty-print one contract (the --explain CLI path)."""
    c = contract
    lines = [f"entry: {c['entry']}", "primitives:"]
    for name, count in sorted(c["primitives"].items(),
                              key=lambda kv: (-kv[1], kv[0])):
        lines.append(f"  {count:5d}  {name}")
    for key, render in (
            ("collectives", lambda e: f"{e['count']}x {e['kind']} "
                                      f"{_fmt_bytes(e['bytes'])} on axis "
                                      f"'{e['axes']}'"),
            ("promotions", lambda e: f"{e['count']}x {e['src']}->{e['dst']} "
                                     f"{_fmt_bytes(e['bytes'])} at "
                                     f"{e['site']}"),
            ("transfers", lambda e: f"{e['count']}x {e['primitive']} at "
                                    f"{e['site']}")):
        lines.append(f"{key}:")
        if not c.get(key):
            lines.append("  (none)")
        for e in c.get(key) or []:
            lines.append(f"  {render(e)}")
    prec = c.get("precision") or {}
    lines.append("precision:")
    cc = prec.get("class_counts", {})
    if cc:
        lines.append("  classes: " + ", ".join(
            f"{k}={v}" for k, v in sorted(cc.items())))
    for e in prec.get("int8_dots") or []:
        lines.append(f"  {e['count']}x int8 dot (accum '{e['accum']}') at "
                     f"{e['site']}")
    for e in prec.get("dequants") or []:
        lines.append(f"  {e['count']}x dequant ->{e['dst']} (scale axes "
                     f"{e['scale_axes']}) at {e['site']}")
    if not prec:
        lines.append("  (none)")
    mem = c.get("memory", {})
    lines.append(f"memory: peak est {_fmt_bytes(mem.get('peak_bytes_est', 0))}"
                 f" (args {_fmt_bytes(mem.get('arg_bytes', 0))}, outputs "
                 f"{_fmt_bytes(mem.get('out_bytes', 0))})")
    don = c.get("donation")
    if don:
        lines.append(f"donation: {don['aliased']}/{don['donated']} donated "
                     "buffers aliased")
    if c.get("vmem"):
        lines.append(f"vmem: {json.dumps(c['vmem'], sort_keys=True)}")
    return "\n".join(lines)
