"""Rule registry, per-file context, suppression comments, and the runner.

Two rule kinds:
  * :class:`Rule` — checked once per file (AST + source in a
    :class:`FileContext`); scoped by repo-relative path prefixes.
  * :class:`ProjectRule` — checked once per run against the whole file set
    (cross-file invariants: estimator/ceiling drift, test coverage).

Suppression: ``# graftlint: disable=rule-a,rule-b`` on the finding's line or
the line directly above it silences those rules for that line. There is no
file-level or repo-level disable on purpose — a suppression should sit next
to the code it excuses, where review sees both.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import subprocess
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# default lint surface: library + entry points. tests/ are read by
# project rules (coverage) but not file-linted — test code legitimately
# hard-codes keys and catches broadly around expected failures.
DEFAULT_ROOTS = ("dalle_tpu", "scripts")

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([\w\-,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # repo-relative, '/'-separated
    line: int        # 1-indexed
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class FileContext:
    """Parsed view of one source file: AST, raw lines, suppressions."""

    def __init__(self, rel_path: str, source: str):
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel_path)
        # suppressions come from REAL comment tokens, not raw line text — a
        # string that merely quotes the directive must not open a silent
        # false-negative hole on its line
        self._suppressed: Dict[int, Tuple[str, ...]] = {}
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
                self._suppressed[tok.start[0]] = rules

    def is_suppressed(self, line: int, rule: str) -> bool:
        for at in (line, line - 1):
            rules = self._suppressed.get(at)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


class Rule:
    """Per-file rule. Subclasses set ``name``/``description``/``include``
    and implement :meth:`check`."""

    name: str = ""
    description: str = ""
    # repo-relative path prefixes this rule applies to (tuple of str)
    include: Tuple[str, ...] = DEFAULT_ROOTS
    exclude: Tuple[str, ...] = ()

    def applies_to(self, rel_path: str) -> bool:
        if not any(rel_path.startswith(p) for p in self.include):
            return False
        return not any(rel_path.startswith(p) for p in self.exclude)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    # final, suppression-aware entry point used by the runner
    def run(self, ctx: FileContext) -> List[Finding]:
        if not self.applies_to(ctx.rel_path):
            return []
        return [f for f in self.check(ctx)
                if not ctx.is_suppressed(f.line, self.name)]


class ProjectRule(Rule):
    """Whole-project rule. ``check_project`` receives every in-scope
    FileContext plus the repo root being linted; per-file ``check`` is
    unused."""

    # which changed paths make this rule worth re-running in --changed-only
    triggers: Tuple[str, ...] = DEFAULT_ROOTS

    def check_project(self, ctxs: Sequence[FileContext],
                      repo_root: str) -> Iterable[Finding]:
        raise NotImplementedError

    def run_project(self, ctxs: Sequence[FileContext],
                    repo_root: str = REPO_ROOT) -> List[Finding]:
        by_path = {c.rel_path: c for c in ctxs}
        out = []
        for f in self.check_project(ctxs, repo_root):
            ctx = by_path.get(f.path)
            if ctx is not None and ctx.is_suppressed(f.line, self.name):
                continue
            out.append(f)
        return out


RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and add to the registry."""
    inst = cls()
    assert inst.name and inst.name not in RULES, f"bad rule {cls}"
    RULES[inst.name] = inst
    return cls


def to_sarif(findings: Sequence[Finding], tool_name: str,
             rules: Dict[str, str]) -> dict:
    """SARIF 2.1.0 document for ``findings`` — the format GitHub code
    scanning ingests to annotate PR diffs. ``rules`` maps rule name ->
    one-line description (the registry's descriptions)."""
    used = sorted({f.rule for f in findings})
    return {
        "version": "2.1.0",
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "rules": [{"id": r,
                           "shortDescription": {"text": rules.get(r, r)}}
                          for r in used],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                }}],
            } for f in findings],
        }],
    }


def iter_repo_files(roots: Sequence[str] = DEFAULT_ROOTS,
                    repo_root: str = REPO_ROOT) -> List[str]:
    """Repo-relative paths of every .py file under ``roots``."""
    out = []
    for root in roots:
        base = os.path.join(repo_root, root)
        if os.path.isfile(base) and base.endswith(".py"):
            out.append(os.path.relpath(base, repo_root))
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(os.path.join(dirpath, fn),
                                               repo_root))
    return sorted(p.replace(os.sep, "/") for p in out)


def changed_files(repo_root: str = REPO_ROOT) -> List[str]:
    """Repo-relative .py paths touched vs HEAD (staged, unstaged, untracked).

    Deleted paths are INCLUDED: they no longer exist to file-lint (and are
    naturally absent from the walked file set), but they must still fire
    project-rule triggers — deleting a test file is exactly how ops lose
    coverage.

    Renames (``R<score>`` status with rename detection) contribute BOTH
    sides: the new path is the lintable file, the old path fires
    project-rule triggers exactly like a deletion. ``--name-only`` output
    lists only the PRE-image of a rename, so a renamed file's new content
    would silently go unlinted.

    Raises on git failure: treating "git broke" as "nothing changed" would
    make --changed-only print 0 findings and exit green having linted
    nothing — the same silent-hole the CLI hard-errors unknown --select
    names to avoid."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-status", "-M", "HEAD"],
            cwd=repo_root, capture_output=True, text=True, check=True).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo_root, capture_output=True, text=True, check=True).stdout
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        raise RuntimeError(
            f"--changed-only cannot determine changed files (git failed: "
            f"{e}); run the full lint instead") from e
    paths = set()
    for line in diff.splitlines():
        fields = line.split("\t")
        if len(fields) < 2:
            continue
        # "M\tpath", "D\tpath", "R095\told\tnew", "C080\tsrc\tdst"
        paths.update(f.strip() for f in fields[1:] if f.strip())
    paths.update(p.strip() for p in untracked.splitlines() if p.strip())
    return sorted(p for p in paths if p.endswith(".py"))


def load_context(rel_path: str, repo_root: str = REPO_ROOT) -> Optional[FileContext]:
    with open(os.path.join(repo_root, rel_path), encoding="utf-8") as fh:
        src = fh.read()
    try:
        return FileContext(rel_path, src)
    except SyntaxError:
        return None  # a syntax error is the compiler's finding, not ours


def run_lint(paths: Optional[Sequence[str]] = None,
             select: Optional[Sequence[str]] = None,
             ignore: Optional[Sequence[str]] = None,
             changed_only: bool = False,
             repo_root: str = REPO_ROOT) -> List[Finding]:
    """Lint ``paths`` (repo-relative; default: the standard roots).

    ``changed_only`` narrows file rules to git-changed files; project rules
    still run when any of their trigger paths changed (they are cross-file
    invariants — a partial view would produce false positives).
    """
    rules = [r for r in RULES.values()
             if (select is None or r.name in select)
             and (ignore is None or r.name not in ignore)]
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]

    default_paths = iter_repo_files(repo_root=repo_root)
    lint_paths = list(paths) if paths is not None else list(default_paths)
    changed: Optional[List[str]] = None
    if changed_only:
        changed = changed_files(repo_root)
        lint_paths = [p for p in lint_paths if p in set(changed)]

    findings: List[Finding] = []
    ctxs: List[FileContext] = []
    for p in lint_paths:
        ctx = load_context(p, repo_root)
        if ctx is None:
            findings.append(Finding("parse-error", p, 1, "file does not parse"))
            continue
        ctxs.append(ctx)
        for rule in file_rules:
            findings.extend(rule.run(ctx))

    # project rules ALWAYS see the full in-scope file set — a partial view
    # (explicit paths or changed-only) would miss cross-file drift and
    # misattribute findings; loaded lazily, once for all of them
    full: Optional[List[FileContext]] = (
        ctxs if lint_paths == default_paths else None)
    for rule in project_rules:
        if changed is not None and not any(
                p.startswith(rule.triggers) for p in changed):
            continue
        if full is None:
            full = [c for c in (load_context(p, repo_root)
                                for p in default_paths) if c is not None]
        findings.extend(rule.run_project(full, repo_root))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
