"""hardcoded-dtype: dtype literals that bypass the precision plumbing.

The AST-level companion of the graftnum precision-flow audit
(:mod:`dalle_tpu.analysis.precision_flow`): that layer certifies the
*traced* program's precision discipline; this rule catches the source
pattern that silently pins a dtype before any config can reach it. The
repo's precision policy flows through explicit knobs — ``PrecisionConfig``
→ ``cast_floating`` for params/compute, ``cache_dtype`` for KV storage,
``quantize_params_int8`` for weights — so model/op code that hard-codes a
float dtype opts a tensor out of every one of those paths at once: a
``jnp.float32`` activation in a bf16 model silently re-widens everything
downstream, and a ``dtype="bfloat16"`` string survives refactors that
rename the real config field.

Three statically certain patterns (zero-false-positive contract, like the
other rules):

1. **String dtype literals** — ``dtype="bfloat16"`` (keyword, or
   positional in a known creator's dtype slot) anywhere in model/op code:
   stringly-typed precision that no config plumbing can see.
2. **jnp float scalar casts** — ``jnp.float32(x)`` / ``jnp.bfloat16(x)``:
   STRONG-typed scalars (the jnp twin of ``weak-type-promotion``'s numpy
   check) that widen/narrow whatever they touch regardless of the
   configured compute dtype.
3. **Float dtype literals in array creation inside nn.Module classes** —
   ``jnp.full(shape, v, jnp.float32)`` in a module body creates a tensor
   whose dtype no precision mode can change. Function-signature DEFAULTS
   are exempt (``dtype=jnp.float32`` as a default IS the config surface),
   as are integer/bool dtypes (token ids and masks are not precision
   knobs).

Scope: ``dalle_tpu/models`` + ``dalle_tpu/ops`` — the code the precision
modes transform. Deliberate pins (e.g. a param initializer that must stay
f32 to avoid weak-type retraces) carry a
``# graftlint: disable=hardcoded-dtype`` suppression next to the line,
with the why in the surrounding comment.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import dotted_name

_FLOAT_DTYPE_NAMES = set()
for _mod in ("jnp", "jax.numpy", "np", "numpy"):
    for _dt in ("float16", "float32", "float64", "bfloat16"):
        _FLOAT_DTYPE_NAMES.add(f"{_mod}.{_dt}")

_JNP_SCALAR_CTORS = {f"{m}.{d}" for m in ("jnp", "jax.numpy")
                     for d in ("float16", "float32", "float64", "bfloat16")}

_CREATORS_DTYPE_POS = {}
for _mod in ("jnp", "jax.numpy"):
    for _fn, _pos in (("zeros", 1), ("ones", 1), ("empty", 1),
                      ("full", 2), ("array", 1), ("asarray", 1)):
        _CREATORS_DTYPE_POS[f"{_mod}.{_fn}"] = _pos

_MODULE_BASES = {"nn.Module", "flax.linen.Module", "linen.Module"}


_FLOAT_DTYPE_STRS = {"float16", "float32", "float64", "bfloat16",
                     "f16", "f32", "f64", "bf16"}


def _float_dtype_literal(node: ast.AST) -> Optional[str]:
    """A float dtype pinned as a literal: the jnp/np attribute form OR a
    string constant naming one (positional ``jnp.zeros((4,), "bfloat16")``
    is the same bypass as the keyword form)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _FLOAT_DTYPE_STRS:
        return f'"{node.value}"'
    name = dotted_name(node)
    return name if name in _FLOAT_DTYPE_NAMES else None


def _default_nodes(tree: ast.Module) -> set:
    """ids of every AST node inside a function-signature default — a dtype
    default IS the configurable surface, not a bypass of it."""
    out = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            continue
        for d in list(fn.args.defaults) + [d for d in fn.args.kw_defaults
                                           if d is not None]:
            for sub in ast.walk(d):
                out.add(id(sub))
    return out


def _module_class_nodes(tree: ast.Module) -> List[ast.ClassDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef)
            and any(dotted_name(b) in _MODULE_BASES or
                    dotted_name(b).endswith(".Module") for b in n.bases)]


@register_rule
class HardcodedDtype(Rule):
    name = "hardcoded-dtype"
    description = ("dtype literal in model/op code bypasses the precision "
                   "plumbing (PrecisionConfig/cast_floating/cache_dtype) — "
                   "string dtypes, jnp float scalar casts, or float dtype "
                   "literals in nn.Module array creation")
    include = ("dalle_tpu/models", "dalle_tpu/ops")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        defaults = _default_nodes(ctx.tree)

        # 1 + 2: string dtype kwargs and jnp float scalar casts, anywhere
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in defaults:
                continue
            str_dtype = None
            for kw in node.keywords:
                if kw.arg == "dtype" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    str_dtype = kw.value.value
            pos = _CREATORS_DTYPE_POS.get(dotted_name(node.func))
            if str_dtype is None and pos is not None \
                    and len(node.args) > pos \
                    and isinstance(node.args[pos], ast.Constant) \
                    and isinstance(node.args[pos].value, str):
                str_dtype = node.args[pos].value     # positional string
            if str_dtype is not None:
                findings.append(Finding(
                    self.name, ctx.rel_path, node.lineno,
                    f'dtype="{str_dtype}" string literal — '
                    "stringly-typed precision no config plumbing can "
                    "see; thread the configured dtype object instead"))
            fname = dotted_name(node.func)
            if fname in _JNP_SCALAR_CTORS and node.args:
                findings.append(Finding(
                    self.name, ctx.rel_path, node.lineno,
                    f"{fname}() scalar cast is STRONG-typed and pins its "
                    "dtype regardless of the configured compute dtype — "
                    "use a Python literal (weak) or the incoming array's "
                    "dtype"))

        # 3: float dtype literals in array creation inside nn.Module bodies
        for cls in _module_class_nodes(ctx.tree):
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call) or id(node) in defaults:
                    continue
                fname = dotted_name(node.func)
                pos = _CREATORS_DTYPE_POS.get(fname)
                if pos is None:
                    continue
                dt = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dt = _float_dtype_literal(kw.value)
                if dt is None and len(node.args) > pos:
                    dt = _float_dtype_literal(node.args[pos])
                if dt is not None:
                    findings.append(Finding(
                        self.name, ctx.rel_path, node.lineno,
                        f"{fname}(..., {dt}) inside an nn.Module hard-pins "
                        "a float dtype no precision mode can change — "
                        "derive it from the input/config, or suppress with "
                        "the why if the pin is deliberate"))
        return findings
