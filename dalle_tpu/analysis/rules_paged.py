"""page-table-dynamic-shape: the page table must stay device DATA.

graftpage's no-recompile invariant rests on one property: the ``(B,
max_blocks)`` page table enters every serve program as an ordinary int32
array operand.  Block remaps, COW forks, and radix hits then change only
the VALUES flowing through a fixed executable.  The moment page-table
contents leak into Python — an ``int()`` on a table entry, a branch on
mapped-block values, a shape computed from them — the program signature
starts tracking admission state and every prefix-cache hit pattern
compiles its own executable (the exact failure the dense slab was paged
out to avoid: one program per occupancy layout).

Three statically certain leak shapes are flagged (same zero-false-positive
contract as the other rules — no dataflow inference, only syntax):

1. **Host conversion of page-table values** — ``int(pages[...])``,
   ``state["pages"].item()``, ``.tolist()``: a blocking device sync whose
   result is a Python scalar; one step from a shape or a static arg.
2. **Python control flow on page-table values** — ``if``/``while`` tests
   mentioning the table (``is None`` / ``is not None`` structure probes
   are exempt: they test which ENGINE is running, not which blocks are
   mapped, and resolve identically on every call).
3. **Page-table values in a shape position** — the table appearing inside
   the shape argument of ``jnp.zeros/ones/full/empty`` or a ``reshape``
   call.  ``pages.shape`` itself is fine (the table's OWN shape is static
   config); its element values are not.

Naming contract: the rule keys on the identifiers ``pages`` /
``page_table(s)`` / ``block_table(s)`` and the ``state["pages"]`` leaf.
Host-side numpy mirrors are deliberately exempt — keep the engine's
``_pages_host`` suffix convention so the mirror (where Python ints are
the whole point) stays visibly distinct from the device leaf.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register_rule

_PAGE_NAME = re.compile(r"^(pages|page_tables?|block_tables?)$")

# constructors whose first argument is a shape
_SHAPE_CTORS = {"zeros", "ones", "full", "empty"}


def _is_page_ref(node: ast.expr) -> bool:
    """``pages`` / ``self.pages`` / ``state["pages"]`` and friends."""
    if isinstance(node, ast.Name):
        return bool(_PAGE_NAME.match(node.id))
    if isinstance(node, ast.Attribute):
        return bool(_PAGE_NAME.match(node.attr))
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and isinstance(sl.value, str) \
            and bool(_PAGE_NAME.match(sl.value))
    return False


def _page_refs(node: ast.AST) -> List[ast.expr]:
    """Page-table references anywhere under ``node``, skipping subtrees
    rooted at ``<ref>.shape`` — the table's own (static) shape is fine."""
    out: List[ast.expr] = []

    def walk(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr == "shape" \
                and _is_page_ref(n.value):
            return                          # static-shape access: exempt
        if isinstance(n, ast.expr) and _is_page_ref(n):
            out.append(n)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return out


def _is_none_probe(test: ast.expr) -> bool:
    """``X is None`` / ``X is not None`` (possibly under not/and/or) —
    an engine-mode structure probe, not a value branch."""
    if isinstance(test, ast.BoolOp):
        return all(_is_none_probe(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_probe(test.operand)
    return (isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.IsNot))
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None)


@register_rule
class PageTableDynamicShape(Rule):
    name = "page-table-dynamic-shape"
    description = ("page-table values leaking into Python (int()/.item(), "
                   "branch tests, shape arguments) — the table must stay a "
                   "device array operand or every block layout compiles its "
                   "own serve program")
    include = ("dalle_tpu/ops/", "dalle_tpu/serve/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            findings.append(Finding(
                self.name, ctx.rel_path, node.lineno,
                f"{what} — page-table contents must stay device data; "
                "a Python-visible value here ties the program signature "
                "to the block layout and retraces per admission pattern"))

        for node in ast.walk(ctx.tree):
            # 1. host conversions: int()/float() of a page ref,
            #    <page ref>.item()/.tolist()
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name) and fn.id in ("int", "float") \
                        and len(node.args) == 1 \
                        and _page_refs(node.args[0]):
                    flag(node, f"{fn.id}() of page-table values")
                elif isinstance(fn, ast.Attribute) \
                        and fn.attr in ("item", "tolist") \
                        and _page_refs(fn.value):
                    flag(node, f".{fn.attr}() on page-table values")

            # 2. Python control flow on page-table values
            if isinstance(node, (ast.If, ast.While)) \
                    and not _is_none_probe(node.test) \
                    and _page_refs(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                flag(node, f"`{kind}` test reads page-table values")

            # 3. page-table values in a shape position
            if isinstance(node, ast.Call):
                fn = node.func
                shape_args: List[ast.expr] = []
                if isinstance(fn, ast.Attribute) and fn.attr == "reshape":
                    shape_args = list(node.args)
                elif isinstance(fn, ast.Attribute) \
                        and fn.attr in _SHAPE_CTORS and node.args:
                    shape_args = [node.args[0]]
                for arg in shape_args:
                    if _page_refs(arg):
                        flag(node, "page-table values in a shape argument")
                        break
        return findings
