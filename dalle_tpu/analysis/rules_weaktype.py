"""weak-type-promotion: scalar-typing hazards that flip jit signatures.

The AST complement to graftir's IR-level promotion audit. Two statically
certain patterns are flagged (dtype inference on arbitrary expressions is
not attempted — same zero-false-positive contract as the other rules):

1. **Weak-typed param initializers** — ``self.param("s", lambda k:
   jnp.full(shape, eps))``: ``jnp.full``/``jnp.array``/``jnp.asarray`` of a
   Python scalar without an explicit ``dtype=`` yields a WEAK-typed array.
   A weak-typed param flips to strong after one pass through a jitted step
   (outputs are strong), changing the input signature — every subsequent
   step call then recompiles the whole program. This exact pattern cost
   ~4-5 s per train_step on the layerscale params before it was found by
   the graftir retrace probe.

2. **Strong numpy scalars in jitted arithmetic** — ``x * np.float32(0.5)``
   inside a jitted function: numpy scalars are STRONG-typed in JAX's
   promotion lattice, so they silently widen bf16/f16 operands to f32
   (a Python literal is weak and preserves the array dtype).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .core import FileContext, Finding, Rule, register_rule
from .jit_scan import body_nodes, dotted_name, find_jit_functions

# constructors whose scalar-fill result is weak-typed without dtype=
_WEAK_CTORS = {"jnp.full", "jnp.array", "jnp.asarray",
               "jax.numpy.full", "jax.numpy.array", "jax.numpy.asarray"}

# numpy scalar types that are strong in the promotion lattice
_NP_STRONG = {"np.float16", "np.float32", "np.float64",
              "numpy.float16", "numpy.float32", "numpy.float64"}


def _certainly_weak_scalar(node: ast.expr, ctor: str) -> bool:
    """Value argument that is certainly a weak-typed Python scalar. Literal
    numbers (and their negations) always are. A bare Name is accepted for
    ``full`` only — a fill_value is overwhelmingly a scalar variable (the
    layerscale ``eps`` pattern this rule exists for), while ``array``/
    ``asarray`` of a Name is routinely a strong-typed ndarray (loaded
    weights). Calls/attributes are never flagged: ``np.float32(0.5)`` and
    friends construct STRONG-typed values."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _certainly_weak_scalar(node.operand, ctor)
    return ctor.endswith("full") and isinstance(node, ast.Name)


def _weak_ctor_call(node: ast.expr) -> Optional[str]:
    """Name of the weak-typed constructor if ``node`` is one without dtype."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name not in _WEAK_CTORS:
        return None
    if any(kw.arg == "dtype" for kw in node.keywords):
        return None
    # jnp.full(shape, fill); jnp.array(x) — the scalar rides arg 1 resp. 0,
    # and a positional dtype would be the NEXT arg
    value_pos, dtype_pos = (1, 2) if name.endswith("full") else (0, 1)
    if len(node.args) > dtype_pos:        # positional dtype given
        return None
    if len(node.args) <= value_pos \
            or not _certainly_weak_scalar(node.args[value_pos], name):
        return None
    return name


def _returns_of(fn: ast.AST):
    """Expressions a param initializer evaluates to (lambda body or returns)."""
    if isinstance(fn, ast.Lambda):
        yield fn.body
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                yield node.value


@register_rule
class WeakTypePromotion(Rule):
    name = "weak-type-promotion"
    description = ("weak-typed param initializer (jnp.full/array of a Python "
                   "scalar, no dtype) or strong numpy scalar in jitted "
                   "arithmetic — signature flips force per-step recompiles; "
                   "numpy scalars upcast bf16 to f32")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        local_defs = {n.name: n for n in ast.walk(ctx.tree)
                      if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

        # 1. weak-typed param initializers: *.param(name, init, ...)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "param" and len(node.args) >= 2):
                continue
            init = node.args[1]
            if isinstance(init, ast.Name):
                init = local_defs.get(init.id, init)
            for ret in _returns_of(init):
                ctor = _weak_ctor_call(ret)
                if ctor:
                    findings.append(Finding(
                        self.name, ctx.rel_path, ret.lineno,
                        f"param initializer builds a WEAK-typed array "
                        f"({ctor} of a Python scalar, no dtype=) — the param "
                        "flips to strong after one jitted step, changing the "
                        "input signature and recompiling the program on "
                        "every call; pass an explicit dtype"))

        # 2. strong numpy scalars in jitted arithmetic
        for info in find_jit_functions(ctx.tree):
            for node in body_nodes(info.func_node):
                if not isinstance(node, ast.BinOp):
                    continue
                for side in (node.left, node.right):
                    if (isinstance(side, ast.Call)
                            and dotted_name(side.func) in _NP_STRONG):
                        findings.append(Finding(
                            self.name, ctx.rel_path, node.lineno,
                            f"{dotted_name(side.func)}() scalar in jitted "
                            "arithmetic is STRONG-typed — it upcasts "
                            "bf16/f16 operands to its own dtype; use a "
                            "Python literal (weak, dtype-preserving) or a "
                            "jnp scalar of the array's dtype"))
        return findings
