"""graftwire — static wire-protocol + lifecycle model of the fleet RPC.

The fleet speaks a hand-grown protocol — ``submit``/``submit_group``/
``health``/``drain``/``telemetry`` verbs over length-prefixed JSON frames
(fleet/transport.py), a one-line JSON handshake (scripts/serve_replica.py
→ fleet/manager.py) and the gateway's SSE event stream — and bitwise-exact
failover depends on both endpoints agreeing on every field. No other
analysis layer sees across that socket: graftsync's model stops at the
process boundary, graftlint reads one call site. This module builds the
cross-process model:

  * **sent schemas** — every dict that goes onto the wire, recovered from
    the AST at the send sites (``send_frame(...)``/``call(...)``/
    ``sse_event(...)``/``print(json.dumps(...))``/``return`` for reply
    builders), including incrementally-built dicts (``h.update(ok=...)``,
    ``out["k"] = v``, ``setdefault``) and conditional ``**{...} if ...``
    spreads (optional fields). A dict fed from a call
    (``telemetry_payload(...)``) is *dynamic* — its full key set is not
    statically known and source-side rules soften accordingly.
  * **read schemas** — every ``msg.get("k")`` (soft) and ``msg["k"]``
    (hard) read of a wire message, attributed to its channel through the
    curated :data:`ENDPOINTS` map (which variables in which functions ARE
    wire messages, and of which verb/direction).
  * **channels** — the (verb × direction) join of the two, with stream
    verbs split per ``kind`` sub-channel; :data:`CHANNEL_POLICY` marks
    reflective channels (health/telemetry replies, the operator-facing
    handshake line, SSE) whose receivers are deliberately open-ended.
  * **verb dispatch** — verbs sent (``{"verb": ...}`` request dicts) vs
    verbs dispatched (``verb == "submit"`` comparisons against a name
    bound from ``msg.get("verb")``): an asymmetry is an orphan.
  * **lifecycle machines** — the request and replica state machines
    (:data:`LIFECYCLES`, both acyclic) plus the :data:`EVENT_EDGES` map
    from every ``record_event`` name emitted in the wire roots to its
    declared transition(s); an emission the map can't place is a finding.

The model is pure AST — no imports of the analyzed code. Rules live in
:mod:`dalle_tpu.analysis.rules_wire`; the CLI is ``scripts/wire_audit.py``
(golden protocol contract in ``contracts/wire.json``); the runtime half is
:mod:`dalle_tpu.obs.wiretap` (an opt-in frame tap in fleet/transport.py —
the smokes assert every observed frame ⊆ this golden). Waivers are source
comments on the finding's line or the line above::

    # graftwire: allow=wire-field-unread -- <reason>

A waiver without a reason, or naming an unknown rule, is itself a finding.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import (Dict, FrozenSet, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from .core import REPO_ROOT, iter_repo_files
from .jit_scan import dotted_name

# every package that puts bytes on (or takes bytes off) the fleet wire
WIRE_ROOTS = ("dalle_tpu/fleet", "dalle_tpu/gateway", "dalle_tpu/serve",
              "scripts/serve_replica.py")

_WAIVER_RE = re.compile(r"#\s*graftwire:\s*allow=([\w\-]+)(?:\s*--\s*(.*))?")

# calls whose argument (by index) is a dict that goes onto the wire
_SEND_CALLS = {"send_frame": 1, "call": 1, "sse_event": 1,
               "_open_stream": 0}


# --------------------------------------------------------------------------
# extracted facts
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SentDict:
    """One dict observed at a send site, classified onto a channel."""
    verb: str
    direction: str              # request | reply | stream
    kind: Optional[str]         # stream sub-kind ("*" when not constant)
    fields: FrozenSet[str]
    optional: FrozenSet[str]    # conditional-spread keys
    dynamic: bool               # fed from a call / non-constant update
    site: str                   # path::qualname
    line: int


@dataclasses.dataclass(frozen=True)
class FieldRead:
    verb: str
    direction: str
    kind: Optional[str]         # stream sub-kind; None = kind-agnostic
    field: str
    hard: bool                  # subscript (KeyError on absence) vs .get
    site: str
    line: int


@dataclasses.dataclass(frozen=True)
class EventEmit:
    name: str
    site: str
    line: int


@dataclasses.dataclass(frozen=True)
class VerbUse:
    verb: str
    site: str
    line: int


# --------------------------------------------------------------------------
# endpoint map: which functions touch the wire, and in which role
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Send:
    """This function sends on (verb, direction). Dicts captured at send
    calls are classified here; ``returns=True`` additionally captures
    ``return <dict>`` (reply-builder helpers like ``_health``)."""
    verb: str
    direction: str
    returns: bool = False


@dataclasses.dataclass(frozen=True)
class Recv:
    """In this function, reads of the named variables are reads of a
    (verb, direction) wire message. ``kind`` narrows a stream read to one
    sub-channel (None = the reader sees every kind)."""
    verb: str
    direction: str
    vars: Tuple[str, ...]
    kind: Optional[str] = None


_T = "dalle_tpu/fleet/transport.py"
_M = "dalle_tpu/fleet/manager.py"
_C = "dalle_tpu/fleet/controller.py"
_R = "dalle_tpu/gateway/replica.py"
_G = "dalle_tpu/gateway/server.py"
_S = "scripts/serve_replica.py"

_ALL_VERBS = ("submit", "submit_group", "health", "drain", "telemetry")

# path::qualname -> endpoint specs. This is the curated half of the model:
# the extractor recovers field sets generically, but WHICH variable is a
# wire message (and on which channel) is a protocol fact, pinned here.
ENDPOINTS: Dict[str, Tuple[object, ...]] = {
    # -- client (RemoteReplica) -------------------------------------------
    f"{_T}::RemoteReplica.__init__": (
        Recv("health", "reply", ("first",)),),
    f"{_T}::RemoteReplica._observe_clock": (
        Recv("health", "reply", ("reply",)),
        Recv("telemetry", "reply", ("reply",)),),
    f"{_T}::RemoteReplica._track_progress": (
        Recv("health", "reply", ("h",)),),
    f"{_T}::RemoteReplica.healthy": (
        Recv("health", "reply", ("self._last_health",)),),
    f"{_T}::RemoteReplica.load": (
        Recv("health", "reply", ("h",)),),
    f"{_T}::RemoteReplica._open_stream": (
        Recv("submit", "reply", ("ack",)),
        Recv("submit_group", "reply", ("ack",)),
        Recv("any", "reply", ("ack",)),),
    f"{_T}::RemoteReplica.migrate": (
        Recv("drain", "reply", ("reply",)),),
    f"{_T}::RemoteCompletion.__init__": (
        Recv("submit", "stream", ("frame",), kind="done"),
        Recv("submit_group", "stream", ("frame",), kind="done"),),
    f"{_T}::RemoteResultStream.events": (
        Recv("submit", "stream", ("frame",)),),
    f"{_T}::RemoteGroupStream.events": (
        Recv("submit_group", "stream", ("frame",)),),
    # -- server (ReplicaServer) -------------------------------------------
    f"{_T}::ReplicaServer._serve_conn": (
        Send("any", "reply"),
        *(Recv(v, "request", ("msg",)) for v in _ALL_VERBS)),
    f"{_T}::ReplicaServer._health": (
        Send("health", "reply", returns=True),),
    f"{_T}::ReplicaServer._telemetry": (
        Send("telemetry", "reply", returns=True),
        Recv("telemetry", "request", ("msg",)),),
    f"{_T}::ReplicaServer._submit_kwargs": (
        Recv("submit", "request", ("msg",)),
        Recv("submit_group", "request", ("msg",)),),
    f"{_T}::ReplicaServer._handle_submit": (
        Send("submit", "reply"), Send("submit", "stream"),
        Recv("submit", "request", ("msg",)),),
    f"{_T}::ReplicaServer._handle_group": (
        Send("submit_group", "reply"), Send("submit_group", "stream"),
        Recv("submit_group", "request", ("msg",)),),
    f"{_T}::ReplicaServer._failed_frame": (
        Send("submit", "stream", returns=True),
        Send("submit_group", "stream", returns=True),),
    f"{_T}::ReplicaServer._handle_drain": (
        Send("drain", "reply"),
        Recv("drain", "request", ("msg",)),),
    # -- handshake (stdout JSON line, not a frame) ------------------------
    f"{_S}::main": (
        Send("handshake", "reply"),),
    f"{_M}::FleetManager.spawn": (
        Recv("handshake", "reply", ("shake",)),),
    f"{_C}::FleetController._attach_fresh": (
        Recv("handshake", "reply", ("rp.handshake",)),),
    # -- controller-side health-reply consumers ---------------------------
    f"{_C}::FleetController._degraded": (
        Recv("health", "reply", ("health",)),),
    # -- in-process replica: the OTHER sender of the health-reply body ----
    f"{_R}::Replica.health": (
        Send("health", "reply", returns=True),),
    f"{_R}::classify_failure": (
        Recv("submit", "stream", ("payload",), kind="replica_failed"),
        Recv("submit_group", "stream", ("payload",),
             kind="replica_failed"),),
    # -- gateway SSE (server-push to browsers; no in-repo receiver) -------
    f"{_G}::_make_handler.Handler._stream": (
        Send("sse", "stream"),),
    f"{_G}::_make_handler.Handler._images_stream": (
        Send("sse", "stream"),),
}

# (verb, direction, kind-or-None) -> why the receiver side is deliberately
# open-ended. Open channels skip wire-field-unread (their consumers are
# reflective: dict-merging health(), the telemetry collector, operators
# reading the handshake line in CI logs, browsers on SSE) — drift on them
# is still caught by the golden contract, field by field.
CHANNEL_POLICY: Dict[Tuple[str, str, Optional[str]], str] = {
    ("health", "reply", None):
        "reflective consumers: RemoteReplica.health() merges the whole "
        "dict; smokes/operators read fields the controller never does",
    ("telemetry", "reply", None):
        "the graftlens collector consumes the whole snapshot generically",
    ("handshake", "reply", None):
        "operator-facing JSON line (CI logs, smokes) beyond the fields "
        "the manager reads",
    ("any", "reply", None):
        "the unknown-verb error ack; every single-verb client may see it",
    ("drain", "reply", None):
        "fire-and-forget ack: drain() discards the body by design "
        "(migrate() reads 'migrated')",
    ("sse", "stream", None):
        "server-push to HTTP clients; the receivers live in browsers",
    ("submit", "stream", "shed"):
        "the router synthesizes its own shed error without reading the "
        "frame body",
    ("submit_group", "stream", "shed"):
        "the router synthesizes its own shed error without reading the "
        "frame body",
    ("submit", "stream", "replica_failed"):
        "classify_failure reads only 'reason'; the router forwards the "
        "whole payload into the failover event detail",
    ("submit_group", "stream", "replica_failed"):
        "classify_failure reads only 'reason'; the router forwards the "
        "whole payload into the failover event detail",
}


def channel_open(verb: str, direction: str, kind: Optional[str]) -> bool:
    return ((verb, direction, kind) in CHANNEL_POLICY
            or (kind is not None
                and (verb, direction, None) in CHANNEL_POLICY))


# --------------------------------------------------------------------------
# lifecycle state machines (both ACYCLIC — a request/replica never returns
# to an earlier state; re-admission after failover is its own state)
# --------------------------------------------------------------------------

LIFECYCLES: Dict[str, Dict[str, Tuple]] = {
    "request": {
        "states": ("submitted", "admitted", "prefill", "decode", "done",
                   "shed", "failed", "readmitted"),
        "edges": (("submitted", "admitted"), ("submitted", "shed"),
                  ("admitted", "prefill"), ("admitted", "shed"),
                  ("prefill", "decode"), ("decode", "done"),
                  ("decode", "shed"), ("decode", "failed"),
                  ("failed", "readmitted")),
    },
    "replica": {
        "states": ("spawned", "attached", "serving", "draining", "wedged",
                   "dead"),
        "edges": (("spawned", "attached"), ("attached", "serving"),
                  ("serving", "draining"), ("serving", "wedged"),
                  ("serving", "dead"), ("wedged", "draining"),
                  ("draining", "dead")),
    },
}

# record_event name -> declared transition(s) it witnesses; () marks a
# deliberately non-lifecycle event (quality gauges, control-loop errors).
# An emission in the wire roots that is absent here — or maps to an edge
# its machine does not declare — is an undeclared-lifecycle-transition.
EVENT_EDGES: Dict[str, Tuple[Tuple[str, str, str], ...]] = {
    # request lifecycle
    "request_submitted": (("request", "submitted", "admitted"),),
    "images_submitted": (("request", "submitted", "admitted"),),
    "request_rejected": (("request", "submitted", "shed"),),
    "request_admitted": (("request", "admitted", "prefill"),),
    "request_completed": (("request", "decode", "done"),),
    "request_shed": (("request", "admitted", "shed"),
                     ("request", "decode", "shed")),
    "failover": (("request", "decode", "failed"),
                 ("request", "failed", "readmitted")),
    # replica lifecycle
    "replica_spawned": (("replica", "spawned", "attached"),),
    "replica_killed": (("replica", "serving", "dead"),
                       ("replica", "draining", "dead")),
    "replica_heartbeat_lost": (("replica", "serving", "dead"),),
    "replica_progress_stalled": (("replica", "serving", "wedged"),),
    "replica_wedged": (("replica", "serving", "wedged"),),
    "replica_failed": (("replica", "serving", "dead"),),
    "replica_migrate": (("replica", "serving", "draining"),
                        ("replica", "wedged", "draining")),
    # non-lifecycle telemetry
    "decode_quality": (),
    "replica_unreaped": (),
    "warm_refill_failed": (),
    "fleet_action": (),
    "fleet_tick_error": (),
}


def lifecycle_cycles(machines: Optional[Dict] = None) -> List[List[str]]:
    """Cycles in the declared machines (each as a state list); the
    contract requires both machines acyclic, and the smokes re-assert it
    against the shipped golden."""
    out: List[List[str]] = []
    for name, machine in sorted((machines or LIFECYCLES).items()):
        adj: Dict[str, List[str]] = {}
        for src, dst in machine["edges"]:
            adj.setdefault(src, []).append(dst)
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(node: str) -> None:
            color[node] = 1
            stack.append(node)
            for nxt in adj.get(node, []):
                if color.get(nxt, 0) == 1:
                    out.append([name] + stack[stack.index(nxt):] + [nxt])
                elif color.get(nxt, 0) == 0:
                    dfs(nxt)
            stack.pop()
            color[node] = 2

        for state in sorted(adj):
            if color.get(state, 0) == 0:
                dfs(state)
    return out


# --------------------------------------------------------------------------
# per-function extraction
# --------------------------------------------------------------------------

def _recv_name(node: ast.AST) -> str:
    """Dotted receiver name; sees through ``(ack or {})``."""
    if isinstance(node, ast.BoolOp) and node.values:
        return dotted_name(node.values[0])
    return dotted_name(node)


class _DictShape:
    """Statically-known shape of one dict value."""

    def __init__(self) -> None:
        self.fields: Set[str] = set()
        self.optional: Set[str] = set()
        self.dynamic = False
        self.verb_const: Optional[str] = None
        self.kind_const: Optional[str] = None

    def merge_literal(self, node: ast.Dict) -> "_DictShape":
        for key, val in zip(node.keys, node.values):
            if key is None:                       # ** spread
                self._merge_spread(val)
            elif isinstance(key, ast.Constant) and isinstance(key.value,
                                                              str):
                self.fields.add(key.value)
                if isinstance(val, ast.Constant) and isinstance(val.value,
                                                                str):
                    if key.value == "verb":
                        self.verb_const = val.value
                    elif key.value == "kind":
                        self.kind_const = val.value
            else:
                self.dynamic = True               # computed key
        return self

    def _merge_spread(self, val: ast.AST) -> None:
        if isinstance(val, ast.Dict):
            sub = _DictShape().merge_literal(val)
            self.fields |= sub.fields
            self.optional |= sub.optional
            self.dynamic |= sub.dynamic
        elif isinstance(val, ast.IfExp):
            # **({...} if cond else {...}): keys of either arm are
            # conditionally present — optional on the wire
            for branch in (val.body, val.orelse):
                if isinstance(branch, ast.Dict):
                    sub = _DictShape().merge_literal(branch)
                    self.optional |= sub.fields | sub.optional
                    self.dynamic |= sub.dynamic
                else:
                    self.dynamic = True
        else:
            self.dynamic = True                   # **payload


class _FuncWalker:
    """Ordered walk of one function body: tracked var-dicts, send-site
    captures, wire-message reads, verb dispatch, record_event emissions."""

    def __init__(self, path: str, qualname: str, node: ast.AST,
                 specs: Tuple[object, ...], collect_nested) -> None:
        self.path = path
        self.qualname = qualname
        self.site = f"{path}::{qualname}"
        self.sends = tuple(s for s in specs if isinstance(s, Send))
        self.recvs = tuple(s for s in specs if isinstance(s, Recv))
        self.collect_nested = collect_nested
        self.var_dicts: Dict[str, _DictShape] = {}
        self.verb_vars: Set[str] = set()          # names bound from
        self.raw_reads: List[Tuple[str, str, bool, int]] = []
        self.sent: List[SentDict] = []
        self.sent_verbs: List[VerbUse] = []
        self.dispatched: List[VerbUse] = []
        self.events: List[EventEmit] = []
        for stmt in node.body:
            self._walk(stmt)

    # -- shape resolution --------------------------------------------------

    def _shape_of(self, node: ast.AST) -> Optional[_DictShape]:
        if isinstance(node, ast.Dict):
            return _DictShape().merge_literal(node)
        if isinstance(node, ast.Name):
            return self.var_dicts.get(node.id)
        return None

    # -- the ordered walk --------------------------------------------------

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.collect_nested(f"{self.qualname}.{node.name}", node)
            return
        if isinstance(node, ast.ClassDef):
            # a class defined inside a function (the gateway's request
            # Handler): keep the class name in the qualname
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.collect_nested(
                        f"{self.qualname}.{node.name}.{item.name}", item)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Assign):
            self._visit_assign(node)
        elif isinstance(node, ast.Return) and node.value is not None:
            if any(s.returns for s in self.sends):
                shape = self._shape_of(node.value)
                if shape is not None:
                    self._classify(shape, node.lineno)
        elif isinstance(node, ast.Call):
            self._visit_call(node)
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx,
                                                            ast.Load):
            self._visit_subscript_read(node)
        elif isinstance(node, ast.Compare):
            self._visit_compare(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _visit_assign(self, node: ast.Assign) -> None:
        if len(node.targets) != 1:
            return
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name):
            if isinstance(node.value, ast.Dict):
                self.var_dicts[tgt.id] = \
                    _DictShape().merge_literal(node.value)
            elif isinstance(node.value, ast.Call):
                func = node.value.func
                if (isinstance(func, ast.Attribute) and func.attr == "get"
                        and node.value.args
                        and isinstance(node.value.args[0], ast.Constant)
                        and node.value.args[0].value == "verb"):
                    # verb = msg.get("verb") — dispatch variable
                    self.verb_vars.add(tgt.id)
                else:
                    # fed from a call: a dict whose full key set is not
                    # statically known (telemetry_payload, replica.health)
                    shape = _DictShape()
                    shape.dynamic = True
                    self.var_dicts[tgt.id] = shape
        elif isinstance(tgt, ast.Subscript) and isinstance(tgt.value,
                                                           ast.Name):
            shape = self.var_dicts.get(tgt.value.id)
            if shape is not None and isinstance(tgt.slice, ast.Constant) \
                    and isinstance(tgt.slice.value, str):
                shape.fields.add(tgt.slice.value)

    def _visit_call(self, node: ast.Call) -> None:
        func = node.func
        fname = dotted_name(func)
        # record_event("name", ...)
        if fname.endswith("record_event") and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            self.events.append(EventEmit(node.args[0].value, self.site,
                                         node.lineno))
        # tracked-dict mutation: d.update(k=...), d.setdefault("k", ...)
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            shape = self.var_dicts.get(func.value.id)
            if shape is not None and func.attr == "update":
                for kw in node.keywords:
                    if kw.arg is not None:
                        shape.fields.add(kw.arg)
                    else:
                        shape.dynamic = True
                for arg in node.args:
                    if isinstance(arg, ast.Dict):
                        sub = _DictShape().merge_literal(arg)
                        shape.fields |= sub.fields
                        shape.optional |= sub.optional
                        shape.dynamic |= sub.dynamic
                    else:
                        shape.dynamic = True      # update(payload)
            elif shape is not None and func.attr == "setdefault" \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                shape.fields.add(node.args[0].value)
        # .get("k") soft read
        if isinstance(func, ast.Attribute) and func.attr == "get" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            recv = _recv_name(func.value)
            if recv:
                self.raw_reads.append((recv, node.args[0].value, False,
                                       node.lineno))
        # send sites
        short = fname.rsplit(".", 1)[-1]
        idx = _SEND_CALLS.get(short)
        if idx is not None and len(node.args) > idx:
            shape = self._shape_of(node.args[idx])
            if shape is not None:
                self._classify(shape, node.lineno)
        elif short == "print" and node.args \
                and isinstance(node.args[0], ast.Call) \
                and dotted_name(node.args[0].func) == "json.dumps" \
                and node.args[0].args:
            shape = self._shape_of(node.args[0].args[0])
            if shape is not None:
                self._classify(shape, node.lineno)

    def _visit_subscript_read(self, node: ast.Subscript) -> None:
        if isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            recv = _recv_name(node.value)
            if recv:
                self.raw_reads.append((recv, node.slice.value, True,
                                       node.lineno))

    def _visit_compare(self, node: ast.Compare) -> None:
        # verb == "submit" where verb came from <msg>.get("verb")
        if isinstance(node.left, ast.Name) \
                and node.left.id in self.verb_vars \
                and len(node.ops) == 1 and isinstance(node.ops[0], ast.Eq) \
                and isinstance(node.comparators[0], ast.Constant) \
                and isinstance(node.comparators[0].value, str):
            self.dispatched.append(VerbUse(node.comparators[0].value,
                                           self.site, node.lineno))

    # -- channel classification -------------------------------------------

    def _classify(self, shape: _DictShape, line: int) -> None:
        if shape.verb_const is not None:
            self.sent.append(SentDict(
                shape.verb_const, "request", None,
                frozenset(shape.fields), frozenset(shape.optional),
                shape.dynamic, self.site, line))
            self.sent_verbs.append(VerbUse(shape.verb_const, self.site,
                                           line))
            return
        is_stream = "kind" in shape.fields or shape.kind_const is not None
        if not is_stream and not any(s.direction == "reply"
                                     for s in self.sends):
            # a stream sender whose event kind is a variable (the SSE
            # handlers pass the kind as sse_event's first argument)
            is_stream = any(s.direction == "stream" for s in self.sends)
        if is_stream:
            for spec in self.sends:
                if spec.direction == "stream":
                    self.sent.append(SentDict(
                        spec.verb, "stream", shape.kind_const or "*",
                        frozenset(shape.fields),
                        frozenset(shape.optional), shape.dynamic,
                        self.site, line))
            return
        for spec in self.sends:
            if spec.direction == "reply":
                self.sent.append(SentDict(
                    spec.verb, "reply", None, frozenset(shape.fields),
                    frozenset(shape.optional), shape.dynamic, self.site,
                    line))

    def reads(self) -> List[FieldRead]:
        out = []
        for recv, field, hard, line in self.raw_reads:
            for spec in self.recvs:
                if recv in spec.vars:
                    out.append(FieldRead(spec.verb, spec.direction,
                                         spec.kind, field, hard,
                                         self.site, line))
        return out


# --------------------------------------------------------------------------
# model build
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Channel:
    """One (verb, direction[, kind]) sub-channel: the sender/receiver
    join the rules and the golden both consume."""
    verb: str
    direction: str
    kind: Optional[str]
    senders: List[SentDict] = dataclasses.field(default_factory=list)
    reads: List[FieldRead] = dataclasses.field(default_factory=list)

    @property
    def sent_fields(self) -> Set[str]:
        out: Set[str] = set()
        for s in self.senders:
            out |= s.fields | s.optional
        return out

    @property
    def optional_fields(self) -> Set[str]:
        """Fields some sender path omits: conditional-spread keys plus
        any field absent from at least one non-dynamic sender literal."""
        out: Set[str] = set()
        static = [s for s in self.senders if not s.dynamic]
        for s in self.senders:
            out |= s.optional
        for f in self.sent_fields:
            if any(f not in s.fields | s.optional for s in static):
                out.add(f)
        return out

    @property
    def dynamic(self) -> bool:
        return any(s.dynamic for s in self.senders)

    @property
    def read_fields(self) -> Set[str]:
        return {r.field for r in self.reads}

    @property
    def open(self) -> bool:
        return channel_open(self.verb, self.direction, self.kind)


@dataclasses.dataclass
class WireModel:
    """The whole-protocol model."""
    sends: List[SentDict]
    reads: List[FieldRead]
    events: List[EventEmit]
    sent_verbs: List[VerbUse]
    dispatched_verbs: List[VerbUse]

    def channels(self) -> Dict[Tuple[str, str, Optional[str]], Channel]:
        """(verb, direction, kind) -> Channel. Stream reads with
        ``kind=None`` are attached to every sub-channel of their verb AND
        kept on a ``(verb, "stream", None)`` aggregate so the golden
        records the kind-agnostic reader once."""
        out: Dict[Tuple[str, str, Optional[str]], Channel] = {}

        def chan(verb, direction, kind) -> Channel:
            return out.setdefault((verb, direction, kind),
                                  Channel(verb, direction, kind))

        for s in self.sends:
            chan(s.verb, s.direction, s.kind).senders.append(s)
        for r in self.reads:
            chan(r.verb, r.direction, r.kind).reads.append(r)
        # fan kind-agnostic stream reads out to the concrete sub-channels
        for (verb, direction, kind), ch in list(out.items()):
            if direction == "stream" and kind is None:
                for (v2, d2, k2), ch2 in out.items():
                    if v2 == verb and d2 == "stream" and k2 is not None:
                        ch2.reads.extend(ch.reads)
        return out


def wire_files(repo_root: str = REPO_ROOT) -> List[str]:
    """Repo-relative .py files in the wire roots."""
    return iter_repo_files(WIRE_ROOTS, repo_root)


def build_model(files: Sequence[Tuple[str, str]]) -> WireModel:
    """Build the protocol model from (rel_path, source) pairs."""
    sends: List[SentDict] = []
    reads: List[FieldRead] = []
    events: List[EventEmit] = []
    sent_verbs: List[VerbUse] = []
    dispatched: List[VerbUse] = []

    for path, source in files:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        pending: List[Tuple[str, ast.AST]] = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pending.append((node.name, node))
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        pending.append((f"{node.name}.{item.name}", item))
        while pending:
            qualname, fnode = pending.pop(0)
            specs = ENDPOINTS.get(f"{path}::{qualname}", ())

            def _collect(q, n):
                pending.append((q, n))
            w = _FuncWalker(path, qualname, fnode, specs, _collect)
            sends.extend(w.sent)
            reads.extend(w.reads())
            events.extend(w.events)
            sent_verbs.extend(w.sent_verbs)
            dispatched.extend(w.dispatched)

    return WireModel(sends=sends, reads=reads, events=events,
                     sent_verbs=sent_verbs, dispatched_verbs=dispatched)


def build_repo_model(repo_root: str = REPO_ROOT,
                     paths: Optional[Sequence[str]] = None) -> WireModel:
    import os
    files = []
    for rel in (paths if paths is not None else wire_files(repo_root)):
        with open(os.path.join(repo_root, rel), encoding="utf-8") as fh:
            files.append((rel, fh.read()))
    return build_model(sorted(files))


# --------------------------------------------------------------------------
# waivers
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WireWaiver:
    rule: str
    reason: str
    line: int


def collect_waivers(source: str, rel_path: str, known_rules: Sequence[str]
                    ) -> Tuple[List[WireWaiver], List[str]]:
    """(waivers, problems) from real comment tokens of one file. A waiver
    applies to findings of its rule on its own line or the line below
    (comment-above placement, graftlint-style)."""
    waivers: List[WireWaiver] = []
    problems: List[str] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return waivers, problems
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _WAIVER_RE.search(tok.string)
        if not m:
            continue
        rule, reason = m.group(1), (m.group(2) or "").strip()
        if rule not in known_rules:
            problems.append(
                f"{rel_path}:{tok.start[0]}: unknown graftwire rule "
                f"'{rule}' in waiver (known: {', '.join(known_rules)})")
            continue
        if not reason:
            problems.append(
                f"{rel_path}:{tok.start[0]}: graftwire waiver for "
                f"'{rule}' has no reason — write "
                f"'# graftwire: allow={rule} -- <why>'")
            continue
        waivers.append(WireWaiver(rule, reason, tok.start[0]))
    return waivers, problems


def _iter_endpoint_specs() -> Iterable[Tuple[str, object]]:
    for key, specs in ENDPOINTS.items():
        for spec in specs:
            yield key, spec
