"""graftsync rules + golden lock-graph audit (the sync_audit.py machinery).

Rules consume the :class:`~dalle_tpu.analysis.sync_flow.SyncModel` — they
are relational (cross-method, cross-file), so they do not register in the
graftlint per-file registry; ``scripts/sync_audit.py`` is their CLI, with
the graftir golden workflow (``contracts/sync.json``, ``--check`` /
``--update`` / ``--explain``) and ``# graftsync: allow=<rule> -- <reason>``
waivers.

| rule | hazard |
|---|---|
| ``unguarded-field`` | a field written under a class lock somewhere is read or written bare from a thread-entry method (Eraser-style lockset violation: the exact PolicyQueue tie-break class of race) |
| ``lock-order-cycle`` | the acquisition graph has a cycle — two call paths take the same locks in opposite orders; both ``file::function`` sites are named |
| ``blocking-under-lock`` | a queue get/put with no timeout, socket recv/dial, ``join``/``wait`` with no timeout, ``subprocess`` wait, ``time.sleep`` or device ``block_until_ready`` inside a ``with <lock>`` body — every other user of that lock stalls behind the wait |
| ``thread-no-join`` | a non-daemon thread whose creating scope (class, for ``self.``-stored threads) never joins — interpreter shutdown blocks on it |
| ``cond-wait-no-predicate`` | ``Condition.wait`` outside a ``while`` loop — a stolen or spurious wakeup silently proceeds on a false predicate (``wait_for`` carries its own loop and is exempt) |
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .core import REPO_ROOT, Finding
from . import sync_flow
from .sync_flow import SyncModel, find_cycles

SCHEMA = 1

SYNC_RULES: Dict[str, str] = {
    "unguarded-field":
        "lock-guarded field read/written bare from a thread entry",
    "lock-order-cycle":
        "cycle in the lock-acquisition graph (deadlock potential)",
    "blocking-under-lock":
        "unbounded blocking call inside a with-lock body",
    "thread-no-join":
        "non-daemon thread with no join on any shutdown path",
    "cond-wait-no-predicate":
        "Condition.wait outside a while predicate loop",
}


def _short(lock_id: str) -> str:
    """'RequestQueue._lock' for display; the golden keeps full ids."""
    return lock_id.split("::", 1)[-1]


# --------------------------------------------------------------------------
# the rules
# --------------------------------------------------------------------------

def check_unguarded_fields(model: SyncModel) -> List[Finding]:
    out, seen = [], set()

    def check_func(info, ckey, entry_key):
        fields = model.guarded.get(ckey, {})
        for acc in info.accesses:
            guards = fields.get(acc.field)
            if not guards or acc.held & guards:
                continue
            dedup = (info.path, acc.line, acc.field)
            if dedup in seen:
                continue
            seen.add(dedup)
            verb = "written" if acc.kind == "w" else "read"
            out.append(Finding(
                "unguarded-field", info.path, acc.line,
                f"{info.cls}.{acc.field} is {verb} without "
                f"{' or '.join(sorted(_short(g) for g in guards))} in "
                f"thread entry {entry_key.split('::')[-1]} — it is "
                f"written under that lock elsewhere; take the lock or "
                f"waive the benign race with a reason"))

    for key, tdef in sorted(model.thread_entries.items()):
        info = model.functions.get(key)
        if info is None or info.cls is None:
            continue
        ckey = f"{info.path}::{info.cls}"
        check_func(info, ckey, key)
        # one call deep: same-class helpers invoked with no lock held run
        # on the entry's thread with the entry's (empty) lockset
        for callee, _, held in info.calls:
            if held:
                continue
            cinfo = model.functions.get(callee)
            if cinfo is not None and cinfo.cls == info.cls \
                    and cinfo.path == info.path:
                check_func(cinfo, ckey, key)
    return out


def check_lock_order(model: SyncModel) -> List[Finding]:
    out = []
    for cycle in find_cycles(model.edges):
        route = " -> ".join([e.src.split("::")[-1] for e in cycle]
                            + [cycle[0].src.split("::")[-1]])
        sites = "; ".join(f"{e.src.split('::')[-1]}->"
                          f"{e.dst.split('::')[-1]} at {e.site}:{e.line}"
                          for e in cycle)
        first = cycle[0]
        out.append(Finding(
            "lock-order-cycle", first.site.split("::")[0], first.line,
            f"lock-order cycle {route} — opposite acquisition orders can "
            f"deadlock ({sites})"))
    return out


def check_blocking_under_lock(model: SyncModel) -> List[Finding]:
    out = []
    for info in model.functions.values():
        for b in info.blocking:
            out.append(Finding(
                "blocking-under-lock", info.path, b.line,
                f"{b.desc} while holding {_short(b.lock_id)} in "
                f"{info.qualname} — every other user of the lock stalls "
                f"behind this wait; move it outside the lock or bound it"))
    return out


def check_thread_lifecycle(model: SyncModel) -> List[Finding]:
    out = []
    for t in model.threads:
        if t.daemon or t.joined:
            continue
        out.append(Finding(
            "thread-no-join", t.path, t.line,
            f"non-daemon thread{f' {t.name!r}' if t.name else ''} created "
            f"in {t.site.split('::')[-1]} with no join in scope — "
            f"interpreter shutdown blocks on it; mark it daemon or join "
            f"it on the shutdown path"))
    return out


def check_cond_waits(model: SyncModel) -> List[Finding]:
    out = []
    for info in model.functions.values():
        for w in info.cond_waits:
            if w.in_loop:
                continue
            out.append(Finding(
                "cond-wait-no-predicate", info.path, w.line,
                f"Condition.wait on {_short(w.lock_id)} in "
                f"{info.qualname} outside a while loop — a spurious or "
                f"stolen wakeup proceeds on a false predicate; use "
                f"wait_for(predicate, ...) or re-check in a loop"))
    return out


_CHECKS = (check_unguarded_fields, check_lock_order,
           check_blocking_under_lock, check_thread_lifecycle,
           check_cond_waits)


def run_sync(model: SyncModel) -> List[Finding]:
    findings: List[Finding] = []
    for check in _CHECKS:
        findings.extend(check(model))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# --------------------------------------------------------------------------
# golden lock graph (contracts/sync.json)
# --------------------------------------------------------------------------

def graph_contract(model: SyncModel) -> dict:
    """The golden: lock inventory + acquisition edges. Keyed on stable
    identities (owner ids, file::function sites) — NOT line numbers, so
    unrelated edits don't read as drift."""
    dedup = {(e.src, e.dst, e.site) for e in model.edges}
    return {
        "schema": SCHEMA,
        "locks": sorted(
            ({"id": d.lock_id, "kind": d.kind}
             for d in model.locks.values()),
            key=lambda l: l["id"]),
        "edges": sorted(
            ({"src": src, "dst": dst, "site": site}
             for src, dst, site in dedup),
            key=lambda e: (e["src"], e["dst"], e["site"])),
    }


def save_contract(contract: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(contract, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_contract(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def diff_contract(old: dict, new: dict) -> List[str]:
    """Human-readable drift lines; empty == no drift."""
    lines = []
    okeys = {l["id"] for l in old.get("locks", [])}
    nkeys = {l["id"] for l in new.get("locks", [])}
    for lid in sorted(nkeys - okeys):
        lines.append(f"+ lock {lid}")
    for lid in sorted(okeys - nkeys):
        lines.append(f"- lock {lid}")
    oe = {(e["src"], e["dst"], e["site"]) for e in old.get("edges", [])}
    ne = {(e["src"], e["dst"], e["site"]) for e in new.get("edges", [])}
    for src, dst, site in sorted(ne - oe):
        lines.append(f"+ edge {_short(src)} -> {_short(dst)} at {site}")
    for src, dst, site in sorted(oe - ne):
        lines.append(f"- edge {_short(src)} -> {_short(dst)} at {site}")
    return lines


# --------------------------------------------------------------------------
# audit orchestration (CLI + tests)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class SyncReport:
    findings: List[Finding]                  # unwaived rule findings
    waived: List[Tuple[Finding, str]]        # (finding, reason)
    problems: List[str]                      # waiver syntax issues
    drift: List[str]                         # golden drift lines
    missing: bool                            # no golden yet
    contract: dict                           # the live contract
    model: SyncModel
    updated: bool = False

    @property
    def failed(self) -> bool:
        return bool(self.findings or self.problems or self.drift)


def _apply_waivers(findings: Sequence[Finding],
                   sources: Dict[str, str]
                   ) -> Tuple[List[Finding], List[Tuple[Finding, str]],
                              List[str]]:
    """Split findings into (unwaived, waived-with-reason, problems) using
    per-file ``# graftsync: allow=`` comments (finding line or line above)."""
    by_file: Dict[str, Dict[Tuple[str, int], str]] = {}
    problems: List[str] = []
    for path, src in sources.items():
        waivers, probs = sync_flow.collect_waivers(
            src, path, tuple(SYNC_RULES))
        problems.extend(probs)
        table = by_file.setdefault(path, {})
        for w in waivers:
            table[(w.rule, w.line)] = w.reason
    unwaived, waived = [], []
    for f in findings:
        table = by_file.get(f.path, {})
        reason = table.get((f.rule, f.line)) or table.get((f.rule, f.line - 1))
        if reason is not None:
            waived.append((f, reason))
        else:
            unwaived.append(f)
    return unwaived, waived, problems


def audit(repo_root: str = REPO_ROOT,
          contract_path: Optional[str] = None,
          update: bool = False,
          paths: Optional[Sequence[str]] = None) -> SyncReport:
    """Build the model over the sync roots, run the rules, apply waivers,
    and compare (or rewrite) the lock-graph golden."""
    if contract_path is None:
        contract_path = os.path.join(repo_root, "contracts", "sync.json")
    rels = list(paths) if paths is not None \
        else sync_flow.sync_files(repo_root)
    sources = {}
    for rel in rels:
        with open(os.path.join(repo_root, rel), encoding="utf-8") as fh:
            sources[rel] = fh.read()
    model = sync_flow.build_model(sorted(sources.items()))
    live = graph_contract(model)
    unwaived, waived, problems = _apply_waivers(run_sync(model), sources)

    if update:
        save_contract(live, contract_path)
        return SyncReport(unwaived, waived, problems, [], False, live,
                          model, updated=True)

    golden = load_contract(contract_path)
    if golden is None:
        return SyncReport(unwaived, waived, problems, [], True, live, model)
    return SyncReport(unwaived, waived, problems,
                      diff_contract(golden, live), False, live, model)


def render_report(report: SyncReport, scope: str) -> str:
    lines = [str(f) for f in report.findings]
    lines += [f"{f} [waived: {reason}]" for f, reason in report.waived]
    lines += [f"waiver-problem: {p}" for p in report.problems]
    for d in report.drift:
        lines.append(f"lock-graph drift: {d}")
    if report.missing:
        lines.append("no golden lock graph at contracts/sync.json — run "
                     "scripts/sync_audit.py --update")
    n = len(report.findings) + len(report.problems)
    if report.failed:
        lines.append(
            f"graftsync: {n} finding{'s' if n != 1 else ''}"
            + (f", {len(report.drift)} drift line"
               f"{'s' if len(report.drift) != 1 else ''}"
               if report.drift else "")
            + f" ({scope})")
        if report.drift:
            lines.append("intentional lock/edge change? regenerate with "
                         "scripts/sync_audit.py --update and commit the "
                         "diff")
    else:
        lines.append(f"graftsync: clean ({scope})")
    return "\n".join(lines)


def explain(model: SyncModel) -> str:
    """Pretty-print the model: locks, acquisition edges, guarded fields,
    thread entries (the --explain CLI path)."""
    lines = [f"locks ({len(model.locks)}):"]
    for lid in sorted(model.locks):
        d = model.locks[lid]
        lines.append(f"  {d.kind:<9} {lid}  ({d.path}:{d.line})")
    lines.append(f"acquisition edges ({len(model.edges)}):")
    if not model.edges:
        lines.append("  (none — no nested acquisitions)")
    for e in model.edges:
        lines.append(f"  {_short(e.src)} -> {_short(e.dst)}  at "
                     f"{e.site}:{e.line}")
    lines.append("guarded fields:")
    for ckey in sorted(model.guarded):
        fields = model.guarded[ckey]
        lines.append(f"  {ckey}:")
        for field in sorted(fields):
            lines.append(f"    {field:<18} under "
                         f"{', '.join(sorted(_short(g) for g in fields[field]))}")
    lines.append(f"thread entries ({len(model.thread_entries)}):")
    for key in sorted(model.thread_entries):
        t = model.thread_entries[key]
        tag = "daemon" if t.daemon else (
            "joined" if t.joined else "UNJOINED")
        lines.append(f"  {key}  [{tag}]")
    return "\n".join(lines)
