"""graftwire rules + golden protocol contract (the wire_audit.py machinery).

Rules consume the :class:`~dalle_tpu.analysis.wire_flow.WireModel` — they
join sender and receiver schemas ACROSS the process boundary, so they do
not register in the graftlint per-file registry; ``scripts/wire_audit.py``
is their CLI, with the graftir golden workflow (``contracts/wire.json``,
``--check`` / ``--update`` / ``--explain``) and
``# graftwire: allow=<rule> -- <reason>`` waivers.

| rule | hazard |
|---|---|
| ``wire-field-unread`` | a field is serialized onto a channel but no mapped receiver ever reads it — dead wire weight, or a consumer the endpoint map forgot |
| ``wire-field-unsourced`` | a receiver reads a field no sender path of the channel ever sets — it silently sees the ``.get`` default forever |
| ``wire-optional-no-default`` | a receiver SUBSCRIPTS a field some sender path omits — the KeyError that kills a replica worker mid-stream |
| ``wire-verb-orphan`` | a verb is sent but never dispatched server-side (or dispatched but never sent) |
| ``undeclared-lifecycle-transition`` | a ``record_event`` emission the declared request/replica state machines cannot place (or a machine with a cycle) |

The golden (``contracts/wire.json``) pins verbs × direction × field sets ×
lifecycle edges with ``file::function`` endpoint sites and NO line
numbers; drift lines name the verb, the field and both endpoint sites, so
a protocol change lands only with an explicit, reviewable golden update.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import REPO_ROOT, Finding
from . import wire_flow
from .wire_flow import (Channel, EVENT_EDGES, LIFECYCLES, WireModel,
                        lifecycle_cycles)

SCHEMA = 1

WIRE_RULES: Dict[str, str] = {
    "wire-field-unread":
        "field sent on a wire channel but never read by any mapped "
        "receiver",
    "wire-field-unsourced":
        "field read off a wire channel but never sent by any sender path",
    "wire-optional-no-default":
        "receiver subscripts a field some sender path omits",
    "wire-verb-orphan":
        "verb sent but never dispatched, or dispatched but never sent",
    "undeclared-lifecycle-transition":
        "emitted event is not a declared request/replica lifecycle "
        "transition",
}


def _chan_name(verb: str, direction: str, kind: Optional[str]) -> str:
    base = f"{verb}.{direction}"
    return f"{base}.{kind}" if kind is not None else base


def _sites(items) -> str:
    return ", ".join(sorted({i.site for i in items}))


def _site_path_line(site: str, line: int) -> Tuple[str, int]:
    return site.split("::", 1)[0], line


# --------------------------------------------------------------------------
# the rules
# --------------------------------------------------------------------------

def _stream_union(channels, verb: str) -> Tuple[Set[str], bool]:
    """(union of sent fields, any-sender-dynamic) across every stream
    sub-channel of ``verb`` — kind-agnostic readers see them all."""
    fields: Set[str] = set()
    dynamic = False
    for (v, d, _k), ch in channels.items():
        if v == verb and d == "stream":
            fields |= ch.sent_fields
            dynamic = dynamic or ch.dynamic
    return fields, dynamic


def check_field_unread(model: WireModel) -> List[Finding]:
    out = []
    for (verb, direction, kind), ch in sorted(
            model.channels().items(), key=lambda kv: str(kv[0])):
        if direction == "stream" and kind is None:
            continue                    # aggregate view, not a channel
        if ch.open or not ch.senders or not ch.reads:
            # open receivers are policy (CHANNEL_POLICY); a channel with
            # no mapped reader at all is either policy-open or handled by
            # golden drift, not a per-field finding
            continue
        read = ch.read_fields
        for field in sorted(ch.sent_fields - read):
            sender = min(ch.senders, key=lambda s: (s.site, s.line))
            path, line = _site_path_line(sender.site, sender.line)
            out.append(Finding(
                "wire-field-unread", path, line,
                f"field '{field}' of {_chan_name(verb, direction, kind)} "
                f"is sent by {_sites(ch.senders)} but no mapped receiver "
                f"({_sites(ch.reads) or 'none'}) reads it — drop it or "
                f"map the consumer in wire_flow.ENDPOINTS"))
    return out


def check_field_unsourced(model: WireModel) -> List[Finding]:
    out = []
    channels = model.channels()
    # one physical read (site, line, field) may map to several channels
    # (overlapping Recv specs, e.g. the shared submit/submit_group ack
    # reader): the variable holds a message from ONE of them at runtime,
    # so the field is unsourced only if NO mapped channel sets it
    groups: Dict[Tuple[str, int, str], List] = {}
    for r in model.reads:
        groups.setdefault((r.site, r.line, r.field), []).append(r)
    for (site, line, field), reads in sorted(groups.items()):
        sourced = False
        names = []
        for r in reads:
            if r.direction == "stream":
                fields, dynamic = _stream_union(channels, r.verb)
            else:
                ch = channels.get((r.verb, r.direction, None))
                if ch is None or not ch.senders:
                    sourced = True      # no sender mapped: golden territory
                    break
                fields, dynamic = ch.sent_fields, ch.dynamic
            if dynamic or not fields or field in fields:
                sourced = True
                break
            names.append(_chan_name(r.verb, r.direction, r.kind))
        if sourced:
            continue
        path, fline = _site_path_line(site, line)
        out.append(Finding(
            "wire-field-unsourced", path, fline,
            f"{site.split('::')[-1]} reads '{field}' off "
            f"{', '.join(sorted(set(names)))} but no sender path sets it "
            f"— the read sees its default forever"))
    return out


def check_optional_no_default(model: WireModel) -> List[Finding]:
    out, seen = [], set()
    channels = model.channels()
    for r in model.reads:
        if not r.hard:
            continue
        if r.direction == "stream":
            # a hard read against every sub-channel where the field occurs
            targets = [ch for (v, d, k), ch in channels.items()
                       if v == r.verb and d == "stream" and k is not None
                       and (r.kind is None or k == r.kind)
                       and r.field in ch.sent_fields]
        else:
            ch = channels.get((r.verb, r.direction, None))
            targets = [ch] if ch is not None and ch.senders else []
        for ch in targets:
            static = [s for s in ch.senders if not s.dynamic]
            if not static:
                continue
            missing = [s for s in static
                       if r.field not in s.fields or r.field in s.optional]
            if not missing:
                continue
            dedup = (r.site, r.line, r.field, ch.kind)
            if dedup in seen:
                continue
            seen.add(dedup)
            path, line = _site_path_line(r.site, r.line)
            out.append(Finding(
                "wire-optional-no-default", path, line,
                f"{r.site.split('::')[-1]} subscripts '{r.field}' of "
                f"{_chan_name(ch.verb, ch.direction, ch.kind)} but sender "
                f"path {_sites(missing)} omits it — a KeyError here kills "
                f"the worker mid-stream; use .get with a default or make "
                f"every sender set it"))
    return out


def check_verb_orphans(model: WireModel) -> List[Finding]:
    out = []
    sent = {}
    for u in model.sent_verbs:
        sent.setdefault(u.verb, u)
    dispatched = {}
    for u in model.dispatched_verbs:
        dispatched.setdefault(u.verb, u)
    for verb in sorted(set(sent) - set(dispatched)):
        u = sent[verb]
        path, line = _site_path_line(u.site, u.line)
        out.append(Finding(
            "wire-verb-orphan", path, line,
            f"verb '{verb}' is sent by {u.site} but no server dispatch "
            f"compares against it — requests would draw the unknown_verb "
            f"error ack"))
    for verb in sorted(set(dispatched) - set(sent)):
        u = dispatched[verb]
        path, line = _site_path_line(u.site, u.line)
        out.append(Finding(
            "wire-verb-orphan", path, line,
            f"verb '{verb}' is dispatched at {u.site} but no client ever "
            f"sends it — dead protocol surface"))
    return out


def check_lifecycles(model: WireModel) -> List[Finding]:
    out = []
    for cycle in lifecycle_cycles():
        out.append(Finding(
            "undeclared-lifecycle-transition",
            "dalle_tpu/analysis/wire_flow.py", 1,
            f"lifecycle machine '{cycle[0]}' declares a cycle "
            f"{' -> '.join(cycle[1:])} — machines must be acyclic"))
    for e in sorted(model.events, key=lambda e: (e.site, e.line, e.name)):
        path, line = _site_path_line(e.site, e.line)
        edges = EVENT_EDGES.get(e.name)
        if edges is None:
            out.append(Finding(
                "undeclared-lifecycle-transition", path, line,
                f"record_event('{e.name}') at {e.site} is not mapped to "
                f"any declared lifecycle transition — add it to "
                f"wire_flow.EVENT_EDGES (as a transition or explicitly "
                f"non-lifecycle)"))
            continue
        for machine, src, dst in edges:
            declared = LIFECYCLES.get(machine, {}).get("edges", ())
            if (src, dst) not in declared:
                out.append(Finding(
                    "undeclared-lifecycle-transition", path, line,
                    f"event '{e.name}' at {e.site} claims transition "
                    f"{machine}:{src}->{dst}, which machine '{machine}' "
                    f"does not declare"))
    return out


_CHECKS = (check_field_unread, check_field_unsourced,
           check_optional_no_default, check_verb_orphans,
           check_lifecycles)


def run_wire(model: WireModel) -> List[Finding]:
    findings: List[Finding] = []
    for check in _CHECKS:
        findings.extend(check(model))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# --------------------------------------------------------------------------
# golden protocol contract (contracts/wire.json)
# --------------------------------------------------------------------------

def _channel_entry(ch: Channel) -> dict:
    return {
        "sender": {
            "fields": sorted(ch.sent_fields),
            "optional": sorted(ch.optional_fields),
            "dynamic": ch.dynamic,
            "sites": sorted({s.site for s in ch.senders}),
        },
        "receiver": {
            "fields": sorted(ch.read_fields),
            "sites": sorted({r.site for r in ch.reads}),
            "open": ch.open,
        },
    }


def wire_contract(model: WireModel) -> dict:
    """The golden: verbs × direction × field sets × lifecycle edges. Keyed
    on stable identities (verbs, fields, file::function sites) — NOT line
    numbers, so unrelated edits don't read as drift."""
    verbs: Dict[str, dict] = {}
    for (verb, direction, kind), ch in model.channels().items():
        if not ch.senders and not ch.reads:
            continue
        v = verbs.setdefault(verb, {})
        if direction == "stream":
            v.setdefault("stream", {})[kind or "*"] = _channel_entry(ch)
        else:
            v[direction] = _channel_entry(ch)
    events: Dict[str, dict] = {}
    for e in model.events:
        entry = events.setdefault(e.name, {"edges": [], "sites": set()})
        entry["sites"].add(e.site)
        entry["edges"] = sorted(
            f"{m}:{s}->{d}" for m, s, d in EVENT_EDGES.get(e.name, ()))
    return {
        "schema": SCHEMA,
        "verbs": verbs,
        "lifecycles": {
            name: {"states": sorted(m["states"]),
                   "edges": sorted([s, d] for s, d in m["edges"])}
            for name, m in LIFECYCLES.items()},
        "events": {name: {"edges": entry["edges"],
                          "sites": sorted(entry["sites"])}
                   for name, entry in events.items()},
    }


def save_contract(contract: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(contract, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_contract(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _iter_channels(contract: dict):
    for verb, dirs in contract.get("verbs", {}).items():
        for direction, entry in dirs.items():
            if direction == "stream":
                for kind, sub in entry.items():
                    yield (verb, "stream", kind), sub
            else:
                yield (verb, direction, None), entry


def _endpoint_sites(entry: dict) -> str:
    """'sender A, B; receiver C' — both file::function endpoint sites of a
    channel, the drift line's anchor."""
    s = ", ".join(entry["sender"]["sites"]) or "none"
    r = ", ".join(entry["receiver"]["sites"]) or "none"
    return f"sender {s}; receiver {r}"


def diff_contract(old: dict, new: dict) -> List[str]:
    """Human-readable drift lines; empty == no drift. Field lines name the
    verb, the field, and both endpoint sites."""
    lines: List[str] = []
    oc = dict(_iter_channels(old))
    nc = dict(_iter_channels(new))
    overbs = {v for v, _, _ in oc}
    nverbs = {v for v, _, _ in nc}
    for verb in sorted(nverbs - overbs):
        lines.append(f"+ verb {verb}")
    for verb in sorted(overbs - nverbs):
        lines.append(f"- verb {verb}")
    for key in sorted(set(oc) | set(nc), key=str):
        verb, direction, kind = key
        name = _chan_name(verb, direction, kind)
        o, n = oc.get(key), nc.get(key)
        if o is None:
            lines.append(f"+ channel {name} ({_endpoint_sites(n)})")
            continue
        if n is None:
            lines.append(f"- channel {name} ({_endpoint_sites(o)})")
            continue
        for sign, a, b in (("+", n, o), ("-", o, n)):
            anchor = a if sign == "+" else o
            for f in sorted(set(a["sender"]["fields"])
                            - set(b["sender"]["fields"])):
                lines.append(f"{sign} field {name} {f} "
                             f"({_endpoint_sites(anchor)})")
            for f in sorted(set(a["receiver"]["fields"])
                            - set(b["receiver"]["fields"])):
                lines.append(f"{sign} read {name} {f} "
                             f"({_endpoint_sites(anchor)})")
            for s in sorted(set(a["sender"]["sites"])
                            - set(b["sender"]["sites"])):
                lines.append(f"{sign} sender {name} at {s}")
            for s in sorted(set(a["receiver"]["sites"])
                            - set(b["receiver"]["sites"])):
                lines.append(f"{sign} receiver {name} at {s}")
        if o["sender"]["dynamic"] != n["sender"]["dynamic"]:
            lines.append(f"~ {name} sender dynamic: "
                         f"{o['sender']['dynamic']} -> "
                         f"{n['sender']['dynamic']}")
        if o["receiver"]["open"] != n["receiver"]["open"]:
            lines.append(f"~ {name} receiver open: "
                         f"{o['receiver']['open']} -> "
                         f"{n['receiver']['open']}")
    ol = old.get("lifecycles", {})
    nl = new.get("lifecycles", {})
    for machine in sorted(set(ol) | set(nl)):
        oe = {tuple(e) for e in ol.get(machine, {}).get("edges", [])}
        ne = {tuple(e) for e in nl.get(machine, {}).get("edges", [])}
        for s, d in sorted(ne - oe):
            lines.append(f"+ lifecycle-edge {machine}: {s} -> {d}")
        for s, d in sorted(oe - ne):
            lines.append(f"- lifecycle-edge {machine}: {s} -> {d}")
    oev = old.get("events", {})
    nev = new.get("events", {})
    for name in sorted(set(nev) - set(oev)):
        e = nev[name]
        lines.append(f"+ event {name} -> "
                     f"{', '.join(e['edges']) or 'non-lifecycle'} "
                     f"(at {', '.join(e['sites'])})")
    for name in sorted(set(oev) - set(nev)):
        lines.append(f"- event {name}")
    for name in sorted(set(oev) & set(nev)):
        if oev[name]["edges"] != nev[name]["edges"]:
            lines.append(f"~ event {name} edges: "
                         f"{', '.join(oev[name]['edges']) or 'none'} -> "
                         f"{', '.join(nev[name]['edges']) or 'none'}")
        elif oev[name]["sites"] != nev[name]["sites"]:
            lines.append(f"~ event {name} sites: "
                         f"{', '.join(oev[name]['sites'])} -> "
                         f"{', '.join(nev[name]['sites'])}")
    return lines


# --------------------------------------------------------------------------
# audit orchestration (CLI + tests)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class WireReport:
    findings: List[Finding]                  # unwaived rule findings
    waived: List[Tuple[Finding, str]]        # (finding, reason)
    problems: List[str]                      # waiver syntax issues
    drift: List[str]                         # golden drift lines
    missing: bool                            # no golden yet
    contract: dict                           # the live contract
    model: WireModel
    updated: bool = False

    @property
    def failed(self) -> bool:
        return bool(self.findings or self.problems or self.drift)


def _apply_waivers(findings: Sequence[Finding],
                   sources: Dict[str, str]
                   ) -> Tuple[List[Finding], List[Tuple[Finding, str]],
                              List[str]]:
    """Split findings into (unwaived, waived-with-reason, problems) using
    per-file ``# graftwire: allow=`` comments (finding line or line above)."""
    by_file: Dict[str, Dict[Tuple[str, int], str]] = {}
    problems: List[str] = []
    for path, src in sources.items():
        waivers, probs = wire_flow.collect_waivers(
            src, path, tuple(WIRE_RULES))
        problems.extend(probs)
        table = by_file.setdefault(path, {})
        for w in waivers:
            table[(w.rule, w.line)] = w.reason
    unwaived, waived = [], []
    for f in findings:
        table = by_file.get(f.path, {})
        reason = table.get((f.rule, f.line)) or table.get((f.rule, f.line - 1))
        if reason is not None:
            waived.append((f, reason))
        else:
            unwaived.append(f)
    return unwaived, waived, problems


def audit(repo_root: str = REPO_ROOT,
          contract_path: Optional[str] = None,
          update: bool = False,
          paths: Optional[Sequence[str]] = None) -> WireReport:
    """Build the protocol model over the wire roots, run the rules, apply
    waivers, and compare (or rewrite) the golden contract."""
    if contract_path is None:
        contract_path = os.path.join(repo_root, "contracts", "wire.json")
    rels = list(paths) if paths is not None \
        else wire_flow.wire_files(repo_root)
    sources = {}
    for rel in rels:
        with open(os.path.join(repo_root, rel), encoding="utf-8") as fh:
            sources[rel] = fh.read()
    model = wire_flow.build_model(sorted(sources.items()))
    live = wire_contract(model)
    unwaived, waived, problems = _apply_waivers(run_wire(model), sources)

    if update:
        save_contract(live, contract_path)
        return WireReport(unwaived, waived, problems, [], False, live,
                          model, updated=True)

    golden = load_contract(contract_path)
    if golden is None:
        return WireReport(unwaived, waived, problems, [], True, live, model)
    return WireReport(unwaived, waived, problems,
                      diff_contract(golden, live), False, live, model)


def render_report(report: WireReport, scope: str) -> str:
    lines = [str(f) for f in report.findings]
    lines += [f"{f} [waived: {reason}]" for f, reason in report.waived]
    lines += [f"waiver-problem: {p}" for p in report.problems]
    for d in report.drift:
        lines.append(f"wire-contract drift: {d}")
    if report.missing:
        lines.append("no golden protocol contract at contracts/wire.json "
                     "— run scripts/wire_audit.py --update")
    n = len(report.findings) + len(report.problems)
    if report.failed:
        lines.append(
            f"graftwire: {n} finding{'s' if n != 1 else ''}"
            + (f", {len(report.drift)} drift line"
               f"{'s' if len(report.drift) != 1 else ''}"
               if report.drift else "")
            + f" ({scope})")
        if report.drift:
            lines.append("intentional protocol change? regenerate with "
                         "scripts/wire_audit.py --update and commit the "
                         "diff — it is the PR's reviewable wire story")
    else:
        lines.append(f"graftwire: clean ({scope})")
    return "\n".join(lines)


def explain(model: WireModel) -> str:
    """Pretty-print the protocol: channels, fields, verbs, lifecycles
    (the --explain CLI path)."""
    channels = model.channels()
    lines = [f"channels ({sum(1 for k in channels if not (k[1] == 'stream' and k[2] is None))}):"]
    for key in sorted(channels, key=str):
        verb, direction, kind = key
        if direction == "stream" and kind is None:
            continue
        ch = channels[key]
        tag = "".join([" [dynamic]" if ch.dynamic else "",
                       " [open]" if ch.open else ""])
        lines.append(f"  {_chan_name(verb, direction, kind)}{tag}")
        opt = ch.optional_fields
        lines.append("    sent: " + (", ".join(
            f + ("?" if f in opt else "")
            for f in sorted(ch.sent_fields)) or "(none)"))
        lines.append("      by: " + (_sites(ch.senders) or "(unmapped)"))
        lines.append("    read: " + (", ".join(sorted(ch.read_fields))
                                     or "(none)"))
        lines.append("      by: " + (_sites(ch.reads) or "(unmapped)"))
    sent = sorted({u.verb for u in model.sent_verbs})
    disp = sorted({u.verb for u in model.dispatched_verbs})
    lines.append(f"verbs sent: {', '.join(sent)}")
    lines.append(f"verbs dispatched: {', '.join(disp)}")
    lines.append("lifecycles:")
    for name, machine in sorted(LIFECYCLES.items()):
        lines.append(f"  {name}: "
                     + "; ".join(f"{s}->{d}" for s, d in machine["edges"]))
    emitted = sorted({e.name for e in model.events})
    lines.append(f"events emitted ({len(emitted)}): {', '.join(emitted)}")
    return "\n".join(lines)
