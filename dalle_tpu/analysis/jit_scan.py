"""Shared AST helpers: find jit-wrapped functions and their jit options.

Recognized spellings (the ones this repo uses):

  @jax.jit                                   decorator
  @partial(jax.jit, static_argnums=...)      via functools.partial or partial
  @functools.partial(jax.jit, ...)
  g = jax.jit(f, static_argnums=...)         call form, named or lambda
  g = partial(jax.jit, ...)(f)               curried call form

``nn.remat``/``jax.checkpoint`` are deliberately NOT matched — their
static_argnums semantics differ and their bodies re-trace by design.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple


def dotted_name(node: ast.AST) -> str:
    """'jax.random.split' for nested Attribute/Name chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit", "pjit", "jax.pjit")


def _is_partial(node: ast.AST) -> bool:
    return dotted_name(node) in ("partial", "functools.partial")


@dataclasses.dataclass
class JitInfo:
    """One jit application found in a module."""
    name: Optional[str]            # name the JITTED callable is bound to
    func_node: ast.AST             # FunctionDef or Lambda being jitted
    line: int
    static_argnums: Tuple[int, ...]
    static_argnames: Tuple[str, ...]
    has_donate: bool
    jit_kwargs: Dict[str, ast.expr]
    wrapped_name: Optional[str] = None   # inner function's own name, if any


def _collect_jit_kwargs(call: ast.Call) -> Dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _literal_ints(node: Optional[ast.expr]) -> Tuple[int, ...]:
    """static_argnums value → tuple of ints (best effort on literals)."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _literal_strs(node: Optional[ast.expr]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value, str))
    return ()


def _info_from_kwargs(name, func_node, line, kwargs,
                      wrapped_name=None) -> JitInfo:
    return JitInfo(
        name=name, func_node=func_node, line=line,
        static_argnums=_literal_ints(kwargs.get("static_argnums")),
        static_argnames=_literal_strs(kwargs.get("static_argnames")),
        has_donate=("donate_argnums" in kwargs or "donate_argnames" in kwargs),
        jit_kwargs=kwargs, wrapped_name=wrapped_name)


def _jit_call_kwargs(node: ast.expr) -> Optional[Dict[str, ast.expr]]:
    """If ``node`` evaluates to a jit-wrapper (jax.jit or partial(jax.jit,...)),
    return its keyword options; else None."""
    if _is_jax_jit(node):
        return {}
    if isinstance(node, ast.Call):
        if _is_jax_jit(node.func):
            return _collect_jit_kwargs(node)
        if _is_partial(node.func) and node.args and _is_jax_jit(node.args[0]):
            return _collect_jit_kwargs(node)
    return None


def _jit_call_parts(node: ast.Call):
    """(wrapped target expr, jit kwargs) if ``node`` is a call-form jit
    application — jax.jit(f, ...) or partial(jax.jit, ...)(f) — else None."""
    if _is_jax_jit(node.func) and node.args:
        return node.args[0], _collect_jit_kwargs(node)
    if isinstance(node.func, ast.Call):
        inner = _jit_call_kwargs(node.func)
        if inner is not None and node.args:
            kwargs = dict(inner)
            kwargs.update(_collect_jit_kwargs(node))
            return node.args[0], kwargs
    return None


def find_jit_functions(tree: ast.Module) -> List[JitInfo]:
    """Every jit application in the module, with the wrapped function body
    when it is syntactically available. For the call form the recorded
    ``name`` is the name the JITTED callable is bound to (``g`` in
    ``g = jax.jit(f)``) — call-site rules must match calls to ``g``, not to
    the plain, un-jitted ``f``."""
    out: List[JitInfo] = []
    defs_by_name = {n.name: n for n in ast.walk(tree)
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seen_calls = set()

    def add_call_form(call: ast.Call, bound: Optional[str]):
        parts = _jit_call_parts(call)
        if parts is None:
            return
        seen_calls.add(id(call))
        target, kwargs = parts
        if isinstance(target, ast.Lambda):
            out.append(_info_from_kwargs(bound, target, call.lineno, kwargs))
        elif isinstance(target, ast.Name):
            body = defs_by_name.get(target.id, ast.Pass())
            out.append(_info_from_kwargs(bound, body, call.lineno, kwargs,
                                         wrapped_name=target.id))

    for node in ast.walk(tree):
        # decorator forms
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                kwargs = _jit_call_kwargs(dec)
                if kwargs is not None:
                    out.append(_info_from_kwargs(node.name, node, node.lineno,
                                                 kwargs,
                                                 wrapped_name=node.name))
        # assignment-bound call forms: g = jax.jit(f, ...)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            bound = (node.targets[0].id
                     if len(node.targets) == 1
                     and isinstance(node.targets[0], ast.Name) else None)
            add_call_form(node.value, bound)

    # unbound call forms (returned / passed directly): name stays None
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and id(node) not in seen_calls:
            add_call_form(node, None)
    return out


def func_param_names(func_node: ast.AST) -> List[str]:
    if isinstance(func_node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = func_node.args
        return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
                + [p.arg for p in a.kwonlyargs])
    return []


def walk_scope(roots):
    """Walk ``roots`` and their descendants WITHOUT descending into nested
    function/lambda definitions — the shared scan-own-scope-only traversal
    (each nested scope is scanned when the caller reaches it as a root)."""
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def body_nodes(func_node: ast.AST):
    """Iterate the wrapped function's own body nodes, pruning nested
    function/lambda definitions: a nested jitted function is scanned at its
    own jit site, and a nested plain def may be a host-callback body
    (jax.pure_callback) where host work is the point — flagging it would
    break the zero-false-positive contract."""
    if isinstance(func_node, ast.Lambda):
        yield from walk_scope([func_node.body])
    elif isinstance(func_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        yield from walk_scope(func_node.body)
