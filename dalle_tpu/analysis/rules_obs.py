"""Observability-hygiene rules — codifying the Prometheus cardinality lesson.

A labeled series (``counter_add``/``gauge_set`` with ``labels={...}``) is a
distinct time series PER LABEL-VALUE COMBINATION, held forever in the obs
registry and rendered into every textfile/scrape. Bounded dimensions
(tenant, reject reason, SLO window, layer group) are exactly what labels
are for; per-request values — trace_id, request_id, raw prompt text — are
not: every request mints a new series, the registry grows without bound,
and the scrape (and every ``MetricsLogger`` record, which merges the
snapshot) bloats with it. graftpulse hit this head-on: per-request decode
quality is deliberately shipped as span args / flight-recorder events
(bounded rings) plus UNLABELED aggregate gauges — never as labels
(serve/engine.py). This rule makes that boundary a lint finding instead of
a review comment:

  * ``unbounded-metric-label`` — a ``counter_add``/``gauge_set`` call whose
    ``labels`` dict has a VALUE derived from per-request data: an
    identifier or attribute named like request identity/payload
    (``trace_id``, ``request_id``, ``text``, ``prompt``, ...), including
    through ``str()``/f-string wrapping. Keys are fine — ``{"trace_id":
    ...}`` is flagged via its value, not its name, so a bounded value under
    an unfortunate key stays legal.

  * ``histogram-unbounded-buckets`` — a ``histogram_observe`` call whose
    ``buckets`` argument is data-derived (computed at the call site rather
    than a literal or a module-level ALL_CAPS constant) or a literal with
    more than ``MAX_HISTOGRAM_BUCKETS`` (32) bounds. A native histogram is
    one series PER BUCKET per family (``_bucket{le=}``): data-derived
    bounds re-register the family with whatever the data says this time —
    trace.py rejects a mismatch at runtime, but only on the code path that
    runs — and oversized bucket lists multiply every scrape and every
    fleet merge. Bounds belong in one named module constant.

Syntactic by design (the rules_jit trade): the denylist names the
identifiers this codebase uses for request-scoped data; a genuinely bounded
value that happens to share a name takes a one-line suppression next to the
call.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import FileContext, Finding, Rule, register_rule

_SINKS = ("counter_add", "gauge_set")

# identifiers that carry per-request (unbounded-cardinality) data in this
# codebase: request identity, raw payload, and per-request randomness
_REQUEST_NAMES = frozenset({
    "trace_id", "request_id", "text", "prompt", "caption", "seed",
    "x_request_id",
})


def _request_taint(node: ast.expr) -> Optional[str]:
    """The denylisted name a label-value expression reaches, or None.
    Walks through calls (str(...), f"{...}"), attributes (req.trace_id),
    and subscripts so wrapping can't launder the value."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _REQUEST_NAMES:
            return sub.id
        if isinstance(sub, ast.Attribute) and sub.attr in _REQUEST_NAMES:
            return sub.attr
    return None


@register_rule
class UnboundedMetricLabel(Rule):
    name = "unbounded-metric-label"
    description = ("counter_add/gauge_set labels value derived from "
                   "per-request data (trace_id, request_id, raw text/"
                   "prompt, seed) — every request mints a new Prometheus "
                   "series and the registry grows without bound; ship "
                   "per-request values as span args / recorder events and "
                   "keep labels for bounded dimensions")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if fname not in _SINKS:
                continue
            # labels is keyword-or-positional: counter_add(name, value,
            # labels) / gauge_set(name, value, labels) — a positional dict
            # must not evade the rule
            labels = next((kw.value for kw in node.keywords
                           if kw.arg == "labels"), None)
            if labels is None and len(node.args) >= 3:
                labels = node.args[2]
            if not isinstance(labels, ast.Dict):
                continue
            for key, val in zip(labels.keys, labels.values):
                taint = _request_taint(val)
                if taint is None:
                    continue
                kname = (key.value if isinstance(key, ast.Constant)
                         else "<dynamic>")
                yield Finding(
                    self.name, ctx.rel_path, node.lineno,
                    f"{fname} label {kname!r} takes its value from "
                    f"per-request data ({taint!r}) — unbounded series "
                    "cardinality; record per-request values as span args "
                    "or flight-recorder events (obs.record_span/"
                    "record_event) and aggregate into unlabeled gauges")


# mirrors obs/trace.py MAX_HISTOGRAM_BUCKETS — duplicated here on purpose:
# the linter must not import the runtime module it audits
_MAX_HISTOGRAM_BUCKETS = 32


def _literal_len(node: ast.expr) -> Optional[int]:
    """Element count when ``node`` is a tuple/list of constants (a literal
    bucket boundary list), else None."""
    if isinstance(node, (ast.Tuple, ast.List)) and \
            all(isinstance(e, ast.Constant) for e in node.elts):
        return len(node.elts)
    return None


def _is_named_constant(node: ast.expr) -> bool:
    """A bare ALL_CAPS name (or attribute, e.g. ``trace.DEFAULT_BUCKETS``)
    — the sanctioned way to share bucket bounds across call sites."""
    if isinstance(node, ast.Name):
        return node.id.isupper()
    if isinstance(node, ast.Attribute):
        return node.attr.isupper()
    return False


@register_rule
class HistogramUnboundedBuckets(Rule):
    name = "histogram-unbounded-buckets"
    description = ("histogram_observe buckets argument is data-derived "
                   "(computed at the call site) or a literal with more "
                   "than 32 bounds — each bound is a _bucket{le=} series "
                   "per family and derived bounds re-register the family "
                   "differently per code path; use one module-level "
                   "ALL_CAPS constant with <=32 sorted bounds")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if fname != "histogram_observe":
                continue
            # buckets is keyword-or-positional: histogram_observe(name,
            # value, buckets=...) — positional index 2 must not evade
            buckets = next((kw.value for kw in node.keywords
                            if kw.arg == "buckets"), None)
            if buckets is None and len(node.args) >= 3:
                buckets = node.args[2]
            if buckets is None:   # default bounds — always fine
                continue
            if isinstance(buckets, ast.Constant) and buckets.value is None:
                continue          # explicit buckets=None, same thing
            n = _literal_len(buckets)
            if n is not None:
                if n > _MAX_HISTOGRAM_BUCKETS:
                    yield Finding(
                        self.name, ctx.rel_path, node.lineno,
                        f"histogram_observe registers {n} bucket bounds "
                        f"(max {_MAX_HISTOGRAM_BUCKETS}) — every bound is "
                        "a _bucket{le=} series in every scrape and every "
                        "fleet merge; thin the boundary list")
                continue
            if _is_named_constant(buckets):
                continue
            yield Finding(
                self.name, ctx.rel_path, node.lineno,
                "histogram_observe buckets are data-derived (computed at "
                "the call site, not a literal or ALL_CAPS module "
                "constant) — bounds must be identical at every observe "
                "or the family re-registers inconsistently across code "
                "paths; hoist them into one named module-level constant")
