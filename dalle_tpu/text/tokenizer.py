"""Text tokenizers — the L1 layer (SURVEY.md §2.3).

Shared contract (reference dalle_pytorch/tokenizer.py:137-152, all four
implementations): ``tokenize(texts, context_length=256, truncate_text=False)
-> int32[b, context_length]`` with 0 as pad, plus ``encode``/``decode`` and
``vocab_size``. Host-side only — token ids are the device boundary.

Implementations:
  * SimpleTokenizer — byte-level BPE (text/bpe.py), CLIP-merges-file
    compatible, native C++ merge core when available. With no merges file it
    degrades to byte-level (still a correct tokenizer, vocab 514).
  * HugTokenizer — HuggingFace `tokenizers` JSON wrapper (tokenizer.py:158-192).
  * ChineseTokenizer — HF transformers bert-base-chinese (tokenizer.py:196-228).
  * YttmTokenizer — the reference wraps YouTokenToMe's C++ BPE
    (tokenizer.py:232-266); here the native core IS in-framework, so this is
    an alias over SimpleTokenizer with a yttm-model-style train/load flow.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional, Sequence

import numpy as np

from .bpe import BPE, DEFAULT_VOCAB_PATH, load_merges, save_merges, train_bpe

_DEFAULT = object()  # sentinel: "use the shipped CLIP vocab"


class SimpleTokenizer:
    """Byte-level BPE with the reference contract. ``bpe_path`` accepts a
    CLIP-format merges file (plain or .gz); ``merges`` accepts an in-memory
    merge list. With no arguments the shipped CLIP merges vocabulary loads
    by default, reproducing the reference's 49,408-token vocab
    (tokenizer.py:55-76 + dalle_pytorch/data/bpe_simple_vocab_16e6.txt);
    pass ``bpe_path=None, merges=[]`` explicitly for a bare byte-level
    tokenizer (vocab 514). ``clip_compat`` truncates merges at the CLIP
    limit (reference tokenizer.py:58); default: only for the shipped vocab —
    user merges files load in full."""

    CLIP_MERGE_LIMIT = 49152 - 256 - 2  # reference tokenizer.py:58

    def __init__(self, bpe_path: Optional[str] = _DEFAULT, merges=None,
                 clip_compat: Optional[bool] = None):
        if bpe_path is _DEFAULT:
            bpe_path = (str(DEFAULT_VOCAB_PATH)
                        if merges is None and DEFAULT_VOCAB_PATH.exists()
                        else None)
            if clip_compat is None and bpe_path is not None:
                clip_compat = True
        if bpe_path is not None:
            limit = self.CLIP_MERGE_LIMIT if clip_compat else None
            merges = load_merges(bpe_path, limit=limit)
        self.bpe = BPE(list(merges if merges is not None else []))

    @property
    def vocab_size(self) -> int:
        return self.bpe.vocab_size

    def encode(self, text: str) -> List[int]:
        return self.bpe.encode(text)

    def decode(self, ids: Iterable[int]) -> str:
        ids = [int(i) for i in np.asarray(list(ids)).reshape(-1) if int(i) != 0]
        return self.bpe.decode(ids)

    def tokenize(self, texts, context_length: int = 256,
                 truncate_text: bool = False) -> np.ndarray:
        """Pad/truncate to a fixed (b, context_length) int32 array, pad id 0
        (reference tokenizer.py:137-152)."""
        if isinstance(texts, str):
            texts = [texts]
        out = np.zeros((len(texts), context_length), dtype=np.int32)
        for i, text in enumerate(texts):
            ids = self.encode(text)
            if len(ids) > context_length:
                if not truncate_text:
                    raise RuntimeError(
                        f"Input {text!r} is too long for context length "
                        f"{context_length}")
                ids = ids[:context_length]
            out[i, :len(ids)] = ids
        return out

    # -- training flow (yttm-style) ----------------------------------------
    @classmethod
    def train(cls, texts: Iterable[str], num_merges: int,
              save_path: Optional[str] = None) -> "SimpleTokenizer":
        merges = train_bpe(texts, num_merges)
        if save_path:
            save_merges(save_path, merges)
        return cls(merges=merges)


class YttmTokenizer(SimpleTokenizer):
    """Name-compatible stand-in for the reference's YouTokenToMe wrapper
    (tokenizer.py:232-266): same contract, BPE model loaded from a merges
    file; the C++ merge core lives in-framework (text/native/)."""

    def __init__(self, bpe_path: str):
        if not Path(bpe_path).exists():
            raise ValueError(f"BPE json path {bpe_path!r} does not exist")
        super().__init__(bpe_path=str(bpe_path), clip_compat=False)


class HugTokenizer:
    """HuggingFace `tokenizers` JSON vocab wrapper (reference
    tokenizer.py:158-192). Import is lazy — the dependency is optional."""

    def __init__(self, bpe_path: str):
        try:
            from tokenizers import Tokenizer  # type: ignore
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "HugTokenizer needs the `tokenizers` package") from e
        path = Path(bpe_path)
        if not path.exists():
            raise ValueError(f"BPE json path {bpe_path!r} does not exist")
        self.tokenizer = Tokenizer.from_file(str(path))
        self.vocab_size = self.tokenizer.get_vocab_size()

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text).ids

    def decode(self, ids) -> str:
        ids = [int(i) for i in np.asarray(list(ids)).reshape(-1) if int(i) != 0]
        return self.tokenizer.decode(ids)

    def tokenize(self, texts, context_length: int = 256,
                 truncate_text: bool = False) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        out = np.zeros((len(texts), context_length), dtype=np.int32)
        for i, text in enumerate(texts):
            ids = self.encode(text)
            if len(ids) > context_length:
                if not truncate_text:
                    raise RuntimeError(
                        f"Input {text!r} is too long for context length "
                        f"{context_length}")
                ids = ids[:context_length]
            out[i, :len(ids)] = ids
        return out


class ChineseTokenizer:
    """bert-base-chinese via HF transformers (reference tokenizer.py:196-228).
    ``model_name`` may also be a local WordPiece ``vocab.txt`` path (one token
    per line). When the HF hub is unreachable (zero-egress environments) the
    default model falls back to the vendored mini WordPiece vocab
    (text/data/chinese_vocab_mini.txt — per-character coverage of the
    synthetic caption domain) with a warning, so the path stays executable
    offline."""

    VENDORED_VOCAB = Path(__file__).parent / "data" / "chinese_vocab_mini.txt"

    def __init__(self, model_name: str = "bert-base-chinese"):
        try:
            from transformers import BertTokenizer  # type: ignore
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "ChineseTokenizer needs the `transformers` package") from e
        if Path(model_name).is_file():
            self.tokenizer = BertTokenizer(vocab_file=str(model_name))
        else:
            try:
                # local cache first: a cached-but-corrupted model raises a
                # parse error (ValueError/JSON) here, which must surface —
                # only "not in cache" (OSError) proceeds to the hub
                self.tokenizer = BertTokenizer.from_pretrained(
                    model_name, local_files_only=True)
            except OSError:
                try:
                    self.tokenizer = BertTokenizer.from_pretrained(model_name)
                except OSError:
                    # hub unreachable AND not cached: fall back (default
                    # model only) so the path stays executable offline
                    if model_name != "bert-base-chinese":
                        raise
                    import warnings
                    warnings.warn(
                        "bert-base-chinese unavailable (offline?) — falling "
                        f"back to the vendored mini vocab "
                        f"{self.VENDORED_VOCAB}")
                    self.tokenizer = BertTokenizer(
                        vocab_file=str(self.VENDORED_VOCAB))
        self.vocab_size = self.tokenizer.vocab_size

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=False)

    def decode(self, ids) -> str:
        ids = [int(i) for i in np.asarray(list(ids)).reshape(-1) if int(i) != 0]
        return self.tokenizer.decode(ids)

    def tokenize(self, texts, context_length: int = 256,
                 truncate_text: bool = False) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        out = np.zeros((len(texts), context_length), dtype=np.int32)
        for i, text in enumerate(texts):
            ids = self.encode(text)
            if len(ids) > context_length:
                if not truncate_text:
                    raise RuntimeError(
                        f"Input {text!r} is too long for context length "
                        f"{context_length}")
                ids = ids[:context_length]
            out[i, :len(ids)] = ids
        return out


def get_tokenizer(kind: str = "simple", **kw):
    """Registry mirroring the reference's CLI selection
    (legacy/train_dalle.py:241-245)."""
    kinds = {"simple": SimpleTokenizer, "yttm": YttmTokenizer,
             "hug": HugTokenizer, "chinese": ChineseTokenizer}
    if kind not in kinds:
        raise ValueError(f"unknown tokenizer {kind!r}; options: {sorted(kinds)}")
    return kinds[kind](**kw)
