"""Lazy g++ build + ctypes binding for the native BPE core."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "bpe_core.cpp"
_LIB = _HERE / "libbpe_core.so"
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    """Compile (once) and dlopen the core; returns None if no toolchain."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", str(_SRC), "-o", str(_LIB)],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(str(_LIB))
            lib.bpe_new.restype = ctypes.c_void_p
            lib.bpe_new.argtypes = [ctypes.c_char_p]
            lib.bpe_free.argtypes = [ctypes.c_void_p]
            lib.bpe_num_merges.restype = ctypes.c_int32
            lib.bpe_num_merges.argtypes = [ctypes.c_void_p]
            lib.bpe_encode_word.restype = ctypes.c_int32
            lib.bpe_encode_word.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_char_p, ctypes.c_int32]
            _lib = lib
        except (subprocess.SubprocessError, OSError):
            _build_failed = True
        return _lib


class NativeBPE:
    """ctypes wrapper over the C++ merge engine. ``available()`` gates use so
    the pure-Python path transparently takes over without a toolchain."""

    SEP = "\x01"

    def __init__(self, merges: List[tuple]):
        lib = _load()
        if lib is None:
            raise RuntimeError("native BPE core unavailable (g++ build failed)")
        self._lib = lib
        text = "\n".join(self.SEP.join(pair) for pair in merges)
        self._handle = lib.bpe_new(text.encode("utf-8"))
        self._buf = ctypes.create_string_buffer(1 << 16)

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def encode_word(self, symbols: List[str]) -> List[str]:
        word = self.SEP.join(symbols).encode("utf-8")
        n = self._lib.bpe_encode_word(self._handle, word, self._buf,
                                      len(self._buf))
        if n < 0:  # pathological word longer than the buffer
            raise ValueError("word too long for native BPE buffer")
        return self._buf.raw[:n].decode("utf-8").split(self.SEP)

    def __del__(self):
        if getattr(self, "_handle", None) and getattr(self, "_lib", None):
            self._lib.bpe_free(self._handle)
            self._handle = None
