// Native BPE merge engine — the framework's yttm-equivalent (the reference
// delegates fast BPE to YouTokenToMe's C++ core, dalle_pytorch/tokenizer.py:232-266;
// here the hot merge loop is in-framework C++ behind a ctypes C ABI).
//
// Protocol: symbols are '\x01'-separated UTF-8 strings. Python owns unicode
// normalization, byte-encoding, and the word-split regex; this core owns the
// O(n log n) greedy lowest-rank pair merging, the per-call allocation-free
// inner loop, and an LRU-less word cache on the Python side.
//
// Build: g++ -O2 -shared -fPIC bpe_core.cpp -o libbpe_core.so  (see build.py)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Bpe {
  std::unordered_map<std::string, int32_t> ranks;  // "a\x01b" -> rank
};

constexpr char kSep = '\x01';

inline std::string pair_key(const std::string& a, const std::string& b) {
  std::string k;
  k.reserve(a.size() + b.size() + 1);
  k += a;
  k += kSep;
  k += b;
  return k;
}

}  // namespace

extern "C" {

// merges: newline-separated lines, each "first<sep>second" with sep = '\x01'.
// Rank = line index.
void* bpe_new(const char* merges) {
  auto* h = new Bpe();
  const char* p = merges;
  int32_t rank = 0;
  while (*p) {
    const char* nl = strchr(p, '\n');
    size_t len = nl ? static_cast<size_t>(nl - p) : strlen(p);
    if (len > 0) {
      h->ranks.emplace(std::string(p, len), rank++);
    }
    if (!nl) break;
    p = nl + 1;
  }
  return h;
}

void bpe_free(void* handle) { delete static_cast<Bpe*>(handle); }

int32_t bpe_num_merges(void* handle) {
  return static_cast<int32_t>(static_cast<Bpe*>(handle)->ranks.size());
}

// word: '\x01'-separated initial symbols. Writes merged symbols ('\x01'-
// separated) into out (capacity cap, NUL-terminated). Returns the number of
// bytes written excluding NUL, or -1 if out is too small.
int32_t bpe_encode_word(void* handle, const char* word, char* out,
                        int32_t cap) {
  const Bpe* h = static_cast<Bpe*>(handle);
  std::vector<std::string> syms;
  {
    const char* p = word;
    const char* start = p;
    for (;; ++p) {
      if (*p == kSep || *p == '\0') {
        if (p > start) syms.emplace_back(start, p - start);
        if (*p == '\0') break;
        start = p + 1;
      }
    }
  }
  while (syms.size() > 1) {
    int32_t best_rank = INT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < syms.size(); ++i) {
      auto it = h->ranks.find(pair_key(syms[i], syms[i + 1]));
      if (it != h->ranks.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == INT32_MAX) break;
    // merge every occurrence of the best pair left-to-right (BPE convention)
    const std::string first = syms[best_i];
    const std::string second = syms[best_i + 1];
    std::vector<std::string> merged;
    merged.reserve(syms.size());
    for (size_t i = 0; i < syms.size();) {
      if (i + 1 < syms.size() && syms[i] == first && syms[i + 1] == second) {
        merged.emplace_back(first + second);
        i += 2;
      } else {
        merged.emplace_back(syms[i]);
        i += 1;
      }
    }
    syms.swap(merged);
  }
  int32_t written = 0;
  for (size_t i = 0; i < syms.size(); ++i) {
    int32_t need = static_cast<int32_t>(syms[i].size()) + (i ? 1 : 0);
    if (written + need + 1 > cap) return -1;
    if (i) out[written++] = kSep;
    memcpy(out + written, syms[i].data(), syms[i].size());
    written += static_cast<int32_t>(syms[i].size());
  }
  out[written] = '\0';
  return written;
}

}  // extern "C"
