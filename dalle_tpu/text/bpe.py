"""Byte-level BPE — vocabulary, encoding, and merge training.

Reference capability: dalle_pytorch/tokenizer.py:55-152 (`SimpleTokenizer`,
OpenAI-CLIP-style byte BPE with a merges file, '</w>' word suffix, and the
`tokenize(texts, context_length, truncate_text) -> int[b, ctx]` contract with
0 as pad). This is a clean-room implementation of the public BPE algorithm:

  * `bytes_to_unicode` — the standard GPT-2 reversible byte↔printable-char
    table (public algorithm), so any UTF-8 text round-trips.
  * Vocabulary layout: 256 byte chars + 256 byte chars+'</w>' + one token per
    merge + specials ('<|startoftext|>', '<|endoftext|>'). With no merges the
    tokenizer degrades gracefully to byte-level (vocab 514).
  * The merges file format is CLIP-compatible ("first second" per line, first
    line optionally a header) so an existing `bpe_simple_vocab_16e6.txt` drops
    in to reproduce the reference's 49408 vocab exactly.
  * `train_bpe` learns merges from an iterator of texts — the in-framework
    replacement for shipping a fixed vocab blob.

The per-word merge loop runs in the native C++ core (text/native/) when the
toolchain is present — the framework's yttm-equivalent (tokenizer.py:232-266)
— with a pure-Python fallback.
"""

from __future__ import annotations

import functools
import html
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import regex as re

WORD_PAT = re.compile(
    r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"""
    r"""|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""",
    re.IGNORECASE)

SOT, EOT = "<|startoftext|>", "<|endoftext|>"


@functools.lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte → printable unicode char map (GPT-2's public scheme:
    keep printable latin ranges, remap the rest above U+0100)."""
    bs = (list(range(ord("!"), ord("~") + 1)) +
          list(range(ord("¡"), ord("¬") + 1)) +
          list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def clean_text(text: str) -> str:
    """Whitespace collapse + html unescape + lowercase. (The reference also
    runs ftfy mojibake repair, tokenizer.py:20-23 — not available offline;
    behavior is identical on well-formed input.)"""
    text = html.unescape(html.unescape(text))
    return re.sub(r"\s+", " ", text.strip()).lower()


def _pairs(word: Sequence[str]):
    return set(zip(word[:-1], word[1:]))


class BPE:
    """Vocabulary + encode/decode over a merge list."""

    def __init__(self, merges: List[Tuple[str, str]]):
        byte_chars = list(bytes_to_unicode().values())
        vocab = byte_chars + [c + "</w>" for c in byte_chars]
        vocab += ["".join(m) for m in merges]
        vocab += [SOT, EOT]
        self.merges = merges
        self.ranks = {m: i for i, m in enumerate(merges)}
        self.encoder = {tok: i for i, tok in enumerate(vocab)}
        self.decoder = {i: tok for tok, i in self.encoder.items()}
        self.byte_enc = bytes_to_unicode()
        self.byte_dec = {v: k for k, v in self.byte_enc.items()}
        self._cache: Dict[str, List[str]] = {SOT: [SOT], EOT: [EOT]}
        self._native = None
        try:
            from .native import NativeBPE
            if NativeBPE.available():
                self._native = NativeBPE(merges)
        except Exception:  # noqa: BLE001 - the C++ core is an optional
            # accelerator: import, toolchain, or ABI failures all mean the
            # same thing (use the pure-Python merge loop), never an error
            self._native = None

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    @property
    def uses_native_core(self) -> bool:
        return self._native is not None

    # -- merge loop --------------------------------------------------------
    def _merge_python(self, symbols: List[str]) -> List[str]:
        word = symbols
        while len(word) > 1:
            best = min(_pairs(word),
                       key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            first, second = best
            out, i = [], 0
            while i < len(word):
                if i + 1 < len(word) and word[i] == first and word[i + 1] == second:
                    out.append(first + second)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            word = out
        return word

    def _bpe_word(self, token: str) -> List[str]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        symbols = [self.byte_enc[b] for b in token.encode("utf-8")]
        if not symbols:
            return []
        symbols = symbols[:-1] + [symbols[-1] + "</w>"]
        if self._native is not None:
            word = self._native.encode_word(symbols)
        else:
            word = self._merge_python(symbols)
        self._cache[token] = word
        return word

    # -- public API --------------------------------------------------------
    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for token in WORD_PAT.findall(clean_text(text)):
            ids.extend(self.encoder[s] for s in self._bpe_word(token))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        text = "".join(self.decoder[i] for i in ids
                       if i in self.decoder and self.decoder[i] not in (SOT, EOT))
        # byte-decode first, then turn '</w>' markers into spaces (the marker's
        # own chars are printable ASCII and pass through the byte table) —
        # replacing first would drop the space, which is not a byte-table char
        data = bytes(self.byte_dec[c] for c in text if c in self.byte_dec)
        return (data.decode("utf-8", errors="replace")
                .replace("</w>", " ").strip())


# ---------------------------------------------------------------------------
# merges file io (CLIP-compatible) + training
# ---------------------------------------------------------------------------

DEFAULT_VOCAB_PATH = Path(__file__).parent / "data" / "bpe_simple_vocab_16e6.txt.gz"


def load_merges(path: str | Path, limit: Optional[int] = None) -> List[Tuple[str, str]]:
    """Read a CLIP-format merges file ('first second' per line; tolerate a
    version header and blank lines), plain or gzipped. ``limit`` reproduces
    the reference's slice (tokenizer.py:58: merges[1:49152-256-2+1])."""
    path = Path(path)
    if path.suffix == ".gz":
        import gzip
        text = gzip.decompress(path.read_bytes()).decode("utf-8")
    else:
        text = path.read_text(encoding="utf-8")
    lines = text.split("\n")
    # The version header may itself split into two tokens (CLIP's reads
    # '"bpe_simple_vocab_16e6.txt#version: 0.2'), so detect it by the
    # '#version' marker or a non-pair shape — a bare '#' test would eat a
    # legitimate first merge containing the byte char '#'. (The reference
    # drops line 0 unconditionally, tokenizer.py:60.)
    if lines and ("#version" in lines[0] or len(lines[0].split()) != 2):
        lines = lines[1:]
    merges = []
    for ln in lines:
        parts = ln.split()
        if len(parts) == 2:
            merges.append((parts[0], parts[1]))
        if limit and len(merges) >= limit:
            break
    return merges


def save_merges(path: str | Path, merges: Sequence[Tuple[str, str]]):
    Path(path).write_text(
        "#version: dalle_tpu bpe\n" +
        "\n".join(f"{a} {b}" for a, b in merges) + "\n", encoding="utf-8")


def train_bpe(texts: Iterable[str], num_merges: int) -> List[Tuple[str, str]]:
    """Learn a merge list from a corpus (classic BPE training: repeatedly fuse
    the most frequent adjacent symbol pair over the word-frequency table)."""
    enc = bytes_to_unicode()
    word_freq: Counter = Counter()
    for text in texts:
        for token in WORD_PAT.findall(clean_text(text)):
            symbols = [enc[b] for b in token.encode("utf-8")]
            if not symbols:
                continue
            symbols = symbols[:-1] + [symbols[-1] + "</w>"]
            word_freq[tuple(symbols)] += 1

    merges: List[Tuple[str, str]] = []
    words = {w: f for w, f in word_freq.items()}
    for _ in range(num_merges):
        pair_freq: Counter = Counter()
        for w, f in words.items():
            for p in zip(w[:-1], w[1:]):
                pair_freq[p] += f
        if not pair_freq:
            break
        best, freq = pair_freq.most_common(1)[0]
        if freq < 2:
            break
        merges.append(best)
        first, second = best
        new_words = {}
        for w, f in words.items():
            out, i = [], 0
            while i < len(w):
                if i + 1 < len(w) and w[i] == first and w[i + 1] == second:
                    out.append(first + second)
                    i += 2
                else:
                    out.append(w[i])
                    i += 1
            new_words[tuple(out)] = new_words.get(tuple(out), 0) + f
        words = new_words
    return merges
