"""graftmend breach→action automation: the policy layer that makes the
graftpulse sentries DO something (docs/RESILIENCE.md).

PRs 2–9 built detectors that can see a run dying — loss-spike z-scores,
per-layer-group grad explosions, codebook-collapse perplexity floors,
nan-precursor inf fractions (:mod:`dalle_tpu.obs.anomaly`). Until now a
breach paged (gauge + flight bundle) and the operator intervened by hand.
:class:`BreachActions` closes the loop with one policy action per breach
class, each applied host-side between steps so NOTHING here touches the
compiled program:

  * ``nan-precursor`` → **preemptive snapshot**
    (``BaseTrainer.take_preemptive_snapshot``): the classic divergence
    shape is inf-in-grads → loss NaN a few steps later, and the NaN
    rollback rewinds to the last save boundary. Snapshotting at the
    precursor means the eventual rollback burns breach→NaN steps (usually
    a handful) instead of up to ``save_every_steps``. The rung is
    one-shot: if the precursor state itself was already contaminated, the
    second rollback falls through to the durable boundary snapshot.
  * ``grad-explosion`` → **rollback + lr cut**: restore the last good
    (params, opt_state) immediately — don't wait for the NaN — and scale
    the learning rate down by ``lr_cut_factor`` so the restored state
    doesn't march straight back into the same cliff. The cut writes
    ``TrainState.lr_scale`` (a data leaf — no recompile) and is clamped at
    ``min_lr_scale`` so repeated breaches can't silently zero the run.
  * ``codebook-collapse`` → **lr cut + gumbel re-anneal**: a collapsed
    codebook at low gumbel temperature is frozen — the straight-through
    gradients all route through the same few codes. Re-annealing (restart
    the temperature schedule from the breach step, for trainers that
    expose ``reanneal_gumbel``) re-softens the assignment distribution so
    unused codes see gradient again, and the lr cut keeps the re-warmed
    phase from tearing up the encoder.
  * ``loss-spike`` → **no action** by default (a spike is the precursor's
    precursor; acting on it double-fires with the detectors above). Policy
    is data: pass ``policy={...}`` to remap.

Discipline (mirrors the sentry's): actions are EDGE-TRIGGERED — the sentry
only delivers ok→breach transitions, and this layer additionally coalesces
one action kind per step (five layer groups exploding in one boundary is
ONE rollback, not five) and honors an optional ``cooldown_steps``. Every
fired action emits a ``breach_action`` flight-recorder event, an
``actions.fired_total{action=}`` counter and an ``actions.lr_scale``
gauge, so post-mortems show what the automation did, not just what it saw.
A failing action degrades to a logged error — the policy layer must never
kill the training loop it protects.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..obs import counter_add, gauge_set, record_event
from ..obs.anomaly import Breach, HealthSentry

# detector name -> action name (the policy table in docs/RESILIENCE.md)
DEFAULT_POLICY: Dict[str, str] = {
    "nan-precursor": "preemptive_snapshot",
    "grad-explosion": "rollback_lr_cut",
    "codebook-collapse": "lr_cut_reanneal",
}


class BreachActions:
    """Callable policy object wired as ``HealthSentry.on_breach``.

    ``attach()`` binds it to the trainer's sentry (creating one from the
    trainer's ObsConfig if ``fit`` hasn't yet), chaining — not replacing —
    any existing ``on_breach`` sink."""

    def __init__(self, trainer, *, policy: Optional[Dict[str, str]] = None,
                 lr_cut_factor: float = 0.5, min_lr_scale: float = 1e-3,
                 cooldown_steps: int = 0, log=print):
        self.trainer = trainer
        self.policy = dict(DEFAULT_POLICY if policy is None else policy)
        self.lr_cut_factor = float(lr_cut_factor)
        self.min_lr_scale = float(min_lr_scale)
        self.cooldown_steps = int(cooldown_steps)
        self.log = log
        self.fired = []                    # (step, action, detector, group)
        self._last_fired: Dict[str, int] = {}   # action -> step
        self._handlers: Dict[str, Callable[[Breach], None]] = {
            "preemptive_snapshot": self._act_preemptive_snapshot,
            "rollback_lr_cut": self._act_rollback_lr_cut,
            "lr_cut_reanneal": self._act_lr_cut_reanneal,
        }

    # -- wiring ------------------------------------------------------------
    def attach(self) -> "BreachActions":
        """Bind to the trainer's HealthSentry (building it from
        ``train_cfg.obs`` when fit() hasn't run yet — fit's ``is None``
        check then reuses the same sentry, so EMA baselines are shared)."""
        sentry = self.trainer.health_sentry
        if sentry is None:
            sentry = HealthSentry.from_obs_config(self.trainer.train_cfg.obs)
            self.trainer.health_sentry = sentry
        prev = sentry.on_breach
        if prev is None:
            sentry.on_breach = self
        else:
            def chained(breach, _prev=prev, _self=self):
                _prev(breach)
                _self(breach)
            sentry.on_breach = chained
        return self

    # -- dispatch ----------------------------------------------------------
    def __call__(self, breach: Breach) -> None:
        action = self.policy.get(breach.detector)
        if action is None:
            return
        handler = self._handlers.get(action)
        if handler is None:
            self.log(f"[actions] unknown action {action!r} for "
                     f"{breach.detector}; ignoring")
            return
        last = self._last_fired.get(action)
        if last is not None and (breach.step == last
                                 or breach.step - last < self.cooldown_steps):
            # coalesce: N groups breaching in one boundary = one action;
            # cooldown bounds the rate across boundaries
            return
        self._last_fired[action] = breach.step
        try:
            handler(breach)
        except Exception as exc:  # noqa: BLE001 - a policy bug must degrade
            # to a missed remediation, never kill the run it protects
            self.log(f"[actions] {action} failed on {breach.detector} "
                     f"breach: {exc!r}")
            return
        self.fired.append((breach.step, action, breach.detector,
                           breach.layer_group))
        counter_add("actions.fired_total", 1.0, labels={"action": action})
        record_event("breach_action", action=action,
                     detector=breach.detector, layer_group=breach.layer_group,
                     step=breach.step, value=breach.value)
        self.log(f"[actions] step {breach.step}: {breach.detector} breach "
                 f"in [{breach.layer_group}] → {action}")

    # -- the actions -------------------------------------------------------
    def _act_preemptive_snapshot(self, breach: Breach) -> None:
        self.trainer.take_preemptive_snapshot()

    def _act_rollback_lr_cut(self, breach: Breach) -> None:
        self.trainer._rollback()
        self._cut_lr()

    def _act_lr_cut_reanneal(self, breach: Breach) -> None:
        self._cut_lr()
        reanneal = getattr(self.trainer, "reanneal_gumbel", None)
        if reanneal is not None:
            reanneal(breach.step)

    def _cut_lr(self) -> float:
        """Multiply ``TrainState.lr_scale`` by the cut factor (clamped at
        ``min_lr_scale``). A data-leaf write placed with the old leaf's
        sharding — same program signature, no recompile; one scalar
        device_get per breach (rare) is the whole host cost."""
        import jax
        import jax.numpy as jnp
        state = self.trainer.state
        # getattr: GANTrainState (full-GAN VQGAN) has no lr_scale FIELD at
        # all, and un-armed TrainStates carry None — both degrade to a
        # logged skip, never an AttributeError that would eat the action
        old = getattr(state, "lr_scale", None)
        if old is None:
            self.log("[actions] state has no lr_scale leaf; lr cut skipped")
            return 1.0
        new = max(float(jax.device_get(old)) * self.lr_cut_factor,
                  self.min_lr_scale)
        leaf = jnp.asarray(new, jnp.float32)
        sharding = getattr(old, "sharding", None)
        if sharding is not None:
            leaf = jax.device_put(leaf, sharding)
        self.trainer.state = state.replace(lr_scale=leaf)
        gauge_set("actions.lr_scale", new)
        return new
