"""Train state + optimizer construction.

Replaces the reference's torch Adam + ReduceLROnPlateau / ExponentialLR wiring
(legacy/train_dalle.py:439-459, legacy/train_vae.py Exponential decay) with an
optax chain. Gradient clipping and accumulation — which the reference delegates
to the DeepSpeed engine (deepspeed_backend.py:135-163) — are optax transforms
inside the jitted step, so they compile into the same XLA program as the psum.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from ..config import OptimConfig


class _ValueEqMethod:
    """Value-comparable wrapper for a bound method held in a static field.

    Static fields ride the pytree treedef, which jit compares with ``==``.
    Bound methods compare by ``__self__`` IDENTITY, so two equal-config
    trainers passing ``model.apply`` get unequal TrainState treedefs and the
    shared train step silently retraces (and recompiles, seconds per
    program) once per trainer instance. Flax modules compare by config, so
    delegating equality to (underlying function, module) restores cross-
    trainer cache hits while ``state.apply_fn(params, x)`` keeps working."""

    __slots__ = ("_func", "_self")

    def __init__(self, method):
        self._func = method.__func__
        self._self = method.__self__

    def __call__(self, *args, **kwargs):
        return self._func(self._self, *args, **kwargs)

    def __eq__(self, other):
        return (type(other) is _ValueEqMethod and self._func is other._func
                and self._self == other._self)

    def __hash__(self):
        return hash((self._func, self._self))


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    # runtime learning-rate multiplier (graftmend breach→action layer,
    # train/actions.py): a (), f32 DATA leaf — the host writes a new value
    # between steps (``state.replace(lr_scale=...)``) without recompiling,
    # which a schedule closed over by the tx (static) cannot do. Updates
    # are multiplied by it after ``tx.update``, which for Adam-family
    # optimizers (update = -lr·normalized ± decay) is exactly a
    # learning-rate scale; moments are untouched, so restoring the scale
    # to 1.0 restores the original trajectory going forward.
    #
    # OPT-IN at creation (``create(..., lr_scale=1.0)``; trainers arm it
    # from ``TrainConfig.runtime_lr_scale``): None means no leaf at all —
    # the compiled program is byte-identical to a scale-less step (the
    # extra per-leaf multiply measurably taxes compile time across the
    # fleet of trainer programs), and arming mid-run is deliberately
    # unsupported because the treedef change would break the pinned
    # out_shardings of an already-jitted step.
    #
    # static fields (no defaults: a direct construction missing them must
    # fail at construction, not later inside apply_gradients); lr_scale is
    # declared last purely for dataclass default ordering — static fields
    # are not pytree leaves, so the leaf order is unchanged
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    lr_scale: Any = None

    @classmethod
    def create(cls, *, apply_fn, params, tx, lr_scale=None):
        import inspect
        if inspect.ismethod(apply_fn):
            apply_fn = _ValueEqMethod(apply_fn)
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params),
                   lr_scale=(None if lr_scale is None
                             else jnp.asarray(lr_scale, jnp.float32)),
                   apply_fn=apply_fn, tx=tx)

    def apply_gradients(self, grads, return_updates: bool = False,
                        **extra_args):
        """``extra_args`` feed GradientTransformationExtraArgs members of the
        chain — e.g. ``value=loss`` drives the plateau schedule; plain
        transforms ignore them (the tx is wrapped with extra-args support).
        ``return_updates=True`` additionally returns the optimizer's update
        tree (the graftpulse health taps derive per-layer-group update
        ratios from it without recomputing ``new - old`` params, which
        would read the donated input buffers) — post-``lr_scale``, i.e. the
        update actually applied."""
        updates, opt_state = self.tx.update(grads, self.opt_state, self.params,
                                            **extra_args)
        if self.lr_scale is not None:
            scale = self.lr_scale
            updates = jax.tree.map(lambda u: u * scale, updates)
        params = optax.apply_updates(self.params, updates)
        new = self.replace(step=self.step + 1, params=params,
                           opt_state=opt_state)
        return (new, updates) if return_updates else new


def make_lr_schedule(cfg: OptimConfig):
    if cfg.lr_scheduler == "constant":
        sched = optax.constant_schedule(cfg.learning_rate)
    elif cfg.lr_scheduler == "cosine":
        sched = optax.cosine_decay_schedule(cfg.learning_rate,
                                            max(cfg.total_steps - cfg.warmup_steps, 1))
    elif cfg.lr_scheduler == "exponential":
        # reference train_vae uses ExponentialLR(gamma=lr_decay_rate) per epoch;
        # here decay applies every lr_transition_steps steps
        sched = optax.exponential_decay(cfg.learning_rate,
                                        transition_steps=cfg.lr_transition_steps,
                                        decay_rate=cfg.lr_decay_rate)
    elif cfg.lr_scheduler == "plateau":
        # base lr stays constant; the ReduceLROnPlateau behavior is an
        # in-graph update scaler appended by make_optimizer (driven by the
        # step's loss via apply_gradients(value=...))
        sched = optax.constant_schedule(cfg.learning_rate)
    else:
        raise ValueError(f"unknown lr_scheduler {cfg.lr_scheduler!r}")
    if cfg.warmup_steps > 0:
        warm = optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
        sched = optax.join_schedules([warm, sched], [cfg.warmup_steps])
    return sched


@functools.lru_cache(maxsize=128)
def make_optimizer(cfg: OptimConfig) -> optax.GradientTransformation:
    """Memoized on the (frozen, hashable) config: two trainers with equal
    OptimConfigs share ONE GradientTransformation object. This matters
    beyond allocation thrift — optax transforms are NamedTuples of fresh
    closures, and the tx rides TrainState's static treedef, so distinct tx
    objects force jit recompiles of otherwise-identical train steps (the
    test suite builds equal-config trainer pairs constantly; sharing the tx
    makes the second trainer's compile a cache hit)."""
    return _build_optimizer(cfg)


def _build_optimizer(cfg: OptimConfig) -> optax.GradientTransformation:
    sched = make_lr_schedule(cfg)
    if cfg.optimizer == "adam":
        core = optax.adam(sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps)
    elif cfg.optimizer == "adamw":
        core = optax.adamw(sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
                           weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "adafactor":
        # factored second moments, no first moment: O(rows+cols) optimizer
        # state instead of Adam's 2x params — the single-chip path to
        # billion-param configs (multi-chip gets the same effect from fsdp
        # sharding of Adam state)
        core = optax.adafactor(sched, momentum=None,
                               weight_decay_rate=cfg.weight_decay or None)
    elif cfg.optimizer == "sgd":
        core = optax.sgd(sched)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    parts = []
    if cfg.grad_clip_norm and cfg.grad_clip_norm > 0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    parts.append(core)
    tx = optax.chain(*parts)
    if cfg.grad_accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.grad_accum_steps)
    if cfg.lr_scheduler == "plateau":
        # ReduceLROnPlateau parity (reference legacy/train_dalle.py:444-459),
        # as an update scaler fed the loss through apply_gradients(value=...).
        # Sits OUTSIDE MultiSteps so it composes with grad accumulation (the
        # reference runs ReduceLROnPlateau together with --ga_steps and steps
        # the scheduler once per data iteration, :100,444-459): the plateau
        # state sees every micro-step's loss; on accumulation micro-steps the
        # emitted updates are zero and scaling them is a no-op.
        from optax import contrib
        tx = optax.chain(optax.with_extra_args_support(tx),
                         contrib.reduce_on_plateau(
                             factor=cfg.plateau_factor,
                             patience=cfg.plateau_patience,
                             cooldown=cfg.plateau_cooldown,
                             min_scale=cfg.plateau_min_scale))
    return optax.with_extra_args_support(tx)


def make_scanned_steps(step_body: Callable):
    """Lift ``step_body(state, *xs_i) -> (state, metrics)`` into ONE jitted
    program running k steps via ``lax.scan`` over stacked per-step inputs
    (each leaf of ``xs`` has a leading k axis). Per-dispatch host overhead
    (20ms-class through remote-device tunnels) amortizes over k, and the
    interior state handoffs never touch the host — the TPU analogue of a
    captured CUDA graph replay. Returns the LAST step's metrics plus
    ``loss_mean`` over the k steps."""

    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def steps(state, xs):
        state, ms = jax.lax.scan(lambda st, x: step_body(st, *x), state, xs)
        metrics = jax.tree.map(lambda a: a[-1], ms)
        metrics["loss_mean"] = jnp.mean(ms["loss"])
        return state, metrics

    return steps


_JIT_STEP_CACHE: dict = {}


def jit_step(body, state=None, *, donate_argnums=(0,)):
    """jit a ``(state, *batch) -> (state, metrics)`` step body, pinning the
    returned state's shardings to the input ``state``'s when it is given.

    Without the pin, GSPMD freely propagates shardings onto output leaves
    whose inputs the partition rules left replicated (a size-1-fallback
    bias next to a tp-sharded kernel, a conv_out kernel whose own dims
    don't divide). The step's state sharding then has no fixed point: the
    first call returns differently-sharded leaves, so the second call
    compiles a SECOND executable, and — because a replicated input buffer
    cannot alias a sharded output — every mismatched donated leaf silently
    loses donation, keeping the old state live in HBM (the graftir donation
    audit counts exactly this). Metrics stay unpinned — every trainer's
    metrics are scalars, replicated either way.

    Memoized on (body, shardings): the step-body factories are lru_cached on
    (model, dtype, ...), so two equal-config trainers pass the SAME body
    object and the same sharding tree — they must get the same jitted
    wrapper back, or the second trainer's first step recompiles the whole
    program (~5 s) for a byte-identical executable."""
    if state is None:
        key = (body, donate_argnums)
        out_shardings = None
    else:
        shardings = jax.tree.map(lambda x: x.sharding, state)
        leaves, treedef = jax.tree.flatten(shardings)
        key = (body, donate_argnums, treedef, tuple(leaves))
        out_shardings = (shardings, None)
    fn = _JIT_STEP_CACHE.get(key)
    if fn is None:
        if out_shardings is None:
            fn = jax.jit(body, donate_argnums=donate_argnums)
        else:
            fn = jax.jit(body, donate_argnums=donate_argnums,
                         out_shardings=out_shardings)
        # bound the cache: un-memoized bodies (the vqgan factories build a
        # fresh closure per trainer) would otherwise pin dead executables
        # forever; insertion-order eviction keeps the recent/live ones
        while len(_JIT_STEP_CACHE) >= 256:
            _JIT_STEP_CACHE.pop(next(iter(_JIT_STEP_CACHE)))
        _JIT_STEP_CACHE[key] = fn
    return fn


def compute_dtype(precision) -> Any:
    """PrecisionConfig.compute → jnp dtype (None when already float32)."""
    name = getattr(precision, "compute", "float32")
    if name in ("float32", "f32", None):
        return None
    return jnp.dtype(name)


def cast_floating(tree, dtype):
    """Cast float leaves to ``dtype`` (params stay f32 in the optimizer; the
    cast copy feeds the forward — standard TPU mixed precision, replacing the
    reference's Apex AMP / DeepSpeed fp16 engine)."""
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x, tree)
