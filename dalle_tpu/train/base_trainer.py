"""Shared trainer shell: mesh resolution, host step/rng bookkeeping, the fit
loop with NaN rollback (reference fork vae.py:100-110 / dalle.py:148-151),
preflight + periodic checkpointing with rotation (legacy/train_dalle.py:547-594),
and throughput metering — one implementation for every model family."""

from __future__ import annotations

import math
import os
import time
from typing import Callable, Optional

import jax
import numpy as np

from ..chaos import step_hook as _chaos_step_hook
from ..config import TrainConfig
from ..obs import (DeviceTelemetry, StallWatchdog, export_chrome_trace,
                   export_spans_jsonl, span)
from ..obs import configure as obs_configure
from .checkpoints import CheckpointManager


def _fmt_metrics(m: dict) -> str:
    """One-line metric rendering for fit()'s log: numbers get %.5g, the
    graftpulse breach columns (strings: detector/group names) print as-is."""
    return " ".join(
        f"{k}={v:.5g}" if isinstance(v, (int, float))
        and not isinstance(v, bool) else f"{k}={v}"
        for k, v in m.items())


@jax.jit
def _tree_copy(t):
    """Bit-exact on-device copy with FRESH buffers: ``jnp.copy`` is never
    input-forwarded by jit, so the result survives a later donation of the
    source (the whole point of the device rollback snapshot). Module-level:
    one jit cache shared by every trainer — equal tree structures compile
    once per process, not once per trainer instance."""
    import jax.numpy as jnp
    return jax.tree.map(jnp.copy, t)


class BaseTrainer:
    """Owns (mesh, state, step fn, checkpoints, meter). Subclasses set
    ``self.state``, ``self.step_fn``-driven ``train_step``, and
    ``model_class`` for checkpoint metadata."""

    model_class = "Model"

    # class-level defaults so duck-typed subclasses that skip __init__ (the
    # test suite's host-only FakeTrainer) still satisfy the fit()/breakdown
    # machinery added after them
    _last_good_device = None
    _deferred_metrics = None
    _obs_last_h2d = 0.0
    _obs_last_ckpt = 0.0
    # graftpulse (obs/anomaly.py): built by fit() when ObsConfig.health is
    # set; every fetched metrics dict passes through _health_observe once
    health_sentry = None
    _health_last_step = -1
    # graftmend (docs/RESILIENCE.md): SIGTERM graceful-preemption latch and
    # the one-shot preemptive-snapshot rung (train/actions.py nan-precursor
    # action) — class-level so duck-typed FakeTrainers satisfy fit()
    _preempt = False
    preempted = False
    _preemptive_good = None
    _preemptive_good_device = None

    def __init__(self, train_cfg: TrainConfig, mesh=None, backend=None):
        self.train_cfg = train_cfg
        if mesh is None and backend is not None:
            mesh = backend.mesh
        if mesh is None:
            from ..parallel import build_mesh
            mesh = build_mesh(train_cfg.mesh)
        self.mesh = mesh
        self.backend = backend
        self.base_key = jax.random.PRNGKey(train_cfg.seed)
        self.ckpt = CheckpointManager(
            train_cfg.checkpoint_dir, keep_n=train_cfg.keep_n_checkpoints,
            async_save=getattr(train_cfg, "async_checkpointing", False))
        self._last_good = None   # host copy of (params, opt_state) for rollback
        self._last_good_device = None   # on-device copy (rollback_snapshot)
        self._host_step = 0      # host mirror of state.step: no device sync
        # grafttrace step-breakdown state (set by fit, consumed by
        # _finish_step; None dispatch-t0 = bare train_step outside fit)
        self._obs_dispatch_t0 = None
        self._obs_last_wait = 0.0
        self._obs_last_h2d = 0.0
        self._obs_last_ckpt = 0.0
        self._obs_wait_accum = 0.0
        self._obs_window_t0 = None
        self._obs_poll_bucket = -1
        self._telemetry = None
        self._deferred_metrics = None   # (step, device metrics) under defer
        self.last_watchdog = None
        # per-instance extras merged into checkpoint metadata, e.g. vae
        # identity for DALLE ckpts (reference legacy/train_dalle.py:535-582)
        self.extra_meta: dict = {}

    # subclasses implement train_step(*batch) -> metrics dict ---------------

    def _meta(self) -> dict:
        return {"hparams": self.model_cfg.to_dict(),
                "train": self.train_cfg.to_dict(),
                "model_class": self.model_class, **self.extra_meta}

    def restore(self, step: Optional[int] = None):
        """Resume model/opt/step from the checkpoint dir (reference
        legacy/train_dalle.py:249-272,531-532)."""
        self.state, meta = self.ckpt.restore(self.state, step)
        self._host_step = int(self.state.step)
        return meta

    def install_signal_checkpoint(self, log=print):
        """SIGUSR1 → checkpoint at the next step boundary (taming's "melk"
        handler, taming/main.py:544-557 — the signal only sets a flag; the
        save happens between steps where the state is consistent)."""
        import signal

        def handler(_sig, _frame):
            self._signal_save = True
            log("SIGUSR1: will checkpoint at the next step boundary")

        self._signal_save = False
        signal.signal(signal.SIGUSR1, handler)

    def install_preemption_handler(self, log=print):
        """SIGTERM → graceful preemption (the k8s/TPU-preemption contract,
        docs/RESILIENCE.md): the handler only latches flags; ``fit`` then
        finishes the in-flight step, forces a synchronous save through the
        SIGUSR1-latch path (which drains async checkpointing), and returns
        with ``self.preempted`` set so the CLI exits 0 with the state
        durable. A second SIGTERM during the wind-down is idempotent."""
        import signal

        def handler(_sig, _frame):
            self._signal_save = True
            self._preempt = True
            log("SIGTERM: graceful preemption — will checkpoint at the "
                "next step boundary and exit")

        self._preempt = False
        self.preempted = False
        # materialize the latch without clobbering a pending SIGUSR1 save
        self._signal_save = getattr(self, "_signal_save", False)
        signal.signal(signal.SIGTERM, handler)

    def _fetch_pending_metrics(self) -> dict:
        """Host-fetch the most recent step's device metrics (used when a save
        boundary lands on a metrics-skipped step, or to bypass the
        ``defer_metrics`` lag: nothing may be checkpointed without a NaN
        check of the CURRENT state)."""
        if getattr(self, "_pending_metrics", None) is None:
            return {}
        sync0 = time.perf_counter()
        with span("fit/sync", on_demand=True):
            metrics = {k: float(v) for k, v in
                       jax.device_get(self._pending_metrics).items()}
        # the same step's metrics are now consumed in-band — retire the
        # deferred copy so the next boundary doesn't re-emit them, but keep
        # its parked breakdown: dropping it would lose every t_* column
        # (and the once-consumed t_ckpt_s) whenever the save cadence
        # coincides with the metrics cadence
        if (self._deferred_metrics is not None
                and self._deferred_metrics[0] == self._host_step):
            part = self._deferred_metrics[2]
            self._deferred_metrics = None
            if part is not None:
                now = time.perf_counter()
                part["t_sync_s"] = now - sync0
                metrics.update(self._finish_breakdown(part, now))
        rep = self.meter.step(self._host_step)
        if rep:
            metrics.update(rep)
        return self._health_observe(self._host_step, metrics)

    def _health_observe(self, step: int, metrics: dict) -> dict:
        """Run the graftpulse sentry over one FETCHED metrics dict (host
        floats) exactly once per metrics step — every path that finalizes a
        record (in-band, deferred-consumed, save-boundary fetch, flushes)
        routes through here. Mutates ``metrics`` with breach columns."""
        sentry = self.health_sentry
        if sentry is None or not metrics or step == self._health_last_step:
            return metrics
        self._health_last_step = step
        sentry.observe(step, metrics)
        return metrics

    def _put(self, x, dtype=None, stacked: bool = False):
        """Convert one host batch leaf and place it on the mesh. A jax Array
        of the right dtype skips the ``np.asarray`` (which would drag it back
        to host) but still routes through the shard fn — ``device_put`` with
        the already-correct sharding is a no-op (the prefetch path stays
        zero-copy) while a direct caller's device array with some other
        placement gets resharded onto the mesh, matching the pre-prefetch
        semantics. A wrong-dtype device array pays the host round-trip the
        coercion always cost."""
        from ..parallel import shard_batch, shard_stacked_batch
        if not (isinstance(x, jax.Array)
                and (dtype is None or x.dtype == np.dtype(dtype))):
            x = np.asarray(x, dtype) if dtype is not None else np.asarray(x)
        return (shard_stacked_batch if stacked else shard_batch)(self.mesh, x)

    def _put_batch(self, batch: tuple, stacked: bool = False) -> tuple:
        """Convert + shard one fit() batch tuple exactly as ``train_step``
        would (dtype coercion included) — the hook the device prefetcher uses
        to move H2D off the critical path. The base implementation is the
        identity (host batches through, for trainers without a device path);
        real trainers override with their per-leaf dtypes."""
        return batch

    def _step_keys(self, k: int):
        """The exact per-step rng stream ``train_step`` would draw for the
        next k host steps — fold_in(base_key, host_step + i) — stacked for
        scanning. Single source of the scan/single rng-parity invariant
        (every trainer's ``train_steps`` must consume THIS stream)."""
        import jax.numpy as jnp
        return jnp.stack([jax.random.fold_in(self.base_key,
                                             self._host_step + i)
                          for i in range(k)])

    def _stack_batches(self, batches, k: int):
        """Group the batch stream into (stacked?, batch) pairs: full groups
        of k become stacked tuples for ``train_steps``; a final short group
        is yielded as plain single batches for ``train_step`` (which is
        already compiled — a (1, ...) stack would force one extra minutes-
        long compile of the scan program just to drain the tail). A group
        whose members disagree in shape (short batch mid-stream from
        drop_last=False loaders or webdataset ``batched(partial=True)``)
        also falls back to singles instead of crashing np.stack (warned
        once: if every group is ragged, scan_steps is effectively off)."""
        import itertools
        import warnings
        it = iter(batches)
        warned = False
        while True:
            group = list(itertools.islice(it, k))
            if not group:
                return
            homogeneous = all(
                len(b) == len(group[0]) and all(
                    np.shape(x) == np.shape(group[0][j])
                    for j, x in enumerate(b))
                for b in group)
            if len(group) < k or not homogeneous:
                if not homogeneous and not warned:
                    warnings.warn(
                        "scan_steps: batch group has mismatched shapes; "
                        "draining it as single steps (a loader with varying "
                        "batch shapes disables the scanned fast path)")
                    warned = True
                for b in group:
                    yield False, b
                if len(group) < k:
                    return
                continue
            yield True, tuple(np.stack(xs) for xs in zip(*group))

    def fit(self, batches, *, steps: Optional[int] = None, log=print,
            sample_fn: Optional[Callable[[int], None]] = None,
            metrics_writer=None,
            on_step: Optional[Callable[[int], None]] = None):
        """Epoch-agnostic loop over ``batches`` (iterable of tuples fed to
        ``train_step``) with the reference's parity behaviors.

        With ``train_cfg.scan_steps > 1`` full groups of k consecutive
        batches run through ``train_steps`` (k optimizer steps per device
        dispatch; the tail drains through ``train_step``); host-side events
        — metrics fetch, NaN check/rollback, checkpoint/log/sample cadence —
        then happen at k-step granularity. Cadences use boundary *crossing*
        (prev//N != cur//N), so a k that does not divide N stretches an
        event by at most k-1 steps, never to lcm(k, N); a NaN rollback
        rewinds the whole k-step group to the last good snapshot.

        Host-overlap layers (docs/PERFORMANCE.md): with
        ``train_cfg.device_prefetch > 0`` the next batches are converted and
        device_put through the trainer's ``_put_batch`` while the current
        step runs — note the lookahead means a fit() that exits on its
        ``steps`` budget has consumed up to ``device_prefetch`` extra
        batches from the iterator (callers sharing one iterator across fit
        calls should pass ``device_prefetch=0``); with
        ``train_cfg.async_checkpointing`` a mid-run save
        costs one device→host snapshot (the write overlaps following steps;
        SIGUSR1-latch saves and fit exit drain); with
        ``train_cfg.defer_metrics`` the metrics device_get reads the
        previous boundary's already-finished step (save boundaries still
        force a synchronous fetch — nothing is checkpointed without a NaN
        check of the current state).

        grafttrace (``train_cfg.obs``, docs/OBSERVABILITY.md): every
        iteration is a ``fit/step`` span nesting ``fit/batch_wait`` (time
        blocked on the batch iterator), ``fit/dispatch`` (host work + device
        dispatch), and ``fit/sync`` (the metrics device_get, inside
        ``_finish_step``); the same splits land in the metrics dict as a
        per-step breakdown with a data-starvation ratio. With
        ``obs.watchdog_deadline_s > 0`` a heartbeat watchdog reports stalls
        (open spans + thread stacks) instead of hanging silently; with
        ``obs.trace`` the span ring is exported as Perfetto-openable
        ``trace.json`` + ``spans.jsonl`` when the loop ends.

        graftmend (docs/RESILIENCE.md): every iteration passes through the
        chaos hook (``chaos.step_hook`` — a no-op ``None`` check unless a
        FaultPlan is installed); ``on_step(step)`` is called after each
        completed step (the elastic runtime's heartbeat point — exceptions
        it raises propagate, which is how an elastic worker aborts the loop
        on a membership change); and after
        :meth:`install_preemption_handler`, a SIGTERM finishes the in-
        flight step, forces a synchronous drained save, sets
        ``self.preempted`` and returns — callers then exit 0."""
        tc = self.train_cfg
        oc = getattr(tc, "obs", None)
        tracing = bool(oc is not None and oc.trace)
        if tracing:
            obs_configure(oc.ring_capacity)
        watchdog = None
        if oc is not None and oc.watchdog_deadline_s > 0:
            watchdog = StallWatchdog(
                oc.watchdog_deadline_s, log=log,
                dump_stacks=oc.watchdog_dump_stacks).start()
            self.last_watchdog = watchdog
        if (oc is not None and getattr(oc, "health", False)
                and self.health_sentry is None):
            # graftpulse sentry: watches the health/* columns the jitted
            # step now emits (trainers pass obs.health into their step-body
            # factories); breaches fire gauges/events/flight bundles and
            # annotate the record obs_report's MODEL-HEALTH verdict reads.
            # Kept across fit() calls so EMA baselines survive resume.
            from ..obs.anomaly import HealthSentry
            self.health_sentry = HealthSentry.from_obs_config(oc)
        scan_k = max(getattr(tc, "scan_steps", 1), 1)
        if scan_k > 1:
            assert hasattr(self, "train_steps"), (
                f"{type(self).__name__} has no train_steps; scan_steps needs "
                "the scanned multi-step API")
            batches = self._stack_batches(batches, scan_k)
        else:
            batches = ((False, b) for b in batches)
        prefetcher = None
        if getattr(tc, "device_prefetch", 0) > 0:
            # double-buffered device placement: the next `depth` batches are
            # converted + device_put (through the trainer's _put_batch, so
            # dtypes/shardings match train_step exactly) while the current
            # step runs — batch wait and H2D leave the critical path
            from ..data.device_prefetch import DevicePrefetcher
            prefetcher = DevicePrefetcher(
                batches,
                lambda item: (item[0], self._put_batch(item[1],
                                                       stacked=item[0])),
                depth=tc.device_prefetch)
            batches = prefetcher
        meta = self._meta()
        if tc.preflight_checkpoint:
            self.ckpt.preflight(self.state, meta)
        self._snapshot_good()

        def crossed(prev, cur, every):
            return every > 0 and prev // every != cur // every

        self._obs_wait_accum = 0.0
        self._obs_window_t0 = time.perf_counter()
        it = iter(batches)
        _END = object()
        try:
            while True:
                with span("fit/step") as step_span:
                    t_wait0 = time.perf_counter()
                    with span("fit/batch_wait"):
                        item = next(it, _END)
                    if item is _END:
                        break
                    self._obs_last_wait = time.perf_counter() - t_wait0
                    self._obs_wait_accum += self._obs_last_wait
                    self._obs_last_h2d = (prefetcher.last_put_s
                                          if prefetcher is not None else 0.0)
                    stacked, batch = item
                    step_call = self.train_steps if stacked else self.train_step
                    k_this = batch[0].shape[0] if stacked else 1
                    prev_step = self._host_step
                    step_span.set(step=prev_step)
                    # chaos injection point: kill/hang/slow/corrupt faults
                    # fire here, BEFORE the dispatch — "mid-step" from the
                    # run's point of view (the last durable save < this step)
                    _chaos_step_hook(prev_step)
                    self._obs_dispatch_t0 = time.perf_counter()
                    # profile the REAL step containing profile_step — no
                    # hidden extra update (the reference's flops profile also
                    # wraps a live step, legacy/train_dalle.py:492-499)
                    if tc.profile_step and prev_step < tc.profile_step <= prev_step + k_this:
                        logdir = f"{tc.checkpoint_dir}/profile_step{tc.profile_step}"
                        with jax.profiler.trace(logdir):
                            with span("fit/dispatch", profiled=True):
                                m = step_call(*batch)
                        log(f"[profile] step {self._host_step}: trace → {logdir}")
                    else:
                        with span("fit/dispatch"):
                            m = step_call(*batch)
                    step_num = self._host_step
                    if watchdog is not None:
                        watchdog.beat(step_num)
                    if on_step is not None:
                        on_step(step_num)
                    # latch the signal flag ONCE per iteration; a save
                    # decision must see the same value the metrics-fetch
                    # decision does
                    want_save = (crossed(prev_step, step_num, tc.save_every_steps) or
                                 getattr(self, "_signal_save", False))
                    # the step these metrics belong to: with defer_metrics the
                    # in-band dict is one boundary stale and tags itself
                    mstep = m.pop("metrics_step", step_num) if m else step_num
                    if want_save and (not m or mstep != step_num):
                        # the save's NaN gate must see the CURRENT step — any
                        # stale (deferred) record is flushed first, BEFORE the
                        # current step's, so writer steps stay monotonic
                        # (wandb silently drops out-of-order steps); then the
                        # live metrics are pulled
                        if m and metrics_writer is not None:
                            metrics_writer.log(mstep, m)
                        elif (not m and self._deferred_metrics is not None
                              and self._deferred_metrics[0] != step_num):
                            # save landed on a metrics-skipped step: an OLDER
                            # boundary's record is still parked — emit it now
                            # (a parked record of the current step is instead
                            # retired by _fetch_pending_metrics, which keeps
                            # its breakdown)
                            dstep, dm, dpart = self._deferred_metrics
                            self._deferred_metrics = None
                            dsync0 = time.perf_counter()
                            with span("fit/sync", on_demand=True):
                                dm = {k: float(v) for k, v in
                                      jax.device_get(dm).items()}
                            if dpart is not None:
                                dnow = time.perf_counter()
                                dpart["t_sync_s"] = dnow - dsync0
                                dm.update(self._finish_breakdown(dpart, dnow))
                            self._health_observe(dstep, dm)
                            if metrics_writer is not None:
                                metrics_writer.log(dstep, dm)
                        m = self._fetch_pending_metrics()
                        mstep = step_num
                    nan = bool(m) and tc.nan_rollback and not math.isfinite(
                        self._nan_check_value(m, log))
                    if nan:
                        log(f"[step {mstep}] NaN loss — rolling back to last good state")
                        self._rollback()
                    else:
                        if m and crossed(prev_step, step_num, tc.log_every):
                            log(f"[step {mstep}] " + _fmt_metrics(m))
                        if m and metrics_writer is not None:
                            metrics_writer.log(mstep, m)
                        if want_save:
                            signal_save = getattr(self, "_signal_save", False)
                            t_ckpt0 = time.perf_counter()
                            with span("fit/checkpoint", step=step_num):
                                # async manager: returns after the snapshot;
                                # the write overlaps the next steps. An
                                # operator-requested (SIGUSR1) save drains so
                                # the latch means "durable now". Metadata is
                                # re-evaluated per save: extra_meta can
                                # change mid-run (the gumbel re-anneal
                                # action records its rebase there) and the
                                # sidecar must carry the CURRENT values
                                self.ckpt.save(step_num, self.state,
                                               self._meta())
                                if signal_save:
                                    self._ckpt_wait()
                                self._snapshot_good()
                            self._obs_last_ckpt = time.perf_counter() - t_ckpt0
                            self._signal_save = False
                            if (getattr(tc, "log_artifacts", False)
                                    and metrics_writer is not None
                                    and hasattr(metrics_writer, "log_artifact")):
                                # the upload reads the step directory, so an
                                # in-flight async write must land first
                                self._ckpt_wait()
                                # only the just-written step's directory —
                                # uploading the whole checkpoint_dir would
                                # re-send every retained checkpoint each save
                                # (ref uploads the one new file,
                                # legacy/train_dalle.py:667-669)
                                metrics_writer.log_artifact(
                                    os.path.join(tc.checkpoint_dir, str(step_num)),
                                    name=f"trained-{self.model_class.lower()}",
                                    metadata={"step": step_num})
                        if want_save and getattr(self, "_preempt", False):
                            # SIGTERM wind-down: the save above ran through
                            # the signal-latch path (synchronous + drained),
                            # so the state is durable — exit the loop; the
                            # CLI then exits 0. A NaN at this boundary skips
                            # the save, so the latch stays set and the NEXT
                            # boundary (post-rollback, finite) winds down.
                            self.preempted = True
                            self._preempt = False
                            log(f"[step {step_num}] graceful preemption: "
                                f"checkpoint durable; exiting fit")
                        if sample_fn and crossed(prev_step, step_num,
                                                 getattr(tc, "sample_every_steps", 0)):
                            sample_fn(step_num)
                if self.preempted:
                    break
                # the steps budget must bound the loop even when steps go NaN
                if steps is not None and step_num >= steps:
                    break
        finally:
            self._obs_dispatch_t0 = None   # bare train_step: no breakdown
            if self._deferred_metrics is not None:
                # defer_metrics parks the final boundary's metrics — flush so
                # the run's last record isn't silently dropped
                fstep, fmetrics, fpart = self._deferred_metrics
                self._deferred_metrics = None
                try:
                    fsync0 = time.perf_counter()
                    with span("fit/sync", flush=True):
                        fm = {k: float(v) for k, v in
                              jax.device_get(fmetrics).items()}
                    if fpart is not None:
                        fnow = time.perf_counter()
                        fpart["t_sync_s"] = fnow - fsync0
                        fm.update(self._finish_breakdown(fpart, fnow))
                    self._health_observe(fstep, fm)
                    log(f"[step {fstep}] " + _fmt_metrics(fm))
                    if metrics_writer is not None:
                        metrics_writer.log(fstep, fm)
                except Exception:  # noqa: BLE001 - the flush is best-effort:
                    pass           # fit may be unwinding from a device error
            # drain in-flight async checkpoint writes: a fit() that returned
            # must leave durable checkpoints behind (duck-typed managers in
            # tests may not expose the drain)
            self._ckpt_wait()
            if watchdog is not None:
                watchdog.stop()
            if tracing:
                outdir = oc.trace_dir or os.path.join(tc.checkpoint_dir, "obs")
                os.makedirs(outdir, exist_ok=True)
                export_chrome_trace(os.path.join(outdir, "trace.json"))
                export_spans_jsonl(os.path.join(outdir, "spans.jsonl"))
        return self.state

    def _ckpt_wait(self):
        wait = getattr(self.ckpt, "wait_until_finished", None)
        if wait is not None:
            with span("ckpt/drain"):
                wait()

    def _nan_check_value(self, m: dict, log=print) -> float:
        """The scalar the NaN-rollback check inspects: ``loss`` when present
        (every in-repo trainer), else the first finite-checkable scalar — a
        metrics dict without one used to KeyError the whole fit loop. With
        nothing checkable the guard is inert (warned once)."""
        val = m.get("loss")
        if val is None:
            val = next((v for v in m.values()
                        if isinstance(v, (int, float))
                        and not isinstance(v, bool)), None)
            if val is None:
                if not getattr(self, "_warned_no_nan_scalar", False):
                    log("[nan-guard] metrics carry no 'loss' or other "
                        "finite-checkable scalar; NaN rollback is inactive")
                    self._warned_no_nan_scalar = True
                return 0.0   # finite → never triggers a rollback
        return val

    def _snapshot_mode(self, live) -> str:
        """Resolve ``rollback_snapshot`` ("auto" → "device"/"host"): the
        on-device copy doubles the (params, opt_state) HBM footprint, so auto
        only takes it when the allocator reports enough headroom (backends
        without a limit — CPU — always fit: "device" there is host RAM)."""
        mode = getattr(self.train_cfg, "rollback_snapshot", "host")
        if mode != "auto":
            return mode
        from ..obs import device_memory_headroom
        d0 = self.mesh.devices.flat[0]
        try:
            headroom = device_memory_headroom(d0)
        except Exception:  # noqa: BLE001 - stats API varies per backend;
            return "host"  # an unreadable gauge must not break training
        if headroom is None:
            return "device"

        # per-device snapshot bytes = what ONE device actually holds — the
        # sum of its shards. global/mesh_size would undercount replicated
        # leaves (a dp-only mesh replicates the whole tree on every device)
        def _on_d0(x):
            try:
                return sum(s.data.nbytes for s in x.addressable_shards
                           if s.device == d0)
            except Exception:  # noqa: BLE001 - conservative on exotic arrays
                return x.nbytes
        per_device = sum(_on_d0(x) for x in jax.tree.leaves(live))
        # 1.15× covers copy transients + rounding
        return "device" if per_device * 1.15 < headroom else "host"

    def _snapshot_good(self):
        # NaN loss is observed AFTER apply_gradients has run, so the optimizer
        # moments are poisoned too — snapshot and restore both (the reference
        # fork reloads the whole checkpoint, vae.py:100-110)
        live = (self.state.params, self.state.opt_state)
        self._last_good_shardings = jax.tree.map(lambda x: x.sharding, live)
        # free the PREVIOUS snapshot before the headroom gate and the copy:
        # gating with it still resident makes auto oscillate device/host on
        # alternating saves (the old snapshot eats exactly the headroom the
        # new one needs), and holding both through the copy would spike to
        # 3× the state footprint
        self._last_good_device = None
        # a fresh boundary snapshot supersedes any parked preemptive rung
        # (which is now the OLDER state — rolling back to it would discard
        # progress the boundary snapshot preserves)
        self._preemptive_good = None
        self._preemptive_good_device = None
        mode = self._snapshot_mode(live)
        with span("ckpt/snapshot_good", mode=mode):
            if mode == "device":
                # donated-safe on-device copy — no host fetch, which at
                # flagship scale is a multi-second device-idle window
                self._last_good_device = _tree_copy(live)
                self._last_good = None
            else:
                self._last_good = jax.device_get(live)
                self._last_good_device = None

    def take_preemptive_snapshot(self):
        """graftmend breach→action (train/actions.py): copy the CURRENT
        (params, opt_state) into a ONE-SHOT rung above the save-boundary
        snapshot. Fired on a nan-precursor breach — the classic divergence
        shape is inf-in-grads → loss NaN a few steps later, and without
        this rung the eventual rollback rewinds to the last save boundary,
        burning up to ``save_every_steps`` of progress. The first rollback
        consumes this rung (burn ≈ breach→NaN steps); if the restored
        state goes NaN again — the precursor state itself was already
        contaminated — the next rollback falls through to the durable
        boundary snapshot, so the ladder never loops on a poisoned rung.
        Same device/host placement policy as :meth:`_snapshot_good`."""
        live = (self.state.params, self.state.opt_state)
        self._preemptive_shardings = jax.tree.map(lambda x: x.sharding, live)
        self._preemptive_good = None
        self._preemptive_good_device = None
        mode = self._snapshot_mode(live)
        with span("ckpt/preemptive_snapshot", mode=mode):
            if mode == "device":
                self._preemptive_good_device = _tree_copy(live)
            else:
                self._preemptive_good = jax.device_get(live)

    def _rollback(self):
        # metrics computed from the poisoned state must die with it: a
        # parked (defer_metrics) NaN record would otherwise trigger a
        # second, spurious rollback at the next boundary, discarding the
        # good step just trained from the restored state
        self._deferred_metrics = None
        self._pending_metrics = None
        with span("ckpt/rollback"):
            if self._preemptive_good_device is not None:
                # one-shot rung: install directly (no defensive copy — the
                # rung is consumed; a repeat NaN falls to the boundary
                # snapshot below, never back here)
                restored, self._preemptive_good_device = (
                    self._preemptive_good_device, None)
            elif self._preemptive_good is not None:
                host, self._preemptive_good = self._preemptive_good, None
                restored = jax.tree.map(jax.device_put, host,
                                        self._preemptive_shardings)
            elif self._last_good_device is not None:
                # install a COPY: the restored tree becomes the live state and
                # gets donated into the next step — the snapshot itself must
                # stay valid in case that step goes NaN again
                restored = _tree_copy(self._last_good_device)
            elif self._last_good is not None:
                restored = jax.tree.map(jax.device_put, self._last_good,
                                        self._last_good_shardings)
            else:
                return
            params, opt_state = restored
            self.state = self.state.replace(params=params, opt_state=opt_state)

    def _finish_step(self, metrics) -> dict:
        """Post-step bookkeeping: advance the host step, pull metrics, attach
        the throughput report keyed on the POST-increment step so it lands in
        the same metrics dict fit() logs at ``log_every`` boundaries.

        With ``metrics_every > 1`` the device_get (a host↔device sync that
        stalls the step pipeline) only happens every N steps; other steps
        return an empty dict and fit() skips their NaN check / logging.

        Boundary steps additionally carry the grafttrace step breakdown
        (batch-wait/dispatch/sync splits, data-starvation ratio) and — at
        ``obs.device_poll_every`` cadence — the HBM and recompile gauges."""
        self._host_step += 1
        self._pending_metrics = metrics   # fit() fetches these on demand at
                                          # save boundaries (NaN-check gate)
        every = max(getattr(self.train_cfg, "metrics_every", 1), 1)
        if self._host_step % every != 0:
            return {}
        step_of = self._host_step
        defer = bool(getattr(self.train_cfg, "defer_metrics", False))
        part = None
        if defer:
            # one-boundary-delayed pull: hand back the PREVIOUS boundary's
            # metrics (that step has long finished — the device_get returns
            # without stalling the pipeline) and park this boundary's for the
            # next call. Records carry their true step via ``metrics_step``,
            # and the wait/dispatch/h2d timings are parked WITH the step they
            # describe so the record's columns all belong to metrics_step.
            part = self._partial_breakdown(time.perf_counter())
            parked, self._deferred_metrics = (self._deferred_metrics,
                                              (step_of, metrics, part))
            if parked is None:
                return {}
            step_of, metrics, part = parked
        sync0 = time.perf_counter()
        with span("fit/sync"):
            metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        rep = self.meter.step(self._host_step)
        if rep:
            metrics.update(rep)
        now = time.perf_counter()
        if defer:
            if part is not None:
                # the sync just paid IS this record's fetch — attribute it here
                part["t_sync_s"] = now - sync0
                metrics.update(self._finish_breakdown(part, now))
        else:
            metrics.update(self._step_breakdown(sync0, now))
        self._health_observe(step_of, metrics)
        if step_of != self._host_step:
            metrics["metrics_step"] = step_of
        return metrics

    def _step_breakdown(self, sync0: float, now: float) -> dict:
        """Where did the step go? batch wait vs dispatch vs sync, plus the
        waiting-on-data share of the whole window since the last report (so
        ``metrics_every``-skipped steps are covered) — 'input-bound vs
        compute-bound' as a logged metric instead of a guess. Device gauges
        (HBM used/peak, compiles, recompiles-per-100-steps) ride along every
        ``obs.device_poll_every`` steps, and the merged dict is mirrored to
        the Prometheus textfile when ``obs.prometheus_path`` is set. Only
        meaningful under fit(): a bare ``train_step()`` call has no
        batch-wait context and gets no breakdown."""
        out = self._partial_breakdown(sync0)
        if out is None:
            return {}
        out["t_sync_s"] = now - sync0
        return self._finish_breakdown(out, now)

    def _partial_breakdown(self, dispatch_end: float) -> Optional[dict]:
        """The per-step splits knowable at dispatch end (everything except
        the sync): wait/dispatch/h2d plus the previous boundary's checkpoint
        cost. None outside fit() (no batch-wait context)."""
        t0 = getattr(self, "_obs_dispatch_t0", None)
        if t0 is None:
            return None
        out = {"t_batch_wait_s": self._obs_last_wait,
               "t_dispatch_s": dispatch_end - t0,
               # host-side H2D enqueue cost of the consumed batch (0 without
               # device prefetch — the put then rides inside batch_wait)
               "t_h2d_s": self._obs_last_h2d}
        if self._obs_last_ckpt:
            # checkpoint dispatch cost of the PREVIOUS boundary (saves run
            # after metrics are fetched, so the cost lands one record late) —
            # obs_report accounts these steps as their own category
            out["t_ckpt_s"] = self._obs_last_ckpt
            self._obs_last_ckpt = 0.0
        return out

    def _finish_breakdown(self, out: dict, now: float) -> dict:
        """Windowed starvation ratio + device-gauge poll + Prometheus mirror,
        merged into ``out`` (the per-step splits)."""
        window_t0 = getattr(self, "_obs_window_t0", None)
        if window_t0 is not None and now > window_t0:
            out["data_starvation"] = min(self._obs_wait_accum / (now - window_t0), 1.0)
        self._obs_window_t0 = now
        self._obs_wait_accum = 0.0
        oc = getattr(self.train_cfg, "obs", None)
        if oc is not None and oc.device_poll_every > 0:
            bucket = self._host_step // oc.device_poll_every
            if bucket != getattr(self, "_obs_poll_bucket", -1):
                self._obs_poll_bucket = bucket
                if getattr(self, "_telemetry", None) is None:
                    self._telemetry = DeviceTelemetry()
                # gauges flow through the metrics dict only — mirroring them
                # into the tracer's gauge map would re-export every value a
                # second time under an obs.-prefixed alias in each record
                out.update(self._telemetry.poll(self._host_step))
                if oc.prometheus_path:
                    from ..obs import metrics_snapshot
                    from ..obs import write_textfile as prom_write
                    prom_write(oc.prometheus_path,
                               {**out, **metrics_snapshot(),
                                "host_step": self._host_step})
        return out
