"""Shared trainer shell: mesh resolution, host step/rng bookkeeping, the fit
loop with NaN rollback (reference fork vae.py:100-110 / dalle.py:148-151),
preflight + periodic checkpointing with rotation (legacy/train_dalle.py:547-594),
and throughput metering — one implementation for every model family."""

from __future__ import annotations

import math
import os
import time
from typing import Callable, Optional

import jax
import numpy as np

from ..config import TrainConfig
from ..obs import (DeviceTelemetry, StallWatchdog, export_chrome_trace,
                   export_spans_jsonl, span)
from ..obs import configure as obs_configure
from .checkpoints import CheckpointManager


class BaseTrainer:
    """Owns (mesh, state, step fn, checkpoints, meter). Subclasses set
    ``self.state``, ``self.step_fn``-driven ``train_step``, and
    ``model_class`` for checkpoint metadata."""

    model_class = "Model"

    def __init__(self, train_cfg: TrainConfig, mesh=None, backend=None):
        self.train_cfg = train_cfg
        if mesh is None and backend is not None:
            mesh = backend.mesh
        if mesh is None:
            from ..parallel import build_mesh
            mesh = build_mesh(train_cfg.mesh)
        self.mesh = mesh
        self.backend = backend
        self.base_key = jax.random.PRNGKey(train_cfg.seed)
        self.ckpt = CheckpointManager(train_cfg.checkpoint_dir,
                                      keep_n=train_cfg.keep_n_checkpoints)
        self._last_good = None   # host copy of (params, opt_state) for rollback
        self._host_step = 0      # host mirror of state.step: no device sync
        # grafttrace step-breakdown state (set by fit, consumed by
        # _finish_step; None dispatch-t0 = bare train_step outside fit)
        self._obs_dispatch_t0 = None
        self._obs_last_wait = 0.0
        self._obs_wait_accum = 0.0
        self._obs_window_t0 = None
        self._obs_poll_bucket = -1
        self._telemetry = None
        self.last_watchdog = None
        # per-instance extras merged into checkpoint metadata, e.g. vae
        # identity for DALLE ckpts (reference legacy/train_dalle.py:535-582)
        self.extra_meta: dict = {}

    # subclasses implement train_step(*batch) -> metrics dict ---------------

    def _meta(self) -> dict:
        return {"hparams": self.model_cfg.to_dict(),
                "train": self.train_cfg.to_dict(),
                "model_class": self.model_class, **self.extra_meta}

    def restore(self, step: Optional[int] = None):
        """Resume model/opt/step from the checkpoint dir (reference
        legacy/train_dalle.py:249-272,531-532)."""
        self.state, meta = self.ckpt.restore(self.state, step)
        self._host_step = int(self.state.step)
        return meta

    def install_signal_checkpoint(self, log=print):
        """SIGUSR1 → checkpoint at the next step boundary (taming's "melk"
        handler, taming/main.py:544-557 — the signal only sets a flag; the
        save happens between steps where the state is consistent)."""
        import signal

        def handler(_sig, _frame):
            self._signal_save = True
            log("SIGUSR1: will checkpoint at the next step boundary")

        self._signal_save = False
        signal.signal(signal.SIGUSR1, handler)

    def _fetch_pending_metrics(self) -> dict:
        """Host-fetch the most recent step's device metrics (used when a save
        boundary lands on a metrics-skipped step: nothing may be checkpointed
        without a NaN check)."""
        if getattr(self, "_pending_metrics", None) is None:
            return {}
        with span("fit/sync", on_demand=True):
            metrics = {k: float(v) for k, v in
                       jax.device_get(self._pending_metrics).items()}
        rep = self.meter.step(self._host_step)
        if rep:
            metrics.update(rep)
        return metrics

    def _step_keys(self, k: int):
        """The exact per-step rng stream ``train_step`` would draw for the
        next k host steps — fold_in(base_key, host_step + i) — stacked for
        scanning. Single source of the scan/single rng-parity invariant
        (every trainer's ``train_steps`` must consume THIS stream)."""
        import jax.numpy as jnp
        return jnp.stack([jax.random.fold_in(self.base_key,
                                             self._host_step + i)
                          for i in range(k)])

    def _stack_batches(self, batches, k: int):
        """Group the batch stream into (stacked?, batch) pairs: full groups
        of k become stacked tuples for ``train_steps``; a final short group
        is yielded as plain single batches for ``train_step`` (which is
        already compiled — a (1, ...) stack would force one extra minutes-
        long compile of the scan program just to drain the tail). A group
        whose members disagree in shape (short batch mid-stream from
        drop_last=False loaders or webdataset ``batched(partial=True)``)
        also falls back to singles instead of crashing np.stack (warned
        once: if every group is ragged, scan_steps is effectively off)."""
        import itertools
        import warnings
        it = iter(batches)
        warned = False
        while True:
            group = list(itertools.islice(it, k))
            if not group:
                return
            homogeneous = all(
                len(b) == len(group[0]) and all(
                    np.shape(x) == np.shape(group[0][j])
                    for j, x in enumerate(b))
                for b in group)
            if len(group) < k or not homogeneous:
                if not homogeneous and not warned:
                    warnings.warn(
                        "scan_steps: batch group has mismatched shapes; "
                        "draining it as single steps (a loader with varying "
                        "batch shapes disables the scanned fast path)")
                    warned = True
                for b in group:
                    yield False, b
                if len(group) < k:
                    return
                continue
            yield True, tuple(np.stack(xs) for xs in zip(*group))

    def fit(self, batches, *, steps: Optional[int] = None, log=print,
            sample_fn: Optional[Callable[[int], None]] = None,
            metrics_writer=None):
        """Epoch-agnostic loop over ``batches`` (iterable of tuples fed to
        ``train_step``) with the reference's parity behaviors.

        With ``train_cfg.scan_steps > 1`` full groups of k consecutive
        batches run through ``train_steps`` (k optimizer steps per device
        dispatch; the tail drains through ``train_step``); host-side events
        — metrics fetch, NaN check/rollback, checkpoint/log/sample cadence —
        then happen at k-step granularity. Cadences use boundary *crossing*
        (prev//N != cur//N), so a k that does not divide N stretches an
        event by at most k-1 steps, never to lcm(k, N); a NaN rollback
        rewinds the whole k-step group to the last good snapshot.

        grafttrace (``train_cfg.obs``, docs/OBSERVABILITY.md): every
        iteration is a ``fit/step`` span nesting ``fit/batch_wait`` (time
        blocked on the batch iterator), ``fit/dispatch`` (host work + device
        dispatch), and ``fit/sync`` (the metrics device_get, inside
        ``_finish_step``); the same splits land in the metrics dict as a
        per-step breakdown with a data-starvation ratio. With
        ``obs.watchdog_deadline_s > 0`` a heartbeat watchdog reports stalls
        (open spans + thread stacks) instead of hanging silently; with
        ``obs.trace`` the span ring is exported as Perfetto-openable
        ``trace.json`` + ``spans.jsonl`` when the loop ends."""
        tc = self.train_cfg
        oc = getattr(tc, "obs", None)
        tracing = bool(oc is not None and oc.trace)
        if tracing:
            obs_configure(oc.ring_capacity)
        watchdog = None
        if oc is not None and oc.watchdog_deadline_s > 0:
            watchdog = StallWatchdog(
                oc.watchdog_deadline_s, log=log,
                dump_stacks=oc.watchdog_dump_stacks).start()
            self.last_watchdog = watchdog
        scan_k = max(getattr(tc, "scan_steps", 1), 1)
        if scan_k > 1:
            assert hasattr(self, "train_steps"), (
                f"{type(self).__name__} has no train_steps; scan_steps needs "
                "the scanned multi-step API")
            batches = self._stack_batches(batches, scan_k)
        else:
            batches = ((False, b) for b in batches)
        meta = self._meta()
        if tc.preflight_checkpoint:
            self.ckpt.preflight(self.state, meta)
        self._snapshot_good()

        def crossed(prev, cur, every):
            return every > 0 and prev // every != cur // every

        self._obs_wait_accum = 0.0
        self._obs_window_t0 = time.perf_counter()
        it = iter(batches)
        _END = object()
        try:
            while True:
                with span("fit/step") as step_span:
                    t_wait0 = time.perf_counter()
                    with span("fit/batch_wait"):
                        item = next(it, _END)
                    if item is _END:
                        break
                    self._obs_last_wait = time.perf_counter() - t_wait0
                    self._obs_wait_accum += self._obs_last_wait
                    stacked, batch = item
                    step_call = self.train_steps if stacked else self.train_step
                    k_this = batch[0].shape[0] if stacked else 1
                    prev_step = self._host_step
                    step_span.set(step=prev_step)
                    self._obs_dispatch_t0 = time.perf_counter()
                    # profile the REAL step containing profile_step — no
                    # hidden extra update (the reference's flops profile also
                    # wraps a live step, legacy/train_dalle.py:492-499)
                    if tc.profile_step and prev_step < tc.profile_step <= prev_step + k_this:
                        logdir = f"{tc.checkpoint_dir}/profile_step{tc.profile_step}"
                        with jax.profiler.trace(logdir):
                            with span("fit/dispatch", profiled=True):
                                m = step_call(*batch)
                        log(f"[profile] step {self._host_step}: trace → {logdir}")
                    else:
                        with span("fit/dispatch"):
                            m = step_call(*batch)
                    step_num = self._host_step
                    if watchdog is not None:
                        watchdog.beat(step_num)
                    # latch the signal flag ONCE per iteration; a save
                    # decision must see the same value the metrics-fetch
                    # decision does
                    want_save = (crossed(prev_step, step_num, tc.save_every_steps) or
                                 getattr(self, "_signal_save", False))
                    if not m and want_save:
                        m = self._fetch_pending_metrics()
                    nan = bool(m) and tc.nan_rollback and not math.isfinite(
                        self._nan_check_value(m, log))
                    if nan:
                        log(f"[step {step_num}] NaN loss — rolling back to last good state")
                        self._rollback()
                    else:
                        if m and crossed(prev_step, step_num, tc.log_every):
                            log(f"[step {step_num}] " +
                                " ".join(f"{k}={v:.5g}" for k, v in m.items()))
                        if m and metrics_writer is not None:
                            metrics_writer.log(step_num, m)
                        if want_save:
                            with span("fit/checkpoint", step=step_num):
                                self.ckpt.save(step_num, self.state, meta)
                                self._snapshot_good()
                            self._signal_save = False
                            if (getattr(tc, "log_artifacts", False)
                                    and metrics_writer is not None
                                    and hasattr(metrics_writer, "log_artifact")):
                                # only the just-written step's directory —
                                # uploading the whole checkpoint_dir would
                                # re-send every retained checkpoint each save
                                # (ref uploads the one new file,
                                # legacy/train_dalle.py:667-669)
                                metrics_writer.log_artifact(
                                    os.path.join(tc.checkpoint_dir, str(step_num)),
                                    name=f"trained-{self.model_class.lower()}",
                                    metadata={"step": step_num})
                        if sample_fn and crossed(prev_step, step_num,
                                                 getattr(tc, "sample_every_steps", 0)):
                            sample_fn(step_num)
                # the steps budget must bound the loop even when steps go NaN
                if steps is not None and step_num >= steps:
                    break
        finally:
            self._obs_dispatch_t0 = None   # bare train_step: no breakdown
            if watchdog is not None:
                watchdog.stop()
            if tracing:
                outdir = oc.trace_dir or os.path.join(tc.checkpoint_dir, "obs")
                os.makedirs(outdir, exist_ok=True)
                export_chrome_trace(os.path.join(outdir, "trace.json"))
                export_spans_jsonl(os.path.join(outdir, "spans.jsonl"))
        return self.state

    def _nan_check_value(self, m: dict, log=print) -> float:
        """The scalar the NaN-rollback check inspects: ``loss`` when present
        (every in-repo trainer), else the first finite-checkable scalar — a
        metrics dict without one used to KeyError the whole fit loop. With
        nothing checkable the guard is inert (warned once)."""
        val = m.get("loss")
        if val is None:
            val = next((v for v in m.values()
                        if isinstance(v, (int, float))
                        and not isinstance(v, bool)), None)
            if val is None:
                if not getattr(self, "_warned_no_nan_scalar", False):
                    log("[nan-guard] metrics carry no 'loss' or other "
                        "finite-checkable scalar; NaN rollback is inactive")
                    self._warned_no_nan_scalar = True
                return 0.0   # finite → never triggers a rollback
        return val

    def _snapshot_good(self):
        # NaN loss is observed AFTER apply_gradients has run, so the optimizer
        # moments are poisoned too — snapshot and restore both (the reference
        # fork reloads the whole checkpoint, vae.py:100-110)
        live = (self.state.params, self.state.opt_state)
        self._last_good = jax.device_get(live)
        self._last_good_shardings = jax.tree.map(lambda x: x.sharding, live)

    def _rollback(self):
        if self._last_good is not None:
            restored = jax.tree.map(jax.device_put, self._last_good,
                                    self._last_good_shardings)
            params, opt_state = restored
            self.state = self.state.replace(params=params, opt_state=opt_state)

    def _finish_step(self, metrics) -> dict:
        """Post-step bookkeeping: advance the host step, pull metrics, attach
        the throughput report keyed on the POST-increment step so it lands in
        the same metrics dict fit() logs at ``log_every`` boundaries.

        With ``metrics_every > 1`` the device_get (a host↔device sync that
        stalls the step pipeline) only happens every N steps; other steps
        return an empty dict and fit() skips their NaN check / logging.

        Boundary steps additionally carry the grafttrace step breakdown
        (batch-wait/dispatch/sync splits, data-starvation ratio) and — at
        ``obs.device_poll_every`` cadence — the HBM and recompile gauges."""
        self._host_step += 1
        self._pending_metrics = metrics   # fit() fetches these on demand at
                                          # save boundaries (NaN-check gate)
        every = max(getattr(self.train_cfg, "metrics_every", 1), 1)
        if self._host_step % every != 0:
            return {}
        sync0 = time.perf_counter()
        with span("fit/sync"):
            metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        rep = self.meter.step(self._host_step)
        if rep:
            metrics.update(rep)
        metrics.update(self._step_breakdown(sync0, time.perf_counter()))
        return metrics

    def _step_breakdown(self, sync0: float, now: float) -> dict:
        """Where did the step go? batch wait vs dispatch vs sync, plus the
        waiting-on-data share of the whole window since the last report (so
        ``metrics_every``-skipped steps are covered) — 'input-bound vs
        compute-bound' as a logged metric instead of a guess. Device gauges
        (HBM used/peak, compiles, recompiles-per-100-steps) ride along every
        ``obs.device_poll_every`` steps, and the merged dict is mirrored to
        the Prometheus textfile when ``obs.prometheus_path`` is set. Only
        meaningful under fit(): a bare ``train_step()`` call has no
        batch-wait context and gets no breakdown."""
        t0 = getattr(self, "_obs_dispatch_t0", None)
        if t0 is None:
            return {}
        out = {"t_batch_wait_s": self._obs_last_wait,
               "t_dispatch_s": sync0 - t0,
               "t_sync_s": now - sync0}
        window_t0 = getattr(self, "_obs_window_t0", None)
        if window_t0 is not None and now > window_t0:
            out["data_starvation"] = min(self._obs_wait_accum / (now - window_t0), 1.0)
        self._obs_window_t0 = now
        self._obs_wait_accum = 0.0
        oc = getattr(self.train_cfg, "obs", None)
        if oc is not None and oc.device_poll_every > 0:
            bucket = self._host_step // oc.device_poll_every
            if bucket != getattr(self, "_obs_poll_bucket", -1):
                self._obs_poll_bucket = bucket
                if getattr(self, "_telemetry", None) is None:
                    self._telemetry = DeviceTelemetry()
                # gauges flow through the metrics dict only — mirroring them
                # into the tracer's gauge map would re-export every value a
                # second time under an obs.-prefixed alias in each record
                out.update(self._telemetry.poll(self._host_step))
                if oc.prometheus_path:
                    from ..obs import metrics_snapshot
                    from ..obs import write_textfile as prom_write
                    prom_write(oc.prometheus_path,
                               {**out, **metrics_snapshot(),
                                "host_step": self._host_step})
        return out
