"""DALL·E trainer — one jitted SPMD train step + host-side epoch loop.

Reference call stack: legacy/train_dalle.py (SURVEY.md §3.1) — epoch loop with
gradient clipping, grad accumulation via the DeepSpeed engine, loss averaging
over workers (`average_all`, :622), periodic checkpointing with rotation
(:547-550), preflight checkpoint (:591-594), periodic in-training sampling
(:639-649), throughput meter (:601-602,651-654), plus the fork's NaN rollback
(dalle.py:148-151).

TPU design mirrors trainer_vae: the entire step — CFG text dropout, loss,
grads, gradient psum over the dp/fsdp axes (inserted by the SPMD partitioner
from the shardings), clip, optimizer — is ONE jitted function with the state
donated, so params update in place in HBM. Grad accumulation is an optax
MultiSteps transform inside the same program rather than an engine feature.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import DalleConfig, TrainConfig
from ..models.dalle import DALLE, init_dalle
from ..parallel import shard_batch, shard_params
from .checkpoints import CheckpointManager
from .metrics import ThroughputMeter, count_params, transformer_train_flops
from .train_state import TrainState, make_optimizer


def make_dalle_train_step(model: DALLE, *, null_cond_prob: float = 0.0,
                          use_dropout: bool = False):
    """Returns step(state, text, image_ids, key) -> (state, metrics). jit-once;
    ``null_cond_prob``/``use_dropout`` are compile-time (they select rng wiring)."""

    def loss_fn(params, text, image_ids, key):
        rngs = {}
        if null_cond_prob > 0:
            rngs["cfg"] = jax.random.fold_in(key, 0)
        if use_dropout:
            rngs["dropout"] = jax.random.fold_in(key, 1)
        loss, aux = model.apply(params, text, image_ids, return_loss=True,
                                null_cond_prob=null_cond_prob,
                                deterministic=not use_dropout,
                                rngs=rngs or None)
        return loss, aux

    @jax.jit
    def step(state: TrainState, text, image_ids, key):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, text, image_ids, key)
        new_state = state.apply_gradients(grads)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads), **aux}
        return new_state, metrics

    return step


class DalleTrainer:
    """Owns (model, sharded state, step fn, checkpoints, meter). Consumes
    batches of (text ids, image codebook ids); raw pixels are tokenized by the
    caller through a VAEAdapter (the reference tokenizes inside DALLE.forward,
    :590-597 — here the vae is upstream of the hot loop so the train step stays
    a pure text+ids program)."""

    def __init__(self, model_cfg: DalleConfig, train_cfg: TrainConfig,
                 mesh=None, backend=None, null_cond_prob: float = 0.0):
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        if mesh is None and backend is not None:
            mesh = backend.mesh
        if mesh is None:
            from ..parallel import build_mesh
            mesh = build_mesh(train_cfg.mesh)
        self.mesh = mesh
        self.backend = backend

        key = jax.random.PRNGKey(train_cfg.seed)
        self.model, params = init_dalle(model_cfg, key)
        params = shard_params(mesh, params)
        tx = make_optimizer(train_cfg.optim)
        self.state = TrainState.create(apply_fn=self.model.apply, params=params,
                                       tx=tx)
        use_dropout = (model_cfg.attn_dropout > 0 or model_cfg.ff_dropout > 0)
        self.step_fn = make_dalle_train_step(
            self.model, null_cond_prob=null_cond_prob, use_dropout=use_dropout)
        self.base_key = key
        self.ckpt = CheckpointManager(train_cfg.checkpoint_dir,
                                      keep_n=train_cfg.keep_n_checkpoints)
        self._last_good = None
        self._host_step = 0

        n = count_params(self.state.params)
        self.num_params = n
        tokens_per_sample = model_cfg.total_seq_len
        self.meter = ThroughputMeter(
            train_cfg.batch_size, train_cfg.log_every,
            tokens_per_sample=tokens_per_sample,
            flops_per_step=transformer_train_flops(
                n, train_cfg.batch_size * tokens_per_sample),
            num_chips=mesh.size)

    def restore(self, step: Optional[int] = None):
        """Resume model/opt/step from the checkpoint dir (reference
        legacy/train_dalle.py:249-272,531-532)."""
        self.state, meta = self.ckpt.restore(self.state, step)
        self._host_step = int(self.state.step)
        return meta

    # -- single step ---------------------------------------------------------
    def train_step(self, text: np.ndarray, image_ids: np.ndarray):
        step_num = self._host_step
        key = jax.random.fold_in(self.base_key, step_num)
        text = shard_batch(self.mesh, np.asarray(text, np.int32))
        image_ids = shard_batch(self.mesh, np.asarray(image_ids, np.int32))
        self.state, metrics = self.step_fn(self.state, text, image_ids, key)
        self._host_step += 1
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        rep = self.meter.step(step_num)
        if rep:
            metrics.update(rep)
        return metrics

    # -- full loop with parity behaviors --------------------------------------
    def fit(self, batches, *, steps: Optional[int] = None, log=print,
            sample_fn: Optional[Callable[[int], None]] = None):
        tc = self.train_cfg
        meta = {"hparams": self.model_cfg.to_dict(), "train": tc.to_dict(),
                "model_class": "DALLE"}
        if tc.preflight_checkpoint:
            self.ckpt.preflight(self.state, meta)
        self._snapshot_good()
        for text, image_ids in batches:
            m = self.train_step(text, image_ids)
            step_num = self._host_step
            if tc.nan_rollback and not math.isfinite(m["loss"]):
                log(f"[step {step_num}] NaN loss — rolling back to last good state")
                self._rollback()
                continue
            if step_num % tc.log_every == 0:
                log(f"[step {step_num}] " +
                    " ".join(f"{k}={v:.5g}" for k, v in m.items()))
            if step_num % tc.save_every_steps == 0:
                self.ckpt.save(step_num, self.state, meta)
                self._snapshot_good()
            if tc.sample_every_steps and sample_fn and \
                    step_num % tc.sample_every_steps == 0:
                sample_fn(step_num)
            if steps is not None and step_num >= steps:
                break
        return self.state

    def _snapshot_good(self):
        live = (self.state.params, self.state.opt_state)
        self._last_good = jax.device_get(live)
        self._last_good_shardings = jax.tree.map(lambda x: x.sharding, live)

    def _rollback(self):
        if self._last_good is not None:
            restored = jax.tree.map(jax.device_put, self._last_good,
                                    self._last_good_shardings)
            params, opt_state = restored
            self.state = self.state.replace(params=params, opt_state=opt_state)
