"""DALL·E trainer — one jitted SPMD train step + host-side epoch loop.

Reference call stack: legacy/train_dalle.py (SURVEY.md §3.1) — epoch loop with
gradient clipping, grad accumulation via the DeepSpeed engine, loss averaging
over workers (`average_all`, :622), periodic checkpointing with rotation
(:547-550), preflight checkpoint (:591-594), periodic in-training sampling
(:639-649), throughput meter (:601-602,651-654), plus the fork's NaN rollback
(dalle.py:148-151).

TPU design mirrors trainer_vae: the entire step — CFG text dropout, loss,
grads, gradient psum over the dp/fsdp axes (inserted by the SPMD partitioner
from the shardings), clip, optimizer — is ONE jitted function with the state
donated, so params update in place in HBM. Grad accumulation is an optax
MultiSteps transform inside the same program rather than an engine feature.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import DalleConfig, TrainConfig
from ..models.dalle import DALLE, init_dalle
from ..obs import span
from ..parallel import commit_to_mesh, shard_params
from .base_trainer import BaseTrainer
from .metrics import ThroughputMeter, count_params, transformer_train_flops
from .train_state import (TrainState, cast_floating, compute_dtype,
                          jit_step, make_optimizer)


def _make_dalle_loss_fn(model: DALLE, *, null_cond_prob: float,
                        use_dropout: bool, dtype):
    def loss_fn(params, text, image_ids, key):
        rngs = {}
        if null_cond_prob > 0:
            rngs["cfg"] = jax.random.fold_in(key, 0)
        if use_dropout:
            rngs["dropout"] = jax.random.fold_in(key, 1)
        loss, aux = model.apply(cast_floating(params, dtype), text, image_ids,
                                return_loss=True,
                                null_cond_prob=null_cond_prob,
                                deterministic=not use_dropout,
                                rngs=rngs or None)
        return loss, aux

    return loss_fn


@functools.lru_cache(maxsize=64)
def _dalle_step_body(model: DALLE, *, null_cond_prob: float = 0.0,
                     use_dropout: bool = False, dtype=None,
                     health: bool = False, health_depth: int = 1):
    # memoized on (model-config, rng wiring, dtype, health wiring) so
    # equal-config trainers hand jit_step the SAME body object and share one
    # jitted wrapper. ``health`` fuses the graftpulse per-layer-group taps
    # (obs/health.py) into the program — scalars in the metrics dict, zero
    # added host syncs.
    loss_fn = _make_dalle_loss_fn(model, null_cond_prob=null_cond_prob,
                                  use_dropout=use_dropout, dtype=dtype)

    def step(state: TrainState, text, image_ids, key):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, text, image_ids, key)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads), **aux}
        if health:
            from ..obs.health import tree_health
            new_state, updates = state.apply_gradients(grads, value=loss,
                                                       return_updates=True)
            metrics.update(tree_health(grads, new_state.params, updates,
                                       depth=health_depth))
        else:
            new_state = state.apply_gradients(grads, value=loss)
        return new_state, metrics

    return step


def make_dalle_train_step(model: DALLE, *, null_cond_prob: float = 0.0,
                          use_dropout: bool = False, dtype=None, state=None,
                          health: bool = False, health_depth: int = 1):
    """Returns step(state, text, image_ids, key) -> (state, metrics). jit-once
    (the (body, shardings)-memoized train_state.jit_step) with the state
    donated; ``null_cond_prob``/``use_dropout`` are compile-time (they select
    rng wiring). ``state`` pins the output state's shardings to the input's —
    see jit_step. ``dtype`` (e.g. bf16) is the compute precision: params are
    cast inside the step, master copies stay f32 — the TPU-native replacement
    for the DeepSpeed fp16 engine (SURVEY.md §2.9 Apex AMP row)."""
    return jit_step(_dalle_step_body(model, null_cond_prob=null_cond_prob,
                                     use_dropout=use_dropout, dtype=dtype,
                                     health=health,
                                     health_depth=health_depth),
                    state)


@functools.lru_cache(maxsize=64)
def make_dalle_train_multi_step(model: DALLE, *, null_cond_prob: float = 0.0,
                                use_dropout: bool = False, dtype=None,
                                health: bool = False, health_depth: int = 1):
    """k optimizer steps in ONE device program: ``lax.scan`` over the step
    body consuming a (k, b, ...) microbatch stack. Per-dispatch host overhead
    (20ms-class through remote-device tunnels) amortizes over k steps, and
    the k-1 interior state handoffs never touch the host — the TPU analogue
    of a captured CUDA graph replay. Math per step is BIT-identical to
    ``make_dalle_train_step``: the caller precomputes the exact single-step
    key stream (fold_in(base_key, host_step + i)) and it is scanned as an
    input, so toggling scan_steps never changes the rng trajectory even with
    null_cond_prob > 0 or dropout (same pattern as trainer_vae.train_steps)."""
    loss_fn = _make_dalle_loss_fn(model, null_cond_prob=null_cond_prob,
                                  use_dropout=use_dropout, dtype=dtype)

    @partial(jax.jit, donate_argnums=(0,))
    def steps(state: TrainState, texts, image_ids, keys):
        def body(state, xs):
            text, ids, key = xs
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, text, ids, key)
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads), **aux}
            if health:
                from ..obs.health import tree_health
                new_state, updates = state.apply_gradients(
                    grads, value=loss, return_updates=True)
                metrics.update(tree_health(grads, new_state.params, updates,
                                           depth=health_depth))
            else:
                new_state = state.apply_gradients(grads, value=loss)
            return new_state, metrics

        state, ms = jax.lax.scan(body, state, (texts, image_ids, keys))
        metrics = jax.tree.map(lambda x: x[-1], ms)   # last step's metrics
        metrics["loss_mean"] = jnp.mean(ms["loss"])
        return state, metrics

    return steps


class DalleTrainer(BaseTrainer):
    """Consumes batches of (text ids, image codebook ids); raw pixels are
    tokenized by the caller through a VAEAdapter (the reference tokenizes
    inside DALLE.forward, :590-597 — here the vae is upstream of the hot loop
    so the train step stays a pure text+ids program)."""

    model_class = "DALLE"

    def __init__(self, model_cfg: DalleConfig, train_cfg: TrainConfig,
                 mesh=None, backend=None, null_cond_prob: float = 0.0):
        super().__init__(train_cfg, mesh=mesh, backend=backend)
        self.model_cfg = model_cfg

        sp = dict(self.mesh.shape).get("sp", 1)
        if sp > 1:
            sp_ok = {"full", "axial_row", "axial_col", "conv_like"}
            bad = set(model_cfg.attn_types or ("full",)) - sp_ok
            assert not bad, (
                f"sequence parallelism (sp > 1) supports attn_types {sp_ok}; "
                f"got unsupported {bad} (tabled 'sparse' masks need host-side "
                "block lists the ring cannot shard)")
        self.model, params = init_dalle(
            model_cfg, self.base_key, sp_mesh=self.mesh if sp > 1 else None)
        params = shard_params(self.mesh, params)
        tx = make_optimizer(train_cfg.optim)
        self.state = commit_to_mesh(self.mesh, TrainState.create(
            apply_fn=self.model.apply, params=params, tx=tx,
            lr_scale=1.0 if train_cfg.runtime_lr_scale else None))
        use_dropout = (model_cfg.attn_dropout > 0 or model_cfg.ff_dropout > 0)
        self.step_fn = make_dalle_train_step(
            self.model, null_cond_prob=null_cond_prob, use_dropout=use_dropout,
            dtype=compute_dtype(train_cfg.precision), state=self.state,
            health=bool(train_cfg.obs.health),
            health_depth=train_cfg.obs.health_group_depth)
        self._multi_step_kw = dict(null_cond_prob=null_cond_prob,
                                   use_dropout=use_dropout,
                                   dtype=compute_dtype(train_cfg.precision),
                                   health=bool(train_cfg.obs.health),
                                   health_depth=train_cfg.obs.health_group_depth)
        self._multi_step_fn = None   # built lazily on first train_steps()

        n = count_params(self.state.params)
        self.num_params = n
        tokens_per_sample = model_cfg.total_seq_len
        self.meter = ThroughputMeter(
            train_cfg.batch_size, train_cfg.log_every,
            tokens_per_sample=tokens_per_sample,
            flops_per_step=transformer_train_flops(
                n, train_cfg.batch_size * tokens_per_sample),
            num_chips=self.mesh.size)

    def _put_batch(self, batch, stacked: bool = False):
        """(text, image_ids) → int32 on the mesh (the device-prefetch hook;
        already-placed jax Arrays pass through untouched)."""
        text, image_ids = batch
        return (self._put(text, np.int32, stacked),
                self._put(image_ids, np.int32, stacked))

    # -- single step ---------------------------------------------------------
    def train_step(self, text: np.ndarray, image_ids: np.ndarray):
        key = jax.random.fold_in(self.base_key, self._host_step)
        with span("dalle/shard_batch"):
            text, image_ids = self._put_batch((text, image_ids))
        with span("dalle/step"):
            self.state, metrics = self.step_fn(self.state, text, image_ids, key)
        return self._finish_step(metrics)

    # -- k steps in one device program ---------------------------------------
    def train_steps(self, texts: np.ndarray, image_ids: np.ndarray):
        """Run ``k = texts.shape[0]`` optimizer steps from stacked (k, b, ...)
        microbatches in a single dispatched scan (see
        make_dalle_train_multi_step). Returns the last step's metrics dict
        plus ``loss_mean`` over the k steps; the host step advances by k."""
        assert texts.ndim == 3 and image_ids.ndim == 3, (
            "train_steps wants stacked (k, b, seq) microbatches")
        if self._multi_step_fn is None:
            self._multi_step_fn = make_dalle_train_multi_step(
                self.model, **self._multi_step_kw)
        k = texts.shape[0]
        keys = self._step_keys(k)
        with span("dalle/shard_batch", k=k):
            texts, image_ids = self._put_batch((texts, image_ids),
                                               stacked=True)
        with span("dalle/steps", k=k):
            self.state, metrics = self._multi_step_fn(self.state, texts,
                                                      image_ids, keys)
        self._host_step += k - 1     # _finish_step adds the final +1
        return self._finish_step(metrics)
