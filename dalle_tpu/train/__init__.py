from .train_state import TrainState, make_optimizer, make_lr_schedule
from .checkpoints import CheckpointManager
from .metrics import ThroughputMeter, device_peak_tflops, count_params, profile_trace
from .trainer_vae import VAETrainer, anneal_temperature, make_vae_train_step
from .trainer_vqgan import (VQGANTrainer, GANTrainState, make_vqgan_train_step,
                            LambdaWarmUpCosineScheduler)
from .trainer_clip import CLIPTrainer, make_clip_train_step
