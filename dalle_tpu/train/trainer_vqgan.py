"""VQGAN trainer — adversarial autoencoder training as two jitted SPMD steps.

Reference: the Lightning ``VQModel.training_step`` two-optimizer schedule
(taming/models/vqgan.py:83-131: AE vs discriminator by ``optimizer_idx``, Adam
β=(0.5, 0.9)), ``VQLPIPSWithDiscriminator`` (taming/modules/losses/
vqperceptual.py:34-136), and the GumbelVQ per-step temperature scheduler
(vqgan.py:279-303).

TPU design:
  * No optimizer_idx branching: each train step is ONE jitted function that
    runs the AE update then the discriminator update, so XLA fuses both
    backwards with the psum-by-sharding collectives.
  * The discriminator step reuses the generator's pre-update reconstruction
    (detached) instead of re-running encoder+decoder after the AE update —
    that second generator forward is pure HBM/MXU waste; Lightning only
    recomputes it because its loop can't share activations across
    optimizer_idx calls.
  * The adaptive disc weight is exact (grad w.r.t. the decoder's conv_out
    kernel, gan.py) — the extra backward stops at the stop-gradiented
    pre-output activation.
  * LPIPS params are frozen constants (taming keeps LPIPS in eval with no
    grads): they live in the state for checkpointing but no optimizer touches
    them.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import TrainConfig, VQGANConfig
from ..models.gan import (GANLossConfig, NLayerDiscriminator, adaptive_disc_weight,
                          adopt_weight, bce_with_quant_loss, hinge_d_loss,
                          vanilla_d_loss)
from ..models.lpips import LPIPS, init_lpips
from ..models.vqgan import VQModel, init_vqgan
from ..obs import span
from ..parallel import commit_to_mesh, shard_params
from .base_trainer import BaseTrainer
from .metrics import ThroughputMeter, count_params
from .train_state import (TrainState, cast_floating, compute_dtype,
                          jit_step, make_optimizer)


class LambdaWarmUpCosineScheduler:
    """Linear warmup then cosine decay multiplier
    (taming/lr_scheduler.py:4-33) — used by GumbelVQ's temperature schedule."""

    def __init__(self, warm_up_steps: int, lr_min: float, lr_max: float,
                 lr_start: float, max_decay_steps: int):
        self.warm_up_steps = warm_up_steps
        self.lr_min = lr_min
        self.lr_max = lr_max
        self.lr_start = lr_start
        self.max_decay_steps = max_decay_steps

    def __call__(self, n: int) -> float:
        if n < self.warm_up_steps:
            return ((self.lr_max - self.lr_start) / self.warm_up_steps * n
                    + self.lr_start)
        t = (n - self.warm_up_steps) / max(
            self.max_decay_steps - self.warm_up_steps, 1)
        t = min(t, 1.0)
        return self.lr_min + 0.5 * (self.lr_max - self.lr_min) * (
            1 + math.cos(t * math.pi))


@flax.struct.dataclass
class GANTrainState:
    """Generator + discriminator + frozen LPIPS in one checkpointable pytree.
    ``params``/``opt_state`` keep the names BaseTrainer's NaN rollback expects."""
    step: jnp.ndarray
    params: Any          # {"gen", "disc", "lpips"}
    opt_state: Any       # {"gen", "disc"}
    batch_stats: Any     # discriminator BatchNorm running stats
    gen_tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    disc_tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, gen_params, disc_params, lpips_params, batch_stats,
               gen_tx, disc_tx):
        return cls(step=jnp.zeros((), jnp.int32),
                   params={"gen": gen_params, "disc": disc_params,
                           "lpips": lpips_params},
                   opt_state={"gen": gen_tx.init(gen_params),
                              "disc": disc_tx.init(disc_params["params"])},
                   batch_stats=batch_stats, gen_tx=gen_tx, disc_tx=disc_tx)


def make_vqgan_train_step(model: VQModel, disc: NLayerDiscriminator,
                          lpips: Optional[LPIPS], loss_cfg: GANLossConfig,
                          dtype=None, scanned: bool = False, state=None,
                          health: bool = False, health_depth: int = 1):
    """Returns step(state, images, key, temp) -> (state, metrics) implementing
    both optimizer updates of vqperceptual.py:76-136 in one XLA program.
    ``state`` pins the output state's shardings to the input's
    (train_state.jit_step). ``scanned``: lift the same body into a
    k-steps-per-dispatch program over stacked (imagess, keys, temps)
    (train_state.make_scanned_steps). ``health`` fuses the graftpulse taps
    (obs/health.py) into the program: codebook vitals from the quantizer's
    own VQOutput plus per-layer-group grad/param/update stats for BOTH
    optimizers (``gen/*`` and ``disc/*`` groups) — scalars in the metrics
    dict, zero added host syncs."""
    lc = loss_cfg
    d_loss_fn = hinge_d_loss if lc.disc_loss == "hinge" else vanilla_d_loss

    def perceptual(lpips_params, x, y):
        if lpips is None or lc.perceptual_weight == 0:
            return jnp.zeros((x.shape[0],), x.dtype)
        return lpips.apply(lpips_params, x, y)

    def ae_loss_fn(gen_params, disc_params, lpips_params, batch_stats, images,
                   key, temp, step):
        # training pass: dropout active, gumbel sampling live (when configured)
        rngs = {"gumbel": key, "dropout": jax.random.fold_in(key, 1)}
        gen_c = cast_floating(gen_params, dtype)
        images_c = images if dtype is None else images.astype(dtype)
        q = model.apply(gen_c, images_c, temp=temp, deterministic=False,
                        method=VQModel.encode, rngs=rngs)
        recon, h_last = model.apply(gen_c, q.quantized, False, True,
                                    method=VQModel.decode, rngs=rngs)

        def nll_of(r):
            # loss reductions in f32 regardless of the compute dtype
            rec = lc.pixelloss_weight * jnp.abs(
                images.astype(jnp.float32) - r.astype(jnp.float32))
            p = perceptual(lpips_params, images, r)
            return jnp.mean(rec) + lc.perceptual_weight * jnp.mean(
                p.astype(jnp.float32))

        def g_of(r):
            logits_fake, _ = disc.apply(
                {"params": disc_params, "batch_stats": batch_stats}, r,
                train=True, mutable=["batch_stats"])
            return -jnp.mean(logits_fake)

        nll = nll_of(recon)
        g_loss = g_of(recon)
        conv_out = gen_c["params"]["decoder"]["conv_out"]
        d_weight = adaptive_disc_weight(nll_of, g_of, h_last, conv_out,
                                        lc.disc_weight)
        disc_factor = adopt_weight(lc.disc_factor, step, lc.disc_start)
        loss = nll + d_weight * disc_factor * g_loss + lc.codebook_weight * q.loss
        aux = {"recon": recon, "nll_loss": nll, "g_loss": g_loss,
               "quant_loss": q.loss, "d_weight": d_weight,
               "disc_factor": disc_factor}
        if health:
            # codebook vitals from the encode's own VQOutput — no recompute
            aux["health"] = model.health_taps(q, temp)
        return loss, aux

    def disc_loss_fn(disc_params, batch_stats, images, recon, step):
        variables = {"params": disc_params, "batch_stats": batch_stats}
        logits_real, vars1 = disc.apply(variables, images, train=True,
                                        mutable=["batch_stats"])
        logits_fake, vars2 = disc.apply(
            {"params": disc_params, "batch_stats": vars1["batch_stats"]},
            jax.lax.stop_gradient(recon), train=True, mutable=["batch_stats"])
        disc_factor = adopt_weight(lc.disc_factor, step, lc.disc_start)
        d_loss = disc_factor * d_loss_fn(logits_real, logits_fake)
        aux = {"batch_stats": vars2["batch_stats"],
               "logits_real": jnp.mean(logits_real),
               "logits_fake": jnp.mean(logits_fake)}
        return d_loss, aux

    def step(state: GANTrainState, images, key, temp):
        gen_p, disc_p, lpips_p = (state.params["gen"], state.params["disc"],
                                  state.params["lpips"])
        # --- optimizer_idx 0: autoencoder ---------------------------------
        (ae_loss, aux), gen_grads = jax.value_and_grad(ae_loss_fn, has_aux=True)(
            gen_p, disc_p["params"], lpips_p, state.batch_stats, images, key,
            temp, state.step)
        gen_updates, gen_opt = state.gen_tx.update(
            gen_grads, state.opt_state["gen"], gen_p, value=ae_loss)
        gen_p = optax.apply_updates(gen_p, gen_updates)
        # --- optimizer_idx 1: discriminator -------------------------------
        (d_loss, d_aux), disc_grads = jax.value_and_grad(
            disc_loss_fn, has_aux=True)(disc_p["params"], state.batch_stats,
                                        images, aux["recon"], state.step)
        disc_updates, disc_opt = state.disc_tx.update(
            disc_grads, state.opt_state["disc"], disc_p["params"], value=d_loss)
        disc_p = {"params": optax.apply_updates(disc_p["params"], disc_updates)}
        state = state.replace(
            step=state.step + 1,
            params={"gen": gen_p, "disc": disc_p, "lpips": lpips_p},
            opt_state={"gen": gen_opt, "disc": disc_opt},
            batch_stats=d_aux["batch_stats"])
        metrics = {"loss": ae_loss, "disc_loss": d_loss,
                   "nll_loss": aux["nll_loss"], "quant_loss": aux["quant_loss"],
                   "g_loss": aux["g_loss"], "d_weight": aux["d_weight"],
                   "logits_real": d_aux["logits_real"],
                   "logits_fake": d_aux["logits_fake"]}
        if health:
            from ..obs.health import tree_health
            metrics.update(aux["health"])
            # POST-update params (fresh buffers — donation aliasing intact)
            metrics.update(tree_health(gen_grads, gen_p, gen_updates,
                                       depth=health_depth, prefix="gen"))
            metrics.update(tree_health(disc_grads, disc_p["params"],
                                       disc_updates, depth=health_depth,
                                       prefix="disc"))
        return state, metrics

    if scanned:
        from .train_state import make_scanned_steps
        return make_scanned_steps(step)
    return jit_step(step, state)


def make_vq_simple_train_step(model: VQModel, loss_cfg: GANLossConfig,
                              mode: str, dtype=None, scanned: bool = False,
                              state=None, health: bool = False,
                              health_depth: int = 1):
    """Single-optimizer VQ variants (taming vqgan.py:159-258):
    ``nodisc`` — L1 recon + codebook loss (VQNoDiscModel);
    ``segmentation`` — BCE over label-map logits + codebook loss
    (VQSegmentationModel with BCELossWithQuant). ``health`` fuses the
    graftpulse codebook + per-layer-group taps (obs/health.py)."""
    lc = loss_cfg

    def loss_fn(params, images, targets, key, temp):
        rngs = {"gumbel": key, "dropout": jax.random.fold_in(key, 1)}
        p = cast_floating(params, dtype)
        x = images if dtype is None else images.astype(dtype)
        recon, qloss, indices = model.apply(p, x, temp=temp,
                                            deterministic=False, rngs=rngs)
        recon32 = recon.astype(jnp.float32)
        hm = {}
        if health:
            from ..obs.health import codebook_health
            hm = codebook_health(indices, model.cfg.n_embed)
        if mode == "segmentation":
            loss, parts = bce_with_quant_loss(recon32, targets, qloss,
                                              lc.codebook_weight)
            return loss, {"nll_loss": parts["bce_loss"], "quant_loss": qloss,
                          **hm}
        rec = jnp.mean(jnp.abs(targets - recon32)) * lc.pixelloss_weight
        return rec + lc.codebook_weight * qloss, {"nll_loss": rec,
                                                  "quant_loss": qloss, **hm}

    def step(state: TrainState, images, targets, key, temp):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, images, targets, key, temp)
        if health:
            from ..obs.health import tree_health
            state, updates = state.apply_gradients(grads, value=loss,
                                                   return_updates=True)
            aux = {**aux, **tree_health(grads, state.params, updates,
                                        depth=health_depth)}
        else:
            state = state.apply_gradients(grads, value=loss)
        return state, {"loss": loss, **aux}

    if scanned:
        from .train_state import make_scanned_steps
        return make_scanned_steps(step)
    return jit_step(step, state)


class VQGANTrainer(BaseTrainer):
    model_class = "VQModel"

    def __init__(self, model_cfg: VQGANConfig, train_cfg: TrainConfig,
                 loss_cfg: Optional[GANLossConfig] = None, mesh=None,
                 backend=None, disc_optim=None,
                 temp_scheduler: Optional[Callable[[int], float]] = None,
                 loss_mode: str = "gan"):
        """``loss_mode``: "gan" (VQModel/GumbelVQ adversarial training),
        "nodisc" (VQNoDiscModel), or "segmentation" (VQSegmentationModel —
        set cfg.out_ch to the label count)."""
        super().__init__(train_cfg, mesh=mesh, backend=backend)
        self.model_cfg = model_cfg
        self.loss_cfg = loss_cfg or GANLossConfig()
        assert loss_mode in ("gan", "nodisc", "segmentation"), loss_mode
        self.loss_mode = loss_mode
        self._health_kw = dict(
            health=bool(train_cfg.obs.health),
            health_depth=train_cfg.obs.health_group_depth)

        self.model, gen_params = init_vqgan(model_cfg, self.base_key)
        if loss_mode != "gan":
            gen_params = shard_params(self.mesh, gen_params)
            tx = make_optimizer(train_cfg.optim)
            self.state = commit_to_mesh(self.mesh, TrainState.create(
                apply_fn=self.model.apply, params=gen_params, tx=tx,
                lr_scale=1.0 if train_cfg.runtime_lr_scale else None))
            self.step_fn = make_vq_simple_train_step(
                self.model, self.loss_cfg, loss_mode,
                dtype=compute_dtype(train_cfg.precision), state=self.state,
                **self._health_kw)
            self.disc = self.lpips = None
            self._finish_init(temp_scheduler)
            return
        self.disc = NLayerDiscriminator(ndf=self.loss_cfg.disc_ndf,
                                        n_layers=self.loss_cfg.disc_num_layers,
                                        use_actnorm=self.loss_cfg.use_actnorm)
        disc_vars = self.disc.init(
            jax.random.fold_in(self.base_key, 1),
            jnp.zeros((2, model_cfg.resolution, model_cfg.resolution,
                       model_cfg.in_channels), jnp.float32), train=True)
        batch_stats = disc_vars.get("batch_stats", {})
        if self.loss_cfg.perceptual_weight > 0:
            if self.loss_cfg.perceptual_net == "tiny":
                # the shipped in-repo perceptual weights (real metric, no
                # egress needed — scripts/train_perceptual.py)
                from ..models.lpips import load_tiny_perceptual
                try:
                    self.lpips, lpips_params = load_tiny_perceptual()
                except FileNotFoundError:
                    import warnings
                    warnings.warn("tiny_perceptual.npz missing — perceptual "
                                  "loss falls back to a random-init net")
                    self.lpips, lpips_params = init_lpips(
                        jax.random.fold_in(self.base_key, 2),
                        model_cfg.resolution)
            else:
                # torchvision-shaped trunk; import real weights via
                # models.lpips.load_torch_weights when vgg.pth is on disk
                self.lpips, lpips_params = init_lpips(
                    jax.random.fold_in(self.base_key, 2), model_cfg.resolution)
        else:
            self.lpips, lpips_params = None, {}

        gen_params = shard_params(self.mesh, gen_params)
        disc_params = shard_params(self.mesh, {"params": disc_vars["params"]})
        lpips_params = shard_params(self.mesh, lpips_params)

        # taming configure_optimizers: both Adam(lr, betas=(0.5, 0.9))
        # (taming/models/vqgan.py:121-131)
        gen_tx = make_optimizer(train_cfg.optim)
        self.disc_optim = disc_optim or train_cfg.optim
        disc_tx = make_optimizer(self.disc_optim)
        self.state = commit_to_mesh(self.mesh, GANTrainState.create(
            gen_params=gen_params, disc_params=disc_params,
            lpips_params=lpips_params, batch_stats=batch_stats,
            gen_tx=gen_tx, disc_tx=disc_tx))
        self.step_fn = make_vqgan_train_step(
            self.model, self.disc, self.lpips, self.loss_cfg,
            dtype=compute_dtype(train_cfg.precision), state=self.state,
            **self._health_kw)
        self._finish_init(temp_scheduler)

    def _finish_init(self, temp_scheduler):
        """Shared tail for both modes: temperature schedule + meter."""
        # GumbelVQ temperature schedule, stepped per train step
        # (taming vqgan.py:279-303)
        self.temp_scheduler = temp_scheduler
        if self.temp_scheduler is None and self.model_cfg.quantizer == "gumbel":
            self.temp_scheduler = LambdaWarmUpCosineScheduler(
                0, 1e-6, 1.0, 1.0, self.train_cfg.optim.total_steps)
        n = count_params(self._gen_params)
        self.meter = ThroughputMeter(
            self.train_cfg.batch_size, self.train_cfg.log_every,
            flops_per_step=6.0 * n * self.train_cfg.batch_size,
            num_chips=self.mesh.size)

    def _put_batch(self, batch, stacked: bool = False):
        """(images[, targets]) → float32 on the mesh (targets only exist for
        the segmentation/nodisc modes)."""
        images, *rest = batch
        return (self._put(images, np.float32, stacked),
                *(self._put(t, np.float32, stacked) if t is not None else t
                  for t in rest))

    def train_step(self, images: np.ndarray, targets=None):
        """``targets``: segmentation one-hots for loss_mode="segmentation";
        defaults to the images themselves for "nodisc"."""
        step_num = self._host_step
        temp = (self.temp_scheduler(step_num) if self.temp_scheduler is not None
                else 1.0)
        key = jax.random.fold_in(self.base_key, step_num)
        with span("vqgan/shard_batch"):
            images = self._put(images, np.float32)
        if self.loss_mode != "gan":
            t = images if targets is None else self._put(targets, np.float32)
            with span("vqgan/step"):
                self.state, metrics = self.step_fn(self.state, images, t, key,
                                                   jnp.float32(temp))
            return self._finish_step(metrics)
        with span("vqgan/step"):
            self.state, metrics = self.step_fn(self.state, images, key,
                                               jnp.float32(temp))
        metrics = self._finish_step(metrics)
        if metrics and self.temp_scheduler is not None:
            metrics["temperature"] = temp
        return metrics

    # -- k steps in one device program ---------------------------------------
    def train_steps(self, images: np.ndarray, targets=None):
        """(k, b, H, W, C) stacked microbatches → k steps (both optimizer
        updates each) in one dispatched scan. Key and temperature streams
        match ``train_step`` exactly."""
        assert images.ndim == 5, "train_steps wants stacked (k, b, H, W, C)"
        if getattr(self, "_multi_step_fn", None) is None:
            dt = compute_dtype(self.train_cfg.precision)
            if self.loss_mode == "gan":
                self._multi_step_fn = make_vqgan_train_step(
                    self.model, self.disc, self.lpips, self.loss_cfg,
                    dtype=dt, scanned=True, **self._health_kw)
            else:
                self._multi_step_fn = make_vq_simple_train_step(
                    self.model, self.loss_cfg, self.loss_mode, dtype=dt,
                    scanned=True, **self._health_kw)
        k = images.shape[0]
        steps = self._host_step + np.arange(k)
        temps = jnp.asarray(
            [self.temp_scheduler(int(s)) if self.temp_scheduler is not None
             else 1.0 for s in steps], jnp.float32)
        keys = self._step_keys(k)
        with span("vqgan/shard_batch", k=k):
            images = self._put(images, np.float32, stacked=True)
        if self.loss_mode != "gan":
            t = (images if targets is None
                 else self._put(targets, np.float32, stacked=True))
            xs = (images, t, keys, temps)
        else:
            xs = (images, keys, temps)
        with span("vqgan/steps", k=k):
            self.state, metrics = self._multi_step_fn(self.state, xs)
        self._host_step += k - 1     # _finish_step adds the final +1
        metrics = self._finish_step(metrics)
        if metrics and self.temp_scheduler is not None:
            metrics["temperature"] = float(temps[-1])
        return metrics

    # -- eval utilities ----------------------------------------------------
    @property
    def _gen_params(self):
        return (self.state.params if self.loss_mode != "gan"
                else self.state.params["gen"])

    def reconstruct(self, images: np.ndarray):
        recon, _, _ = self.model.apply(self._gen_params, jnp.asarray(images),
                                       deterministic=True)
        return recon

    def get_codebook_indices(self, images: np.ndarray):
        return self.model.apply(self._gen_params, jnp.asarray(images),
                                method=VQModel.get_codebook_indices)
