"""dVAE trainer — one jitted SPMD train step + host-side epoch loop.

Reference call stack: legacy/train_vae.py (§3.4 of SURVEY.md) — epoch loop with
Gumbel temperature annealing ``temp = max(temp·exp(−rate·step), temp_min)``
(:269-271), codebook-index histogram as a collapse monitor (:245-264), loss
averaging over workers, checkpoint {hparams, weights}. The fork adds NaN
rollback (vae.py:100-110).

TPU design: the entire step (loss, grads, psum over dp via shardings, optimizer)
is ONE jitted function with the state donated (params update in place in HBM);
temperature enters as a traced scalar so annealing doesn't retrigger
compilation; the gumbel rng is folded from the step counter for cross-host
determinism.
"""

from __future__ import annotations

import math
import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import AnnealConfig, DVAEConfig, TrainConfig
from ..models.dvae import DiscreteVAE, init_dvae
from ..obs import span
from ..parallel import commit_to_mesh, shard_params
from .base_trainer import BaseTrainer
from .metrics import ThroughputMeter, count_params
from .train_state import (TrainState, cast_floating, compute_dtype,
                          jit_step, make_optimizer)


def anneal_temperature(cfg: AnnealConfig, global_step: int) -> float:
    return max(cfg.starting_temp * math.exp(-cfg.anneal_rate * global_step),
               cfg.temp_min)


@functools.lru_cache(maxsize=64)
def _vae_step_body(model: DiscreteVAE, dtype=None, health: bool = False,
                   health_depth: int = 1):
    # memoized on (model-config, dtype, health wiring) so equal-config
    # trainers hand jit_step the SAME body object and share one jitted
    # wrapper. ``health`` fuses the graftpulse taps (obs/health.py) into the
    # program: the dVAE's codebook/gumbel vitals ride the loss aux, the
    # per-layer-group grad/param/update stats reduce in the same step — all
    # scalars in the metrics dict, zero added host syncs.
    def loss_fn(params, images, key, temp):
        if dtype is not None:
            images = images.astype(dtype)
        out = model.apply(
            cast_floating(params, dtype), images, temp=temp, return_loss=True,
            return_recons=True, return_health=health, rngs={"gumbel": key})
        if health:
            loss, _recons, hm = out
            return loss, hm
        loss, _recons = out
        return loss, None

    def step(state: TrainState, images, key, temp):
        (loss, hm), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, images, key, temp)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        if health:
            from ..obs.health import tree_health
            state, updates = state.apply_gradients(grads, value=loss,
                                                   return_updates=True)
            metrics.update(hm)
            metrics.update(tree_health(grads, state.params, updates,
                                       depth=health_depth))
        else:
            state = state.apply_gradients(grads, value=loss)
        return state, metrics

    return step


def make_vae_train_step(model: DiscreteVAE, dtype=None, state=None,
                        health: bool = False, health_depth: int = 1):
    """Returns step(state, images, key, temp) -> (state, metrics). jit-once
    (the (body, shardings)-memoized train_state.jit_step); the state is
    donated so params/moments update in place in HBM. ``state`` pins the
    output state's shardings to the input's — see jit_step. ``dtype``
    selects the compute precision (params cast per-step; masters stay f32);
    ``health`` fuses the graftpulse model-health taps into the program
    (docs/OBSERVABILITY.md)."""
    return jit_step(_vae_step_body(model, dtype, health, health_depth), state)


@functools.lru_cache(maxsize=64)
def make_vae_train_multi_step(model: DiscreteVAE, dtype=None,
                              health: bool = False, health_depth: int = 1):
    """k steps per dispatch (train_state.make_scanned_steps) over stacked
    (images, keys, temps) — the identical step body, so with matching key and
    temperature streams the result equals k single dispatches."""
    from .train_state import make_scanned_steps
    return make_scanned_steps(_vae_step_body(model, dtype, health,
                                             health_depth))


@partial(jax.jit, static_argnums=1)
def _codebook_counts(indices, num_tokens):
    """Histogram of codebook usage — the collapse monitor the reference logs to
    wandb (legacy/train_vae.py:258-264)."""
    return jnp.bincount(indices.reshape(-1), length=num_tokens)


class VAETrainer(BaseTrainer):
    model_class = "DiscreteVAE"

    def __init__(self, model_cfg: DVAEConfig, train_cfg: TrainConfig,
                 anneal_cfg: Optional[AnnealConfig] = None, mesh=None,
                 backend=None):
        super().__init__(train_cfg, mesh=mesh, backend=backend)
        self.model_cfg = model_cfg
        self.anneal_cfg = anneal_cfg or AnnealConfig()

        # graftmend (train/actions.py): temperature-schedule rebase point —
        # reanneal_gumbel(step) restarts the anneal from `step`, re-warming
        # a collapsed codebook; temp is a traced scalar so no recompile
        self._anneal_step0 = 0

        self.model, params = init_dvae(model_cfg, self.base_key)
        params = shard_params(self.mesh, params)
        tx = make_optimizer(train_cfg.optim)
        self.state = commit_to_mesh(self.mesh, TrainState.create(
            apply_fn=self.model.apply, params=params, tx=tx,
            lr_scale=1.0 if train_cfg.runtime_lr_scale else None))
        self._health_kw = dict(
            health=bool(train_cfg.obs.health),
            health_depth=train_cfg.obs.health_group_depth)
        self.step_fn = make_vae_train_step(
            self.model, dtype=compute_dtype(train_cfg.precision),
            state=self.state, **self._health_kw)
        self._multi_step_fn = None   # built lazily on first train_steps()

        n = count_params(self.state.params)
        self.meter = ThroughputMeter(train_cfg.batch_size, train_cfg.log_every,
                                     flops_per_step=6.0 * n * train_cfg.batch_size *
                                     model_cfg.image_seq_len,
                                     num_chips=self.mesh.size)

    def _put_batch(self, batch, stacked: bool = False):
        """(images[, labels]) → float32 images on the mesh; trailing labels
        (ignored by the step) pass through as-is."""
        images, *rest = batch
        return (self._put(images, np.float32, stacked), *rest)

    def _temp_at(self, step: int) -> float:
        """Anneal temperature with the re-anneal rebase applied: the
        schedule runs on ``step - _anneal_step0`` so a codebook-collapse
        action can restart the warm phase mid-run (docs/RESILIENCE.md)."""
        return anneal_temperature(self.anneal_cfg,
                                  max(step - self._anneal_step0, 0))

    def reanneal_gumbel(self, step: int) -> float:
        """Restart the gumbel temperature schedule from ``step`` (the
        codebook-collapse breach action). Returns the re-warmed temp.
        The rebase point rides checkpoint METADATA (``extra_meta`` flows
        into every later save's sidecar) so a preemption/respawn resumes
        the re-warmed schedule instead of snapping back to the cold
        end-of-schedule temperature — the lr-cut action gets the same
        durability from ``TrainState.lr_scale`` living in the state."""
        self._anneal_step0 = int(step)
        self.extra_meta["anneal_step0"] = self._anneal_step0
        return self._temp_at(step)

    def restore(self, step=None):
        meta = super().restore(step)
        if meta and meta.get("anneal_step0"):
            # best-effort like all metadata: a missing sidecar resumes the
            # un-rebased schedule (and a breach would just re-fire)
            self._anneal_step0 = int(meta["anneal_step0"])
            self.extra_meta["anneal_step0"] = self._anneal_step0
        return meta

    # -- single step -------------------------------------------------------
    def train_step(self, images: np.ndarray, _labels=None):
        step_num = self._host_step
        temp = self._temp_at(step_num)
        key = jax.random.fold_in(self.base_key, step_num)
        with span("vae/shard_batch"):
            images = self._put(images, np.float32)
        with span("vae/step"):
            self.state, metrics = self.step_fn(self.state, images, key,
                                               jnp.float32(temp))
        metrics = self._finish_step(metrics)
        if metrics:   # empty when metrics_every skips the host sync this step
            metrics["temperature"] = temp
        return metrics

    # -- k steps in one device program ---------------------------------------
    def train_steps(self, images: np.ndarray, _labels=None):
        """(k, b, H, W, C) stacked microbatches → k optimizer steps in one
        dispatched scan. Key and temperature streams match ``train_step``
        exactly (precomputed per host step and scanned as inputs), so the
        result is identical to k single dispatches. ``_labels`` (stacked
        captions from the (images, captions) loaders) is ignored, mirroring
        ``train_step``."""
        assert images.ndim == 5, "train_steps wants stacked (k, b, H, W, C)"
        if self._multi_step_fn is None:
            self._multi_step_fn = make_vae_train_multi_step(
                self.model, dtype=compute_dtype(self.train_cfg.precision),
                **self._health_kw)
        k = images.shape[0]
        steps = self._host_step + np.arange(k)
        keys = self._step_keys(k)
        temps = jnp.asarray([self._temp_at(int(s)) for s in steps],
                            jnp.float32)
        with span("vae/shard_batch", k=k):
            images = self._put(images, np.float32, stacked=True)
        with span("vae/steps", k=k):
            self.state, metrics = self._multi_step_fn(
                self.state, (images, keys, temps))
        self._host_step += k - 1     # _finish_step adds the final +1
        metrics = self._finish_step(metrics)
        if metrics:
            metrics["temperature"] = float(temps[-1])
        return metrics

    # -- eval utilities ----------------------------------------------------
    def reconstruct(self, images: np.ndarray, hard: bool = True):
        return self.model.apply(self.state.params, jnp.asarray(images),
                                hard_recons=hard,
                                rngs=None if hard else {"gumbel": self.base_key})

    def codebook_histogram(self, images: np.ndarray) -> np.ndarray:
        idx = self.model.apply(self.state.params, jnp.asarray(images),
                               method=DiscreteVAE.get_codebook_indices)
        return np.asarray(_codebook_counts(idx, self.model_cfg.num_tokens))
