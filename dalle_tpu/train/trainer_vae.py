"""dVAE trainer — one jitted SPMD train step + host-side epoch loop.

Reference call stack: legacy/train_vae.py (§3.4 of SURVEY.md) — epoch loop with
Gumbel temperature annealing ``temp = max(temp·exp(−rate·step), temp_min)``
(:269-271), codebook-index histogram as a collapse monitor (:245-264), loss
averaging over workers, checkpoint {hparams, weights}. The fork adds NaN
rollback (vae.py:100-110).

TPU design: the entire step (loss, grads, psum over dp via shardings, optimizer)
is ONE jitted function; temperature enters as a traced scalar so annealing
doesn't retrigger compilation; the gumbel rng is folded from the step counter
for cross-host determinism.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import AnnealConfig, DVAEConfig, TrainConfig
from ..models.dvae import DiscreteVAE, init_dvae
from ..parallel import shard_batch, shard_params
from .checkpoints import CheckpointManager
from .metrics import ThroughputMeter, count_params
from .train_state import TrainState, make_optimizer


def anneal_temperature(cfg: AnnealConfig, global_step: int) -> float:
    return max(cfg.starting_temp * math.exp(-cfg.anneal_rate * global_step),
               cfg.temp_min)


def make_vae_train_step(model: DiscreteVAE):
    """Returns step(state, images, key, temp) -> (state, metrics). jit-once."""

    def loss_fn(params, images, key, temp):
        loss, recons = model.apply(
            params, images, temp=temp, return_loss=True, return_recons=True,
            rngs={"gumbel": key})
        return loss, recons

    @jax.jit
    def step(state: TrainState, images, key, temp):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, images, key, temp)
        state = state.apply_gradients(grads)
        return state, {"loss": loss, "grad_norm": optax.global_norm(grads)}

    return step


from functools import partial


@partial(jax.jit, static_argnums=1)
def _codebook_counts(indices, num_tokens):
    """Histogram of codebook usage — the collapse monitor the reference logs to
    wandb (legacy/train_vae.py:258-264)."""
    return jnp.bincount(indices.reshape(-1), length=num_tokens)


class VAETrainer:
    def __init__(self, model_cfg: DVAEConfig, train_cfg: TrainConfig,
                 anneal_cfg: Optional[AnnealConfig] = None, mesh=None,
                 backend=None):
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.anneal_cfg = anneal_cfg or AnnealConfig()
        if mesh is None and backend is not None:
            mesh = backend.mesh
        if mesh is None:
            from ..parallel import build_mesh
            mesh = build_mesh(train_cfg.mesh)
        self.mesh = mesh
        self.backend = backend

        key = jax.random.PRNGKey(train_cfg.seed)
        self.model, params = init_dvae(model_cfg, key)
        params = shard_params(mesh, params)
        tx = make_optimizer(train_cfg.optim)
        self.state = TrainState.create(apply_fn=self.model.apply, params=params, tx=tx)
        self.step_fn = make_vae_train_step(self.model)
        self.base_key = key
        self.ckpt = CheckpointManager(train_cfg.checkpoint_dir,
                                      keep_n=train_cfg.keep_n_checkpoints)
        self._last_good = None   # host copy of (params, opt_state) for NaN rollback
        self._host_step = 0      # host mirror of state.step: no device sync per step

        n = count_params(self.state.params)
        self.meter = ThroughputMeter(train_cfg.batch_size, train_cfg.log_every,
                                     flops_per_step=6.0 * n * train_cfg.batch_size *
                                     model_cfg.image_seq_len,
                                     num_chips=jax.device_count())

    # -- single step -------------------------------------------------------
    def train_step(self, images: np.ndarray):
        step_num = self._host_step
        temp = anneal_temperature(self.anneal_cfg, step_num)
        key = jax.random.fold_in(self.base_key, step_num)
        images = shard_batch(self.mesh, images.astype(np.float32))
        self.state, metrics = self.step_fn(self.state, images, key,
                                           jnp.float32(temp))
        self._host_step += 1
        metrics = {k: float(v) for k, v in jax.device_get(metrics).items()}
        metrics["temperature"] = temp
        rep = self.meter.step(step_num)
        if rep:
            metrics.update(rep)
        return metrics

    # -- full loop with parity behaviors ----------------------------------
    def fit(self, batches, *, steps: Optional[int] = None, log=print):
        tc = self.train_cfg
        meta = {"hparams": self.model_cfg.to_dict(), "train": tc.to_dict(),
                "model_class": "DiscreteVAE"}
        if tc.preflight_checkpoint:
            self.ckpt.preflight(self.state, meta)
        self._snapshot_good()
        for images, _ in batches:
            m = self.train_step(images)
            step_num = self._host_step
            if tc.nan_rollback and not math.isfinite(m["loss"]):
                log(f"[step {step_num}] NaN loss — rolling back to last good state")
                self._rollback()
                continue
            if step_num % tc.log_every == 0:
                log(f"[step {step_num}] " +
                    " ".join(f"{k}={v:.5g}" for k, v in m.items()))
            if step_num % tc.save_every_steps == 0:
                self.ckpt.save(step_num, self.state, meta)
                self._snapshot_good()
            if steps is not None and step_num >= steps:
                break
        return self.state

    def _snapshot_good(self):
        # NaN loss is observed AFTER apply_gradients has run, so the optimizer
        # moments are poisoned too — snapshot and restore both (the reference
        # fork reloads the whole checkpoint, vae.py:100-110)
        live = (self.state.params, self.state.opt_state)
        self._last_good = jax.device_get(live)
        self._last_good_shardings = jax.tree.map(lambda x: x.sharding, live)

    def _rollback(self):
        if self._last_good is not None:
            restored = jax.tree.map(jax.device_put, self._last_good,
                                    self._last_good_shardings)
            params, opt_state = restored
            self.state = self.state.replace(params=params, opt_state=opt_state)

    # -- eval utilities ----------------------------------------------------
    def reconstruct(self, images: np.ndarray, hard: bool = True):
        return self.model.apply(self.state.params, jnp.asarray(images),
                                hard_recons=hard,
                                rngs=None if hard else {"gumbel": self.base_key})

    def codebook_histogram(self, images: np.ndarray) -> np.ndarray:
        idx = self.model.apply(self.state.params, jnp.asarray(images),
                               method=DiscreteVAE.get_codebook_indices)
        return np.asarray(_codebook_counts(idx, self.model_cfg.num_tokens))
