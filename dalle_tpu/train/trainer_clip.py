"""CLIP trainer — contrastive text/image training as one jitted SPMD step.

The reference ships the CLIP model with its symmetric-CE loss
(dalle_pytorch/dalle_pytorch.py:292-332) but no training script (CLIP is used
for reranking, generate_images :553-555). This trainer completes the family so
a rerank model can be trained in-framework, with the same shell as every other
trainer (NaN rollback, checkpoints, meter, bf16 compute).
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import optax

from ..config import ClipConfig, TrainConfig
from ..models.clip import CLIP, init_clip
from ..obs import span
from ..parallel import commit_to_mesh, shard_params
from .base_trainer import BaseTrainer
from .metrics import ThroughputMeter, count_params, transformer_train_flops
from .train_state import (TrainState, cast_floating, compute_dtype,
                          jit_step, make_optimizer)


@functools.lru_cache(maxsize=64)
def _clip_step_body(model: CLIP, dtype=None, health: bool = False,
                    health_depth: int = 1):
    # memoized on (model-config, dtype, health wiring) so equal-config
    # trainers hand jit_step the SAME body object and share one jitted
    # wrapper. ``health`` fuses the graftpulse per-layer-group taps
    # (obs/health.py) into the program.
    def loss_fn(params, text, images):
        x = images if dtype is None else images.astype(dtype)
        return model.apply(cast_floating(params, dtype), text, x,
                           return_loss=True)

    def step(state: TrainState, text, images):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, text, images)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        if health:
            from ..obs.health import tree_health
            state, updates = state.apply_gradients(grads, value=loss,
                                                   return_updates=True)
            metrics.update(tree_health(grads, state.params, updates,
                                       depth=health_depth))
        else:
            state = state.apply_gradients(grads, value=loss)
        return state, metrics

    return step


def make_clip_train_step(model: CLIP, dtype=None, state=None,
                         health: bool = False, health_depth: int = 1):
    """Returns step(state, text, images) -> (state, metrics). ``state`` pins
    the output state's shardings (train_state.jit_step)."""
    return jit_step(_clip_step_body(model, dtype, health, health_depth),
                    state)


def make_clip_train_multi_step(model: CLIP, dtype=None, health: bool = False,
                               health_depth: int = 1):
    """k steps per dispatch over stacked (texts, imagess) —
    train_state.make_scanned_steps over the identical step body."""
    from .train_state import make_scanned_steps
    return make_scanned_steps(_clip_step_body(model, dtype, health,
                                              health_depth))


class CLIPTrainer(BaseTrainer):
    model_class = "CLIP"

    def __init__(self, model_cfg: ClipConfig, train_cfg: TrainConfig,
                 mesh=None, backend=None):
        super().__init__(train_cfg, mesh=mesh, backend=backend)
        self.model_cfg = model_cfg
        self.model, params = init_clip(model_cfg, self.base_key)
        params = shard_params(self.mesh, params)
        tx = make_optimizer(train_cfg.optim)
        self.state = commit_to_mesh(self.mesh, TrainState.create(
            apply_fn=self.model.apply, params=params, tx=tx,
            lr_scale=1.0 if train_cfg.runtime_lr_scale else None))
        self._health_kw = dict(
            health=bool(train_cfg.obs.health),
            health_depth=train_cfg.obs.health_group_depth)
        self.step_fn = make_clip_train_step(
            self.model, dtype=compute_dtype(train_cfg.precision),
            state=self.state, **self._health_kw)
        self._multi_step_fn = None   # built lazily on first train_steps()
        n = count_params(self.state.params)
        self.num_params = n
        tokens_per_sample = (model_cfg.text_seq_len +
                             (model_cfg.visual_image_size //
                              model_cfg.visual_patch_size) ** 2)
        self.meter = ThroughputMeter(
            train_cfg.batch_size, train_cfg.log_every,
            tokens_per_sample=tokens_per_sample,
            flops_per_step=transformer_train_flops(
                n, train_cfg.batch_size * tokens_per_sample),
            num_chips=self.mesh.size)

    def _put_batch(self, batch, stacked: bool = False):
        """(text, images) → int32 text + float32 images on the mesh."""
        text, images = batch
        return (self._put(text, np.int32, stacked),
                self._put(images, np.float32, stacked))

    def train_step(self, text: np.ndarray, images: np.ndarray):
        with span("clip/shard_batch"):
            text, images = self._put_batch((text, images))
        with span("clip/step"):
            self.state, metrics = self.step_fn(self.state, text, images)
        return self._finish_step(metrics)

    def train_steps(self, texts: np.ndarray, imagess: np.ndarray):
        """(k, b, ...) stacked microbatches → k steps in one dispatched scan
        (identical math to k single dispatches — the step is rng-free)."""
        assert texts.ndim == 3 and imagess.ndim == 5, (
            "train_steps wants stacked (k, b, seq) / (k, b, H, W, C)")
        if self._multi_step_fn is None:
            self._multi_step_fn = make_clip_train_multi_step(
                self.model, dtype=compute_dtype(self.train_cfg.precision),
                **self._health_kw)
        k = texts.shape[0]
        with span("clip/shard_batch", k=k):
            texts, imagess = self._put_batch((texts, imagess), stacked=True)
        with span("clip/steps", k=k):
            self.state, metrics = self._multi_step_fn(self.state,
                                                      (texts, imagess))
        self._host_step += k - 1     # _finish_step adds the final +1
        return self._finish_step(metrics)

    def similarity(self, text: np.ndarray, images: np.ndarray):
        """Per-pair rerank scores (reference generate_images :553-555)."""
        import jax.numpy as jnp
        return self.model.apply(self.state.params, jnp.asarray(text),
                                jnp.asarray(images))
