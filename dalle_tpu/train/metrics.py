"""Throughput + MFU instrumentation.

The reference's two perf hooks (SURVEY.md §6): a samples/sec meter every 10
steps (legacy/train_dalle.py:601-602,651-654) and a FLOPS profile at step 200
(DeepSpeed flops profiler, :492-499). TPU equivalents: the same rolling
samples/sec meter, an analytic-FLOPs MFU estimate against the chip's peak, and
`jax.profiler` trace capture.
"""

from __future__ import annotations

import time
from typing import Optional

import jax

# peak bf16 matmul TFLOP/s per chip by device kind (public figures)
PEAK_TFLOPS = {
    "TPU v2": 45.0, "TPU v3": 123.0, "TPU v4": 275.0,
    "TPU v5 lite": 197.0, "TPU v5e": 197.0, "TPU v5": 459.0, "TPU v5p": 459.0,
    "TPU v6 lite": 918.0, "TPU v6e": 918.0, "cpu": 0.1,
}


_warned_unknown_peak = False


def device_peak_tflops_info(device: Optional[jax.Device] = None
                            ) -> tuple[float, bool]:
    """(peak bf16 TFLOP/s, estimated?) — ``estimated`` is True when the
    device kind has no entry in PEAK_TFLOPS and the 100.0 placeholder is in
    play, so MFU consumers can tag the number as fiction instead of fact."""
    global _warned_unknown_peak
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu")
    for k, v in PEAK_TFLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v, False
    if not _warned_unknown_peak:
        import warnings
        warnings.warn(
            f"unknown accelerator {kind!r}: MFU uses a 100 TFLOP/s guess and "
            "reports are tagged mfu_estimated — add the chip's peak to "
            "train/metrics.PEAK_TFLOPS for a real number")
        _warned_unknown_peak = True
    return 100.0, True


def device_peak_tflops(device: Optional[jax.Device] = None) -> float:
    return device_peak_tflops_info(device)[0]


class ThroughputMeter:
    """samples/sec + tokens/sec + MFU, reported every ``interval`` steps
    (reference computes batch*10/Δt every 10 steps)."""

    def __init__(self, batch_size: int, interval: int = 10,
                 tokens_per_sample: int = 0, flops_per_step: float = 0.0,
                 num_chips: int = 1):
        self.batch = batch_size
        self.interval = interval
        self.tokens_per_sample = tokens_per_sample
        self.flops_per_step = flops_per_step
        self.num_chips = max(num_chips, 1)
        self._t0 = time.perf_counter()
        self._last_step = 0
        self._last_report = None

    def step(self, step_num: int):
        """Call at any (possibly irregular) step numbers — e.g. only at
        ``metrics_every`` boundaries; rates use the ACTUAL steps elapsed."""
        if step_num - self._last_step < self.interval or step_num == 0:
            return None
        now = time.perf_counter()
        dt = now - self._t0
        n_steps = step_num - self._last_step
        self._t0 = now
        self._last_step = step_num
        sps = self.batch * n_steps / dt
        rep = {"sample_per_sec": sps, "step_time_s": dt / n_steps}
        if self.tokens_per_sample:
            rep["tokens_per_sec"] = sps * self.tokens_per_sample
            rep["tokens_per_sec_per_chip"] = sps * self.tokens_per_sample / self.num_chips
        if self.flops_per_step:
            achieved = self.flops_per_step * n_steps / dt
            peak_tflops, estimated = device_peak_tflops_info()
            rep["mfu"] = achieved / (peak_tflops * 1e12 * self.num_chips)
            if estimated:
                # unknown chip → the denominator is a guess; without the tag
                # the report would present a made-up MFU as authoritative
                rep["mfu_estimated"] = True
        self._last_report = rep
        return rep


def transformer_train_flops(n_params: int, tokens_per_batch: int) -> float:
    """6·N·D analytic training FLOPs per step (fwd+bwd) — the standard MFU
    denominator's numerator."""
    return 6.0 * n_params * tokens_per_batch


def count_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def profile_trace(logdir: str, fn, *args):
    """Capture a jax.profiler trace around one call of ``fn`` — the stand-in for
    the reference's flops-profiler-at-step-200 report."""
    with jax.profiler.trace(logdir):
        out = fn(*args)
        jax.block_until_ready(out)
    return out


class MetricsLogger:
    """Experiment-metrics sink: JSONL on disk, mirrored to wandb when the
    package+login are available — the reference's L6 observability layer
    (wandb.init/log at legacy/train_dalle.py:463-476,659-660) without a hard
    dependency on the external service."""

    def __init__(self, path: Optional[str] = None, use_wandb: bool = False,
                 project: str = "dalle-tpu", config: Optional[dict] = None,
                 run_name: Optional[str] = None):
        self._fh = open(path, "a") if path else None
        self._wandb = None
        if use_wandb:
            try:
                import wandb
                self._wandb = wandb.init(project=project, name=run_name,
                                         config=config or {}, resume="allow")
            except Exception as e:   # noqa: BLE001 - wandb offline / not
                # installed / auth failure: all degrade to jsonl-only logging
                print(f"[metrics] wandb unavailable ({e!r}); jsonl only")

    @staticmethod
    def _coerce_scalar(v):
        """Numeric scalars of ANY stripe → float: np.float32 is not a
        ``float`` and a 0-d device array is not an ``int``, so the plain
        isinstance filter used to drop them from the JSONL silently.
        Returns None for non-scalars (arrays, objects)."""
        if isinstance(v, (bool, int, float, str)):
            return v
        if getattr(v, "ndim", None) == 0:   # 0-d numpy/jax array, np scalar
            try:
                return float(v)
            except (TypeError, ValueError):  # non-numeric dtype
                return None
        return None

    def log(self, step: int, metrics: dict):
        import json
        import time as _time
        from ..obs import metrics_snapshot
        merged = {**metrics, **metrics_snapshot()}   # obs counters/gauges
        coerced = ((k, self._coerce_scalar(v)) for k, v in merged.items())
        rec = {"step": step, "time": _time.time(),
               **{k: v for k, v in coerced if v is not None}}
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        if self._wandb is not None:
            self._wandb.log({k: v for k, v in rec.items() if k != "step"},
                            step=step)

    def log_images(self, step: int, images, key: str = "samples",
                   captions=None):
        """Periodic generated/reconstruction image logging (reference
        legacy/train_dalle.py:639-649, train_vae.py:245-255). ``images`` is
        (b, H, W, C) float [0,1]; no-op without a live wandb run (disk grids
        are the script's responsibility)."""
        if self._wandb is None:
            return
        import numpy as np
        import wandb
        arr = np.asarray(images)
        caps = captions or [None] * len(arr)
        self._wandb.log(
            {key: [wandb.Image((a * 255).clip(0, 255).astype("uint8"),
                               caption=c) for a, c in zip(arr, caps)]},
            step=step)

    def log_artifact(self, path: str, name: str, type: str = "model",
                     metadata: Optional[dict] = None):
        """Checkpoint artifact upload (reference legacy/train_dalle.py:584-587,
        667-669: per-epoch trained-dalle wandb.Artifact). No-op without wandb."""
        if self._wandb is None:
            return
        import os
        import wandb
        art = wandb.Artifact(name, type=type, metadata=metadata or {})
        if os.path.isdir(path):
            art.add_dir(path)
        else:
            art.add_file(path)
        self._wandb.log_artifact(art)

    def close(self):
        if self._fh is not None:
            self._fh.close()
        if self._wandb is not None:
            self._wandb.finish()
