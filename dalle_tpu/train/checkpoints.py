"""Checkpoint save/restore with embedded model identity.

Contract parity with the reference (SURVEY.md §5.4): checkpoints carry
``hparams``/``vae_params``/``vae_class_name`` *inside* the file so generation can
reconstruct the exact model (legacy/train_dalle.py:535-582, generate.py:82-106);
rotation keeps the newest ``keep_n`` (:547-550); a pre-flight save fails fast on
misconfiguration (:591-594).

Implementation is Orbax (sharded, multi-host-safe — the TPU equivalent of the
DeepSpeed partitioned checkpoint dir) with the metadata dict stored alongside.

With ``async_save=True`` (the trainer default, ``TrainConfig.
async_checkpointing``) a mid-run ``save()`` blocks only for the device→host
snapshot; serialization and the filesystem write happen on orbax's background
thread, so the accelerator resumes stepping while the bytes land. The manager
drains (``wait_until_finished``) exactly at the durability points: before any
``restore``, at ``preflight``, when the caller asks (``save(wait=True)`` — the
SIGUSR1 latch path), and at ``close()``/atexit — an interrupted write never
finalizes its step directory, and orbax lists only finalized steps, so a save
racing process exit leaves either a complete checkpoint or an ignored
``*.orbax-checkpoint-tmp-*`` directory, never a truncated one.

graftmend resilience layers (docs/RESILIENCE.md):

  * **Retried I/O** — the orbax save/restore calls run under the
    jittered-backoff retry policy (``utils/retry.py``), so a transient
    filesystem blip costs milliseconds of backoff instead of a dead run;
    absorbed failures show as ``retry.attempts_total{op="ckpt_save"|
    "ckpt_restore"}``. The chaos harness injects exactly here
    (``chaos.io_hook`` inside the retried callable).
  * **Stale-tmp GC** — interrupted ``*-tmp-*`` directories used to pile up
    forever; :meth:`CheckpointManager.gc_stale_tmp` sweeps them on
    ``restore``/``preflight``, skipping any younger than a grace window so
    a sibling process's in-flight write is never deleted under it.
  * **Corruption fallback** — a latest-step restore that fails (torn or
    bitrotted files) falls back to the next older durable step instead of
    raising, counted as ``ckpt.restore_fallback_total`` and recorded as a
    flight event; an explicitly pinned ``step`` still raises (the caller
    asked for THAT state).
"""

from __future__ import annotations

import atexit
import os
import shutil
import time
import weakref
from typing import Any, Optional

import orbax.checkpoint as ocp

from ..chaos import io_hook
from ..obs import counter_add, gauge_set, record_event, span
from ..utils.retry import RetryBudgetExceeded, with_retry

# every live manager, drained at interpreter exit so an in-flight background
# write can finish before the process dies (a WeakSet: test suites create
# hundreds of short-lived managers and atexit must not pin them)
_LIVE_MANAGERS: "weakref.WeakSet[CheckpointManager]" = weakref.WeakSet()

# process-wide count of managers with a write in flight — the
# ``ckpt.write_inflight`` gauge. A count, not a 0/1 flag: one manager
# draining must not zero the gauge while another manager's write runs.
_inflight_count = 0


def _inflight_delta(d: int) -> None:
    global _inflight_count
    _inflight_count = max(_inflight_count + d, 0)
    gauge_set("ckpt.write_inflight", _inflight_count)


def _newest_mtime(path: str) -> float:
    """Most recent mtime in ``path``'s tree (the path itself for files) —
    the liveness signal for a possibly-in-flight checkpoint tmp dir."""
    newest = os.path.getmtime(path)
    for dirpath, _dirs, files in os.walk(path):
        for name in files:
            try:
                newest = max(newest,
                             os.path.getmtime(os.path.join(dirpath, name)))
            except OSError:
                continue   # file finalized/vanished mid-walk
    return newest


@atexit.register
def _drain_live_managers():
    for mgr in list(_LIVE_MANAGERS):
        try:
            mgr.close()
        except Exception:  # noqa: BLE001 - atexit must try every manager;
            pass           # a torn-down orbax thread pool raises arbitrarily


class CheckpointManager:
    # retry policy for the orbax I/O calls (utils/retry.py); instance-
    # overridable so tests pin a fake sleep / tighter budget
    retry_kw = {"attempts": 4, "base_delay_s": 0.05, "max_delay_s": 1.0}

    def __init__(self, directory: str, keep_n: Optional[int] = None,
                 async_save: bool = False, tmp_grace_s: float = 600.0):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.async_save = bool(async_save)
        # stale-tmp sweep threshold: an interrupted write's *-tmp-* dir is
        # reclaimable once it is plausibly ownerless; anything younger may
        # be a sibling process's in-flight write and survives the sweep
        self.tmp_grace_s = float(tmp_grace_s)
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=keep_n, create=True,
            enable_async_checkpointing=self.async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=opts)
        self._closed = False
        self.in_flight_step: Optional[int] = None
        _LIVE_MANAGERS.add(self)

    def save(self, step: int, state: Any, metadata: Optional[dict] = None,
             *, wait: Optional[bool] = None):
        """``state`` is any pytree (TrainState works). ``metadata`` is the
        config/hparams dict that travels with the weights. Async managers
        return once the device buffers are snapshotted to host (donation-safe:
        orbax owns a copy); pass ``wait=True`` to force durability before
        returning (signal-latch saves, final saves)."""
        args = {"state": ocp.args.PyTreeSave(state)}
        if metadata is not None:
            args["metadata"] = ocp.args.JsonSave(metadata)

        def _do_save():
            io_hook("ckpt_save")     # chaos injection point (fail_io)
            return self._mgr.save(step, args=ocp.args.Composite(**args))

        # orbax itself drains any still-running previous save at the top of
        # save() — back-to-back boundaries (rotation pressure) self-serialize.
        # The retry absorbs transient I/O failures (attempts that reached
        # orbax and tore leave only an unfinalized *-tmp-* dir, which the
        # stale-tmp GC reclaims; a same-step re-save after finalization
        # raises a non-transient error and propagates immediately).
        with span("ckpt/snapshot", step=step, asynchronous=self.async_save):
            with_retry("ckpt_save", _do_save, retry_kw=self.retry_kw)
        if self.async_save:
            if self.in_flight_step is None:
                _inflight_delta(+1)   # orbax drained any previous write above
            self.in_flight_step = step
        if wait if wait is not None else not self.async_save:
            self.wait_until_finished()

    def wait_until_finished(self):
        """Drain any in-flight background write (no-op when idle/sync)."""
        self._mgr.wait_until_finished()
        if self.in_flight_step is not None:
            self.in_flight_step = None
            _inflight_delta(-1)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _restore_step(self, state_template: Any, step: int):
        """One step's retried restore (transient I/O absorbed; a corrupt
        checkpoint's deterministic error propagates to the caller).

        ``restore_args`` are constructed from the template explicitly —
        each leaf restores onto the TEMPLATE's sharding, not the sharding
        recorded in the checkpoint. That is what makes restore-with-
        RESHARDING work (graftmend elastic): a checkpoint written by a
        2-process pod names devices a surviving 1-process pod doesn't
        have, so restoring 'as saved' is impossible after a topology
        change; placing onto the live state's shardings is always
        well-defined."""

        def _do_restore():
            io_hook("ckpt_restore")   # chaos injection point (fail_io)
            return self._mgr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.PyTreeRestore(
                        state_template,
                        restore_args=ocp.checkpoint_utils.
                        construct_restore_args(state_template))))

        restored = with_retry("ckpt_restore", _do_restore,
                              retry_kw=self.retry_kw)
        return restored["state"], self.load_metadata(step)

    def restore(self, state_template: Any, step: Optional[int] = None,
                log=print):
        """Restore into the structure/shardings of ``state_template``.
        Returns (state, metadata|None). Drains in-flight saves first so a
        just-requested step is durable before it is read back; steps whose
        write never finalized (``*-tmp-*`` dirs) are invisible to orbax and
        are never restored — and stale ones are garbage-collected here
        (:meth:`gc_stale_tmp`).

        With ``step=None`` (resume-from-latest) a step whose restore FAILS
        — truncated or corrupted files from a crash mid-finalize or disk
        rot — falls back to the next older durable step instead of killing
        the resume (``ckpt.restore_fallback_total`` + a
        ``ckpt_restore_fallback`` flight event per skipped step). An
        explicit ``step`` is a pinned request for exactly that state and
        still raises."""
        self.wait_until_finished()
        self.gc_stale_tmp(log=log)
        if step is not None:
            return self._restore_step(state_template, step)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        last_exc: Optional[BaseException] = None
        bad_steps: list = []
        for s in steps:
            try:
                out = self._restore_step(state_template, s)
                # quarantine is DEFERRED until some step restores: a
                # successful restore proves the template/reader are fine,
                # so the skipped newer steps really are bad on disk. If
                # EVERY step fails (a template↔checkpoint tree mismatch, a
                # broken reader) nothing is renamed — a systemic failure
                # must not destroy the whole checkpoint history.
                for b in bad_steps:
                    self._quarantine_step(b)
                return out
            except RetryBudgetExceeded as exc:
                if isinstance(exc.last, FileNotFoundError):
                    # the step VANISHED between listing and reading — a
                    # peer's quarantine rename (every pod member races the
                    # same fallback) or rotation. Skip it; there is
                    # nothing on disk to quarantine, and crashing here
                    # would kill the peer mid-restore too.
                    last_exc = exc
                    log(f"[ckpt] step {s} vanished during restore (peer "
                        "quarantine/rotation); falling back")
                    continue
                # transient I/O exhaustion is an INFRASTRUCTURE failure,
                # not evidence this step is corrupt — falling back (and
                # quarantining!) would discard a healthy checkpoint and,
                # in a pod, desync this worker's step list from peers
                # whose restore succeeded
                raise
            except Exception as exc:  # noqa: BLE001 - a corrupt step raises
                # version-dependent orbax/numpy types; any failure here
                # means THIS step is unusable, and the run is better served
                # by the previous durable step than by the traceback
                last_exc = exc
                bad_steps.append(int(s))
                counter_add("ckpt.restore_fallback_total", 1.0)
                record_event("ckpt_restore_fallback", step=int(s),
                             error=repr(exc))
                log(f"[ckpt] restore of step {s} failed ({exc!r}); "
                    "falling back to the previous durable step")
        raise RuntimeError(
            f"every checkpoint in {self.directory} failed to restore "
            f"(steps tried: {steps})") from last_exc

    def _quarantine_step(self, step: int) -> None:
        """Rename an unrestorable step dir to ``<step>.corrupt`` — bytes
        kept for forensics, step NUMBER freed so resumed training can
        re-save it when it re-crosses the boundary. Best-effort: in a
        multi-process pod every worker races the same rename and one wins
        — but the reload must run on EVERY worker regardless of who won
        (a worker whose manager still lists the quarantined step would
        later run different save/rotation collectives than its peers —
        observed as a gloo payload-size mismatch abort)."""
        bad = os.path.join(self.directory, str(step))
        try:
            os.replace(bad, bad + ".corrupt")
        except OSError:
            pass
        try:
            self._mgr.reload()
        except AttributeError:
            pass

    def gc_stale_tmp(self, grace_s: Optional[float] = None,
                     log=print) -> list:
        """Sweep interrupted ``*.orbax-checkpoint-tmp-*`` directories (and
        files) under the checkpoint root and one level down. An async save
        killed mid-write leaves its tmp dir forever — orbax ignores it on
        restore but never reclaims it, so crash-looping runs leak disk.
        Entries younger than the grace window (default
        ``self.tmp_grace_s``) are skipped: they may be a live sibling
        process's write in flight. Returns the reclaimed paths."""
        grace = self.tmp_grace_s if grace_s is None else float(grace_s)
        now = time.time()
        reclaimed = []
        parents = [self.directory]
        parents += [os.path.join(self.directory, d)
                    for d in sorted(os.listdir(self.directory))
                    if os.path.isdir(os.path.join(self.directory, d))]
        for parent in parents:
            try:
                names = os.listdir(parent)
            except OSError:
                continue
            for name in names:
                if ".orbax-checkpoint-tmp" not in name:
                    continue
                p = os.path.join(parent, name)
                try:
                    # liveness = the NEWEST mtime anywhere in the tree: a
                    # long-running save streams leaf data into nested
                    # files without touching the top-level dir's mtime, so
                    # judging the dir alone would sweep an in-flight write
                    # out from under the saver at exactly the large-
                    # checkpoint scale the grace window exists to protect
                    if now - _newest_mtime(p) < grace:
                        continue
                except OSError:
                    continue   # vanished under us (racing sweep/finalize)
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
                    if os.path.exists(p):
                        continue   # rmtree failed: still leaking, don't
                                   # count it reclaimed
                else:
                    try:
                        os.remove(p)
                    except OSError:
                        continue
                reclaimed.append(p)
        if reclaimed:
            counter_add("ckpt.tmp_reclaimed_total", float(len(reclaimed)))
            log(f"[ckpt] reclaimed {len(reclaimed)} stale checkpoint tmp "
                f"entr{'y' if len(reclaimed) == 1 else 'ies'}: "
                + ", ".join(os.path.basename(r) for r in reclaimed))
        return reclaimed

    def load_metadata(self, step: Optional[int] = None) -> Optional[dict]:
        self.wait_until_finished()
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        meta_path = os.path.join(self.directory, str(step), "metadata")
        if not os.path.isdir(meta_path):
            return None
        def _do_restore_meta():
            io_hook("ckpt_restore")
            return self._mgr.restore(
                step, args=ocp.args.Composite(metadata=ocp.args.JsonRestore()))

        try:
            restored = with_retry("ckpt_restore_meta", _do_restore_meta,
                                  retry_kw=self.retry_kw)
            return restored["metadata"]
        except Exception:  # noqa: BLE001 - metadata is best-effort sidecar:
            # orbax raises version-dependent types for a missing/corrupt item
            # and the weights restore (the part that must not fail) succeeded
            return None

    def preflight(self, state: Any, metadata: Optional[dict] = None):
        """Save-before-training so a broken checkpoint config fails immediately
        (reference legacy/train_dalle.py:591-594) — synchronous even on async
        managers: a preflight that fails in a background thread three steps
        later defeats its purpose. Also the second stale-tmp sweep point:
        a fresh run inherits whatever a crashed predecessor left behind."""
        self.gc_stale_tmp()
        self.save(0, state, metadata, wait=True)

    def close(self):
        """Drain in-flight writes, then release orbax resources. Idempotent
        (also runs from the module atexit hook)."""
        if self._closed:
            return
        self._closed = True
        _LIVE_MANAGERS.discard(self)
        self.wait_until_finished()
        self._mgr.close()
