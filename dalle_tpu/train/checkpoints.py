"""Checkpoint save/restore with embedded model identity.

Contract parity with the reference (SURVEY.md §5.4): checkpoints carry
``hparams``/``vae_params``/``vae_class_name`` *inside* the file so generation can
reconstruct the exact model (legacy/train_dalle.py:535-582, generate.py:82-106);
rotation keeps the newest ``keep_n`` (:547-550); a pre-flight save fails fast on
misconfiguration (:591-594).

Implementation is Orbax (sharded, multi-host-safe — the TPU equivalent of the
DeepSpeed partitioned checkpoint dir) with the metadata dict stored alongside.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, keep_n: Optional[int] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=keep_n, create=True, enable_async_checkpointing=False)
        self._mgr = ocp.CheckpointManager(self.directory, options=opts)

    def save(self, step: int, state: Any, metadata: Optional[dict] = None):
        """``state`` is any pytree (TrainState works). ``metadata`` is the
        config/hparams dict that travels with the weights."""
        args = {"state": ocp.args.PyTreeSave(state)}
        if metadata is not None:
            args["metadata"] = ocp.args.JsonSave(metadata)
        self._mgr.save(step, args=ocp.args.Composite(**args))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_template: Any, step: Optional[int] = None):
        """Restore into the structure/shardings of ``state_template``.
        Returns (state, metadata|None)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(
                state=ocp.args.PyTreeRestore(state_template)))
        meta = self.load_metadata(step)
        return restored["state"], meta

    def load_metadata(self, step: Optional[int] = None) -> Optional[dict]:
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        meta_path = os.path.join(self.directory, str(step), "metadata")
        if not os.path.isdir(meta_path):
            return None
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.Composite(metadata=ocp.args.JsonRestore()))
            return restored["metadata"]
        except Exception:  # noqa: BLE001 - metadata is best-effort sidecar:
            # orbax raises version-dependent types for a missing/corrupt item
            # and the weights restore (the part that must not fail) succeeded
            return None

    def preflight(self, state: Any, metadata: Optional[dict] = None):
        """Save-before-training so a broken checkpoint config fails immediately
        (reference legacy/train_dalle.py:591-594)."""
        self.save(0, state, metadata)

    def close(self):
        self._mgr.close()
